"""Executor: lowers a Program into ONE jitted XLA computation.

Parity: reference python/paddle/fluid/executor.py:256 + the C++ interpreter
(paddle/fluid/framework/executor.cc) that walks the ProgramDesc op-by-op,
launching a CUDA kernel per op.

TPU-first redesign: Executor.run symbolically evaluates the whole block
through the lowering registry inside a single jax.jit trace, keyed by
(program version, feed signature, fetch names). XLA then fuses the entire
step — forward, backward (one jax.grad over the traced forward, contributed
by the `autodiff` op that backward.append_backward plants), optimizer
updates — into one module: one device launch per step vs hundreds.
Persistable variables (parameters, optimizer state, BN stats) live in the
Scope as device arrays and are donated to each step, so updates are
in-place in HBM.

Pipelined hot loop (docs/perf.md): `run_bundle` scans K steps inside ONE
compiled module (one dispatch + one host round-trip per K steps),
`run(sync='async')` returns lazy FetchHandles so the host runs ahead of
the device, and PADDLE_TPU_COMPILE_CACHE reuses XLA executables across
processes (zero cold compiles on restart).
"""
import collections
import os
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from . import core
from . import lowering
from . import ops_impl  # noqa: F401  (registers all rules)
from .framework import default_main_program, Program
from .lowering import SeqValue, Ctx

# ZeRO floor (elements): tensors smaller than this keep their tp-only
# layout instead of ('tp','dp')-product sharding — mirrors
# parallel.fsdp_shard_params(min_size=1024). Tests lower it to exercise
# the product path on tiny models.
_ZERO_MIN_SIZE = 1024

__all__ = ['Executor', 'FetchHandle', 'global_scope', 'scope_guard',
           '_switch_scope', 'Scope', 'anomaly_guard']

# Persistent XLA compilation cache (docs/perf.md): point this env var at a
# directory and every Executor in the process wires
# jax_compilation_cache_dir at construction, so a RESTARTED process
# (Trainer resume after preemption, serving warmup) deserializes compiled
# modules instead of re-compiling them.
ENV_COMPILE_CACHE = 'PADDLE_TPU_COMPILE_CACHE'

# Compile-time stderr capture for XLA partitioner diagnostics
# (docs/parallel.md): the SPMD partitioner reports "Involuntary full
# rematerialization" — a sharding transition it can only do by
# replicating the whole tensor — through C++ logging on fd 2, invisible
# to Python warnings and absent from any API. PADDLE_TPU_REMAT_CAPTURE=0
# disables the fd redirection for embedders whose stderr is not dup-able.
ENV_REMAT_CAPTURE = 'PADDLE_TPU_REMAT_CAPTURE'
_REMAT_MARKER = b'Involuntary full rematerialization'


def _remat_capture_enabled():
    return os.environ.get(ENV_REMAT_CAPTURE, '1').lower() not in (
        '0', 'off', 'false', 'no')


import contextlib as _contextlib


import threading as _threading

# fd 2 is process-global state: two overlapping captures (two Executors
# compiling on different threads) would interleave dup2 save/restore and
# could leave stderr pointing at a deleted temp file forever. One capture
# at a time; a contended compile simply runs uncaptured (missing one
# remat detection beats corrupting fd 2).
_CAPTURE_FD2_LOCK = _threading.Lock()


@_contextlib.contextmanager
def _capture_fd2(sink):
    """Tee C++-level stderr (fd 2) into `sink` (a list of bytes) for the
    duration, re-emitting everything to the real stderr afterwards —
    capture must never swallow a diagnostic, only OBSERVE it. This is the
    only hook that sees XLA's C++ log lines (glog writes straight to the
    fd); Python-level warnings hooks never fire for them. Degrades to a
    no-op when the fd cannot be duplicated (exotic embedders) or when
    another thread is already capturing."""
    import io
    import sys as _sys
    import tempfile
    if not _CAPTURE_FD2_LOCK.acquire(blocking=False):
        yield
        return
    try:
        try:
            _sys.stderr.flush()
        except Exception:
            pass
        old = tmp = None
        try:
            old = os.dup(2)
            tmp = tempfile.TemporaryFile()
            os.dup2(tmp.fileno(), 2)
        except (OSError, ValueError, io.UnsupportedOperation):
            # partial setup must not leak per compile: close whatever
            # succeeded before degrading to a no-op
            if old is not None:
                try:
                    os.close(old)
                except OSError:
                    pass
            if tmp is not None:
                try:
                    tmp.close()
                except Exception:
                    pass
            yield
            return
        try:
            yield
        finally:
            try:
                _sys.stderr.flush()
            except Exception:
                pass
            os.dup2(old, 2)
            os.close(old)
            try:
                tmp.seek(0)
                data = tmp.read()
                tmp.close()
                if data:
                    sink.append(data)
                    os.write(2, data)
            except Exception:
                pass
    finally:
        _CAPTURE_FD2_LOCK.release()


def anomaly_guard(program=None, enable=True, max_consecutive_skips=None):
    """Enable the COMPILED-path anomaly guard (`check_nan_inf` for the
    one-module world): the jitted step computes a cheap health vector
    inside the XLA module — finiteness of the loss and of every gradient,
    plus the global grad-norm — and, when the step is unhealthy, SKIPS it:
    every persistable output (params, optimizer state, BN stats) is
    `where(healthy, new, old)`-selected back to its pre-step value, the
    same policy AMP loss-scaling uses for overflowed steps. No eager
    fallback, no extra launch: the guard is a few fused reductions on
    values the backward pass already produced.

    The reference's FLAGS_check_nan_inf aborted the process from the C++
    interpreter loop; that loop no longer exists on the compiled path, and
    a long-running job is better served by skip-and-continue. The eager
    per-op attribution mode is still available via
    fluid.debugger.check_nan_inf().

    After each guarded run, `exe.last_step_health` holds the numpy health
    vector and `exe.skipped_steps` counts skips. With
    max_consecutive_skips=N, the N-th consecutive unhealthy step raises
    FloatingPointError on the host (divergence, not a transient)."""
    if program is None:
        program = default_main_program()
    program._anomaly_guard = bool(enable)
    program._anomaly_guard_max_skips = max_consecutive_skips
    program._bump_version()
    return program


class _VarHolder(object):
    """Mimics the pybind Variable handle (find_var().get_tensor())."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return _TensorHandle(self._scope, self._name)

    def set(self, value, place=None):
        self._scope.vars[self._name] = jnp.asarray(value)


class _TensorHandle(object):
    """The pybind Tensor surface on a scope var: reads like an ndarray
    (__array__), writes back with set(value, place) — the reference idiom
    `scope.find_var(n).get_tensor().set(arr, place)` loads pretrained
    parameters in place (book test_label_semantic_roles.py:180)."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def _raw(self):
        v = self._scope.vars[self._name]
        return v.data if isinstance(v, SeqValue) else v

    def __array__(self, dtype=None, copy=None):
        if copy is False:
            # NumPy 2 __array__ contract: materializing a device array on
            # the host always copies, so a no-copy request is unsatisfiable
            raise ValueError(
                'converting a device tensor to numpy requires a '
                'device-to-host copy; copy=False cannot be satisfied')
        a = np.asarray(self._raw())
        if dtype is not None and a.dtype != np.dtype(dtype):
            a = a.astype(dtype)
        elif copy:
            a = a.copy()
        return a

    def set(self, value, place=None):
        self._scope.vars[self._name] = jnp.asarray(value)

    def shape(self):
        # metadata only — no device-to-host transfer
        return list(self._raw().shape)

    def __repr__(self):
        return '_TensorHandle(%r, shape=%r)' % (self._name, self.shape())


class Scope(object):
    """name -> device array store, optionally chained to a parent scope
    (reference paddle/fluid/framework/scope.h: kid scopes fall back to
    the parent on lookup; writes stay local)."""

    def __init__(self, parent=None):
        self.vars = collections.OrderedDict()
        self.parent = parent

    def find_var(self, name):
        if name in self.vars:
            return _VarHolder(self, name)
        if self.parent is not None:
            return self.parent.find_var(name)
        return None

    def var(self, name):
        self.vars.setdefault(name, None)
        return _VarHolder(self, name)

    def new_scope(self):
        return Scope(parent=self)

    def _chain_get(self, name, default=None):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return default

    def _chain_set(self, name, value):
        """Update the scope that OWNS `name` (so persistable updates made
        while running under a kid scope land where the var lives); new
        names are created locally."""
        s = self
        while s is not None:
            if name in s.vars:
                s.vars[name] = value
                return
            s = s.parent
        self.vars[name] = value

    def __contains__(self, name):
        if name in self.vars:
            return True
        return self.parent is not None and name in self.parent


_global_scope = Scope()


def global_scope():
    return _global_scope


def _switch_scope(scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    return prev


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)


def _as_fetch_name(f):
    from .framework import Variable
    if isinstance(f, Variable):
        return f.name
    return str(f)


# The compiled step is a first-class artifact now (fluid/step_artifact.py):
# one object per (program, feed-sig, fetch) owning the optimized program,
# the memory/donation plan, the NamedSharding trees, the RNG-stream
# policy, the feed/fetch signature, and the state_dict seam — with run /
# run_bundle / StepHandle / the serving dispatch as thin drivers over it.
from .step_artifact import (StepArtifact, _feed_signature, _is_annotated,
                            _nan_inf_hook, stable_signature as _stable_sig)

# migration alias (docs/architecture.md#step-artifact): external code that
# poked the executor internals via `_CompiledStep` keeps importing it here.
_CompiledStep = StepArtifact



# Process-wide executor telemetry (docs/observability.md). Shared,
# UNLABELED instruments: per-executor labels would grow the registry
# without bound under executor churn (tests, notebooks); the
# per-instance view lives in plain ints behind exe.cache_stats.
_C_HITS = obs.counter('executor.cache.hits')
_C_MISSES = obs.counter('executor.cache.misses')
_C_EVICTIONS = obs.counter('executor.cache.evictions')
_C_PERSISTENT_HITS = obs.counter('executor.cache.persistent_hits')
# AOT warm-signature deserializations (docs/perf.md#aot): persistent hits
# whose executable was imported from an exported step-artifact blob
_C_AOT_HITS = obs.counter('executor.cache.aot_hits')
_C_FEED_BYTES = obs.counter('executor.feed.bytes')
_G_LAST_COMPILE = obs.gauge('executor.last_compile.seconds')
_C_SKIPPED = obs.counter('anomaly.skipped_steps')
_G_GRAD_NORM = obs.gauge('anomaly.grad_norm')
# async-fetch pipeline (docs/perf.md): how many run(sync='async') fetch
# handles are outstanding (dispatched, not yet host-synced), and the
# executor.host_stall.seconds histogram (recorded via obs.span in
# FetchHandle.block) measuring time the host actually BLOCKED on the
# device — the number that proves (or disproves) the overlap.
_G_INFLIGHT = obs.gauge('executor.inflight')
_C_BUNDLED_STEPS = obs.counter('executor.bundle.steps')
# involuntary-rematerialization detections during compile (the MULTICHIP
# blind spot: the warning only ever lived in dryrun stderr tails; now it
# is an executor.remat_detected event + this counter, so a sharding
# regression shows up in obs_report)
_C_REMAT = obs.counter('executor.remat_detected')
# sharded-embedding subsystem (docs/embedding.md): upper bound on table
# rows touched by sparse updates this process ran (the per-step bound is
# static — the id count of the step's lookups; dedup/merge can only
# shrink it). The per-key geometry lives in the embedding.lookup /
# embedding.update_rows run-log events; this counter carries the volume.
_C_EMBED_ROWS = obs.counter('embedding.rows_touched')

# RLock: FetchHandle.__del__ may run from a GC pass triggered INSIDE an
# _inflight_delta call on the same thread (allocation under the lock);
# a plain Lock would self-deadlock. The instrument locks in obs.metrics
# are reentrant for the same reason.
_inflight_lock = threading.RLock()
_inflight_n = 0


def _inflight_delta(d):
    global _inflight_n
    with _inflight_lock:
        _inflight_n += d
        _G_INFLIGHT.set(_inflight_n)


class FetchHandle(object):
    """Lazy fetch from `run(sync='async')`: wraps the step's device-side
    output so the device-to-host sync happens at FIRST READ
    (np.asarray / float() / .block()), not inside run(). The host can
    dispatch the next step(s) while the device still works on this one —
    the async dispatch window that hides host latency.

    Contract:
      * `np.asarray(handle)` (or `float(handle)` for one-element fetches)
        blocks until the value is on the host; the wait is recorded in the
        `executor.host_stall.seconds` histogram, and the result is cached.
      * `.ready` is a non-blocking completion probe.
      * deferred errors: a step that fails ON DEVICE (or a conversion that
        fails) raises at the first read — and again at every later read —
        not at run() time (docs/migration.md).
      * the `executor.inflight` gauge counts handles created minus handles
        synced (or garbage-collected unread)."""

    __slots__ = ('_value', '_materialize', '_result', '_synced')

    def __init__(self, value, materialize=None):
        self._value = value
        self._materialize = materialize if materialize is not None \
            else (lambda v=value: np.asarray(v))
        self._result = None
        self._synced = False
        _inflight_delta(1)

    @property
    def ready(self):
        """Non-blocking: has the device finished producing this value?"""
        if self._synced:
            return True
        try:
            return bool(self._value.is_ready())
        except AttributeError:
            return True

    def block(self):
        """Materialize on the host (cached). Records the blocking wait as
        executor.host_stall; re-raises a deferred device error on every
        read."""
        if not self._synced:
            was_ready = self.ready
            try:
                with obs.span('executor.host_stall', ready=was_ready):
                    self._result = (True, self._materialize())
            except BaseException as e:
                self._result = (False, e)
            finally:
                self._synced = True
                self._value = None
                self._materialize = None
                _inflight_delta(-1)
        ok, payload = self._result
        if ok:
            return payload
        raise payload

    def __array__(self, dtype=None, copy=None):
        a = np.asarray(self.block())
        if dtype is not None and a.dtype != np.dtype(dtype):
            a = a.astype(dtype)
        elif copy:
            a = a.copy()
        return a

    def __float__(self):
        a = np.asarray(self.block())
        if a.size != 1:
            raise TypeError(
                'float() on a fetch handle of shape %r — only one-element '
                'fetches convert to a scalar' % (a.shape,))
        return float(a.reshape(-1)[0])

    def __del__(self):
        # never-read handle: release its inflight slot so the gauge does
        # not drift (the device work itself completes regardless)
        if not getattr(self, '_synced', True):
            self._synced = True
            try:
                _inflight_delta(-1)
            except Exception:
                pass   # interpreter shutdown: registry may be gone

    def __repr__(self):
        state = 'synced' if self._synced else (
            'ready' if self.ready else 'pending')
        return 'FetchHandle(%s)' % state


class StepHandle(object):
    """Pinned low-overhead driver for ONE compiled (program, feed-sig,
    fetch) step — the continuous-batching decode engine's hot loop
    (paddle_tpu.serving.decode) calls the same jitted module thousands of
    times per second with per-slot donated state, and `run()`'s per-call
    work (feed placement, cache key derivation, persist re-collection
    from the scope, fetch conversion, step spans) would dominate the
    step itself. `Executor.acquire_step` resolves all of that ONCE:

      * the donated (written) persistables live as device arrays INSIDE
        the handle between calls and are donated to every step — the
        memory plan's in-place state update, with zero per-call scope
        walks. The scope is kept in sync after each step, so
        `save_inference_model`/tools reading the scope always see the
        live arrays, never a donated (invalidated) buffer;
      * read-only persistables (weights) and the feed signature are
        fixed at acquire time; `step()` takes pre-placed feed arrays (or
        nothing) and returns the raw device-side fetches — the caller
        decides when to pay the host sync;
      * the first call still classifies compile-vs-persistent-hit via
        the executor's timed-first-call probe, so warmup telemetry
        (executor.compile spans, cache_stats) is identical to run()'s.
        Steady-state calls record NO per-step run-log events (a decode
        loop would write thousands of span records per second); the
        `executor.handle.steps` counter carries the volume instead.

    Programs that CREATE persistables (startup-style) are rejected at
    acquire: the donated pytree structure must be stable across calls.
    RNG-consuming ops see a fixed key unless `seed` is passed per call.
    """

    __slots__ = ('_exe', '_compiled', '_scope', '_program', '_donated',
                 '_readonly', '_key', '_first', 'steps', 'key_id')

    _C_STEPS = None   # registry counter, created lazily on first handle

    def __init__(self, exe, compiled, scope, program, persist, key_id):
        self._exe = exe
        self._compiled = compiled
        self._scope = scope
        self._program = program
        donated, readonly = compiled.plan.split(persist)
        self._donated = donated
        self._readonly = readonly
        self._key = jax.random.key(0)
        # a compiled step already first-called via run() (warmup) needs
        # no compile-classification probe here
        self._first = not getattr(compiled, '_obs_compiled', False)
        self.steps = 0
        self.key_id = key_id
        if StepHandle._C_STEPS is None:
            StepHandle._C_STEPS = obs.counter('executor.handle.steps')

    @property
    def state(self):
        """Merged name -> device array view of the step's persistable
        state (donated + read-only). Mutate via set_state."""
        view = dict(self._readonly)
        view.update(self._donated)
        return view

    def state_dict(self):
        """Placement-true {name: jax.Array} of this handle's persistable
        state — the artifact's state_dict seam (step_artifact.StepArtifact
        .state_dict), read through the scope the handle keeps in sync;
        what save_sharded consumes for a checkpoint taken mid-decode."""
        return self._compiled.state_dict(self._scope)

    def set_state(self, name, value):
        """Replace one persistable between steps (the decode engine's
        slot join: row-scatter a fresh request's state into the pool).
        Routes to the donated or read-only dict and keeps the scope in
        sync."""
        if name in self._donated:
            self._donated[name] = value
        elif name in self._readonly:
            self._readonly[name] = value
        else:
            raise KeyError('no persistable %r in this step (have %r)'
                           % (name, sorted(self._donated)
                              + sorted(self._readonly)))
        self._scope._chain_set(name, value)

    def step(self, feed=None, seed=None):
        """One execution; returns the raw fetch list (device arrays, in
        acquire-time fetch_list order). `feed` must match the
        acquire-time signature exactly (pre-placed arrays; None for a
        feedless step program)."""
        # the handle OWNS the donated persistables between calls; if
        # another path (run()/run_bundle/a second handle) drove the same
        # (program, scope) meanwhile, it re-collected and donated the
        # scope buffers this handle still points at — the next dispatch
        # would die with an opaque deleted-buffer error (on real chips)
        # or silently diverge from the scope (CPU, where donation is a
        # no-op). Scope identity is the platform-independent tell.
        for n, v in self._donated.items():
            if self._scope._chain_get(n) is not v:
                raise RuntimeError(
                    'StepHandle state invalidated: persistable %r was '
                    'rewritten in the scope by another execution path '
                    '(run()/run_bundle/another handle) since the last '
                    'step — a pinned handle must be the only driver of '
                    'its (program, scope); re-acquire_step() to resume'
                    % n)
        key = self._key if seed is None else jax.random.key(
            np.uint32(int(seed) % (1 << 32)))
        args = (self._donated, self._readonly, feed or {}, key)
        if self._first:
            (fetches, new_persist, health), _ = \
                self._exe._timed_first_call(
                    self._compiled._jitted, args, self.key_id, handle=True,
                    aot_sig=self._exe._aot_sig_of(self._compiled),
                    aot_entry='step')
            self._compiled._obs_compiled = True
            self._first = False
        else:
            fetches, new_persist, health = self._compiled._jitted(*args)
        for n, v in new_persist.items():
            self._donated[n] = v
            self._scope._chain_set(n, v)
        if health is not None:
            self._exe._observe_health(self._program, health)
        self.steps += 1
        StepHandle._C_STEPS.inc()
        return fetches


class Executor(object):
    """Parity: reference python/paddle/fluid/executor.py:256."""

    def __init__(self, place=None):
        if place is None:
            place = core.TPUPlace(0) if core.is_compiled_with_tpu() else core.CPUPlace()
        self.place = place
        self._cache = {}
        self._run_counter = 0
        # anomaly-guard observability (see anomaly_guard()): health of the
        # most recent guarded step, total skipped steps, and the running
        # consecutive-skip count backing max_consecutive_skips
        self.last_step_health = None
        self.skipped_steps = 0
        self._consecutive_skips = 0
        # per-instance compile-cache stats (process-wide aggregates go to
        # the registry counters above)
        self._cache_hits = 0
        self._cache_misses = 0
        self._cache_evictions = 0
        self._persistent_hits = 0
        self._last_compile_s = None
        self._last_cache_lookup = None   # {'outcome', 'key', 'entries'}
        # AOT warm signatures (docs/perf.md#aot): load_warm_signatures
        # arms the set of stable signature hashes whose executables were
        # imported from an exported artifact; first calls matching one
        # classify as aot_hit (vs plain persistent_hit / compile)
        self._aot_sigs = None
        self._aot_entries = None   # sig -> {'step': bool, 'bundles': set}
        self._aot_manifest = None
        self._aot_hits = 0
        self._aot_stale = 0
        # first calls that really XLA-compiled (vs deserialized): the
        # number the zero-online-compile contracts assert on
        self._online_compiles = 0
        # involuntary-rematerialization detections across this
        # executor's compiles (see _scan_remat); tests assert 0 on the
        # pipeline compositions that used to warn (MULTICHIP_r05 tail)
        self.remat_detected = 0
        # Persistent XLA compilation cache: PADDLE_TPU_COMPILE_CACHE=<dir>
        # wires jax's on-disk executable cache at construction, so a
        # restarted process (Trainer resume, serving warmup) deserializes
        # already-built modules — zero cold compiles on the second run.
        # The min-compile-time/min-entry-size floors are zeroed so EVERY
        # executable persists; the hit/miss probe below relies on a miss
        # always writing a new cache entry.
        self._compile_cache_dir = None
        # cache entries THIS executor's first calls wrote (names):
        # export_warm_signatures ships exactly these when it can, instead
        # of whatever else accumulated in a shared long-lived cache dir
        self._warm_entries = set()
        cc = os.environ.get(ENV_COMPILE_CACHE)
        if cc:
            try:
                self._wire_compile_cache(cc)
            except Exception as e:
                import warnings
                warnings.warn(
                    '%s=%r: persistent compilation cache unavailable in '
                    'this jax (%s: %s) — compiles stay per-process'
                    % (ENV_COMPILE_CACHE, cc, type(e).__name__, e),
                    RuntimeWarning)

    def _wire_compile_cache(self, cc, reset=False):
        """The ONE wiring point for the persistent XLA compilation cache
        (construction from PADDLE_TPU_COMPILE_CACHE, and
        load_warm_signatures for a cold replica). The min-compile-time /
        min-entry-size floors are zeroed so EVERY executable persists
        (the hit/miss probe relies on a miss always writing an entry),
        and jax's path-embedding XLA-autotune-cache option is disabled —
        by default the cache dir's ABSOLUTE PATH lands inside the hashed
        compile options, so two processes (or machines) with different
        cache paths would never share an entry, which would break the
        AOT warm-signature export (docs/perf.md#aot; GPU-only feature,
        CPU/TPU lose nothing). reset=True additionally resets jax's
        lazily-initialized cache object — required when wiring AFTER any
        jit already ran in the process (cold-replica import), or the new
        dir is never consulted. Raises on an incompatible jax."""
        jax.config.update('jax_compilation_cache_dir', cc)
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          0.0)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
        jax.config.update('jax_persistent_cache_enable_xla_caches', '')
        if reset:
            try:
                from jax._src import compilation_cache as _jcc
                _jcc.reset_cache()
            except Exception:
                pass   # private API drift: degrade to pre-reset behavior
        self._compile_cache_dir = cc

    def _device(self):
        return self.place.jax_device()

    def _to_device(self, val, var=None):
        if isinstance(val, jax.Array):
            from jax.sharding import NamedSharding
            if (isinstance(val.sharding, NamedSharding)
                    or len(val.sharding.device_set) > 1):
                # mesh-placed by the caller — don't collapse the sharding
                return val
            return jax.device_put(val, self._device())
        if isinstance(val, SeqValue):
            return SeqValue(jax.device_put(jnp.asarray(val.data), self._device()),
                            jax.device_put(jnp.asarray(val.lengths), self._device()),
                            val.outer_lengths)
        from .lod_tensor import LoDTensor
        if isinstance(val, LoDTensor):
            sv = val.to_seq_value()
            return self._to_device(sv)
        arr = np.asarray(val)
        return jax.device_put(arr, self._device())

    def _host_stage(self, val):
        """Host-side feed normalization WITHOUT device placement (the
        annotated path's counterpart to _to_device): LoDTensor ->
        SeqValue, everything else to numpy, leaving already-placed
        jax.Arrays alone. The mesh placement happens once, in
        _annot_shard_feed."""
        if isinstance(val, (jax.Array, SeqValue)):
            return val
        from .lod_tensor import LoDTensor
        if isinstance(val, LoDTensor):
            return val.to_seq_value()
        return np.asarray(val)

    def _annot_placement(self, program, scope):
        """The GSPMD annotation path (docs/parallel.md): a Program that
        declared its mesh via `set_mesh()` (with per-tensor specs on
        `ParamAttr(sharding=...)`/`Variable.sharding`) is lowered WITHOUT
        any strategy wrapper — this places every scope-initialized
        persistable on the mesh per its annotation (replicated when
        un-annotated), caches the built Mesh on the program, and returns
        it. The compiled step then runs with explicit in/out shardings
        and the memory plan's donation vector (_prepare)."""
        import collections as _c
        from .. import parallel
        axes = _c.OrderedDict(program._mesh_axes)
        mesh = parallel.make_mesh(axes)
        program._dist_mesh = mesh
        program._annot_axes = program._mesh_axes
        from jax.sharding import NamedSharding, PartitionSpec as P
        for v in program.list_vars():
            if not v.persistable:
                continue
            val = scope._chain_get(v.name)
            if val is None or isinstance(val, SeqValue):
                continue
            spec = P(*v.sharding) if v.sharding else P()
            try:
                placed = jax.device_put(val, NamedSharding(mesh, spec))
            except ValueError as e:
                import warnings
                warnings.warn(
                    'sharding annotation %r on %r does not fit the mesh '
                    '%r (%s); replicating instead — program_lint --mesh '
                    'catches this statically' % (
                        v.sharding, v.name, dict(axes), e))
                placed = jax.device_put(val, NamedSharding(mesh, P()))
            scope._chain_set(v.name, placed)
        return mesh

    def _ensure_dist_placement(self, program, scope):
        """Consume the program's parallelism declaration and return its
        Mesh (or None). Two sources, one consumer: (a) the first-class
        GSPMD annotation path — `Program.set_mesh()` + per-tensor
        sharding annotations (docs/parallel.md); (b) the legacy
        DistributeTranspiler `_dist_config` — build the dp mesh (capped
        at the locally visible devices; multi-host grows it via
        parallel.init_distributed), place parameters (replicated by
        default; dp-sharded ZeRO-3/FSDP when shard_parameters is set),
        and ZeRO-shard optimizer accumulators over dp (the reference's
        slice_var_up pserver memory scaling)."""
        mesh = getattr(program, '_dist_mesh', None)
        if mesh is not None and _is_annotated(program) \
                and getattr(program, '_annot_axes', None) \
                != program._mesh_axes:
            mesh = None   # set_mesh changed the spec: rebuild
        if mesh is not None:
            # Already built from annotations/_dist_config, or placed
            # directly by ParallelExecutor. False sentinel -> single
            # device, no-op.
            if mesh:
                self._replace_strays(program, scope, mesh)
            return mesh or None
        dist = getattr(program, '_dist_config', None)
        if dist is None:
            if _is_annotated(program):
                return self._annot_placement(program, scope)
            return None
        if not dist.get('sync_mode', True) and not getattr(
                program, '_async_warned', False):
            # reference distribute_transpiler.py:185-206 async pserver
            # updates; inside one GSPMD module replicas are bit-identical
            # and the gradient all-reduce is part of the compiled step, so
            # the Program path stays synchronous. The supported async
            # analogue is local SGD (parallel/local_sgd.py).
            import warnings
            warnings.warn(
                "DistributeTranspiler sync_mode=False: the TPU Program path "
                "runs SYNCHRONOUS data-parallel (GSPMD all-reduce each "
                "step). For async-style training use "
                "paddle_tpu.parallel.LocalSGD (periodic parameter "
                "averaging, docs/distributed.md).", UserWarning,
                stacklevel=3)
            program._async_warned = True
        from .. import parallel
        n_dev = len(jax.devices())
        pp = int(dist.get('pp_size') or 1)
        pp_axis = dist.get('pp_axis', 'pp')
        sp = int(dist.get('sp_size') or 1)
        tp = int(dist.get('tp_size') or 1)
        fixed = pp * sp * tp  # structural axis sizes are never capped
        if fixed > n_dev:
            raise RuntimeError(
                'mesh needs pp=%d x sp=%d x tp=%d = %d devices but only %d '
                'are visible' % (pp, sp, tp, fixed, n_dev))
        dp = min(int(dist.get('dp_size') or 1), max(1, n_dev // fixed))
        axes = {}
        if dp > 1:
            axes['dp'] = dp
        if tp > 1:
            axes['tp'] = tp
        if pp > 1:
            axes[pp_axis] = pp
        if sp > 1:
            axes['sp'] = sp
        if not axes:
            program._dist_mesh = False
            return None
        mesh = parallel.make_mesh(axes)
        program._dist_mesh = mesh
        acc_names = {v.name for v in program.list_vars()
                     if getattr(v, '_is_optimizer_accumulator', False)}
        persistable = {v.name for v in program.list_vars() if v.persistable}
        fsdp = dist.get('shard_parameters', False)
        # ZeRO-3 subsumes the lower levels: sharding the parameters while
        # replicating Adam state (2x the params) would silently forfeit
        # the memory scaling just asked for
        zero = dist.get('shard_optimizer_states', False) or fsdp
        # tp: Megatron layouts from the program graph
        # (TensorParallelTranspiler); accumulators inherit their master
        # parameter's layout (names embed the param name, shapes match)
        tp_specs = {}
        if tp > 1:
            import re as _re
            rules = parallel.auto_tp_rules(program)
            for name in persistable:
                for pat, spec in rules:
                    if _re.search(pat, name):
                        tp_specs[name] = spec
                        break
            for name in acc_names & persistable:
                if name in tp_specs:
                    continue
                av = scope.vars.get(name)
                for pname, spec in list(tp_specs.items()):
                    pv = scope.vars.get(pname)
                    if (pname in name and av is not None and pv is not None
                            and getattr(av, 'shape', None) == pv.shape):
                        tp_specs[name] = spec
                        break
        import re as _re2
        from jax.sharding import PartitionSpec as _P
        has_dp = 'dp' in mesh.shape

        def compose_dp(spec, v):
            """Also shard a ZeRO-requested var over dp: put 'dp' on the
            first dim the tp layout left whole (and that divides). When no
            free dim divides dp (typically 1-D biases / their moments,
            whose only dim 'tp' took), shard a tp-taken dim over the
            ('tp', 'dp') PRODUCT instead — each device then holds
            size/(tp*dp) elements, the full ZeRO scaling. The product
            path (only) floors at _ZERO_MIN_SIZE elements, mirroring
            fsdp_shard_params' min_size rationale: gather latency on a
            tiny tensor outweighs the bytes saved. The free-dim 'dp'
            path above keeps its historical no-floor behavior."""
            entries = list(tuple(spec)) + [None] * (v.ndim - len(tuple(spec)))
            for i, e in enumerate(entries):
                if e is None and v.shape[i] % mesh.shape['dp'] == 0:
                    entries[i] = 'dp'
                    return _P(*entries)
            if v.size < _ZERO_MIN_SIZE:
                return _P(*entries)   # keep the tp-only layout, no warning
            prod = mesh.shape['tp'] * mesh.shape['dp']
            for i, e in enumerate(entries):
                if e == 'tp' and v.shape[i] % prod == 0:
                    entries[i] = ('tp', 'dp')
                    return _P(*entries)
            return None

        for name in persistable:
            v = scope.vars.get(name)
            if v is None or isinstance(v, SeqValue):
                continue
            if name in tp_specs:
                spec = tp_specs[name]
                wants_zero = has_dp and ((zero and name in acc_names)
                                         or (fsdp and name not in acc_names))
                if wants_zero:
                    both = compose_dp(spec, v)
                    if both is not None:
                        spec = both
                    else:
                        import warnings
                        warnings.warn(
                            '%r keeps a tp-only layout %r (no remaining '
                            'dim divides dp=%d); its dp ZeRO sharding is '
                            'forfeited' % (name, spec, mesh.shape['dp']))
                # single placement path shared with the functional API
                # (device_put + warn-and-replicate on misfit)
                scope.vars.update(parallel.shard_params_by_rules(
                    {name: v}, mesh,
                    [('^' + _re2.escape(name) + '$', spec)]))
            elif has_dp and zero and name in acc_names:
                scope.vars.update(parallel.shard_optimizer_states(
                    {name: v}, mesh))
            elif has_dp and fsdp and name not in acc_names:
                # ZeRO-3: the parameters themselves shard over dp (the
                # reference's slice_var_up split param blocks across
                # pservers; this is its GSPMD equivalent)
                scope.vars.update(parallel.fsdp_shard_params(
                    {name: v}, mesh))
            else:
                scope.vars[name] = parallel.replicate(mesh, v)
        return mesh

    def _replace_strays(self, program, scope, mesh):
        """Re-assert mesh placement of persistables that were overwritten
        with single-device arrays since the first placement pass (io.load /
        load_inference_model / user writes into the scope) — mixing them
        with mesh-replicated feeds would fail jit's device check."""
        if len(mesh.devices.flat) <= 1:
            return
        from .. import parallel
        from jax.sharding import NamedSharding, PartitionSpec as P
        for v in program.list_vars():
            if not v.persistable:
                continue
            val = scope.vars.get(v.name)
            if (isinstance(val, jax.Array)
                    and len(val.sharding.device_set) == 1):
                if getattr(v, 'sharding', None):
                    # annotated var: re-assert ITS declared layout, not a
                    # blanket replicate (io.load overwrote a sharded
                    # param; replicating it would silently forfeit the
                    # annotation until the next cold placement)
                    try:
                        scope.vars[v.name] = jax.device_put(
                            val, NamedSharding(mesh, P(*v.sharding)))
                        continue
                    except ValueError:
                        pass   # misfit: fall through to replicate
                scope.vars[v.name] = parallel.replicate(mesh, val)

    def _annot_shard_feed(self, name, dv, mesh, program):
        """Feed placement for the annotation path: an explicitly
        annotated feed var takes its own spec; otherwise the batch dim
        shards over the program's data axis (replicated when none is
        declared or the value is a scalar). On a multi-process mesh the
        caller feeds its PER-HOST slice and the global array is
        assembled via parallel.global_batch
        (jax.make_array_from_process_local_data) — each host transfers
        only its own rows (docs/parallel.md)."""
        from .. import parallel
        from jax.sharding import NamedSharding, PartitionSpec as P
        if isinstance(dv, SeqValue):
            return SeqValue(
                self._annot_shard_feed(name, dv.data, mesh, program),
                self._annot_shard_feed(name, dv.lengths, mesh, program),
                dv.outer_lengths)
        var = program.global_block().vars.get(name)
        spec = getattr(var, 'sharding', None) if var is not None else None
        data_axis = getattr(program, '_mesh_data_axis', None)
        if spec is not None:
            # trim to the VALUE's rank: a SeqValue feed recurses here for
            # its rank-1 lengths vector with the data var's multi-dim
            # spec — only the leading (batch) entries can apply to it
            sh = NamedSharding(mesh, P(*spec[:dv.ndim]))
        elif (data_axis is not None and data_axis in mesh.shape
                and dv.ndim >= 1):
            n = mesh.shape[data_axis]
            # multi-process: dv is THIS host's slice, so the divisibility
            # contract is on the assembled global batch (local rows x
            # process_count), not on the local rows alone — checking the
            # local slice against the global axis size would spuriously
            # reject e.g. 12 local rows on a 2-host dp=8 mesh (global 24,
            # 3 rows/device: valid)
            global_rows = dv.shape[0] * jax.process_count()
            if global_rows % n:
                raise ValueError(
                    "feed %r global batch size %d (%d per-host rows x %d "
                    "processes) is not divisible by the %r mesh axis size "
                    "%d; drop the remainder (e.g. "
                    "paddle.batch(..., drop_last=True))"
                    % (name, global_rows, dv.shape[0], jax.process_count(),
                       data_axis, n))
            sh = NamedSharding(mesh, P(data_axis))
        else:
            return parallel.replicate(mesh, dv)
        return parallel.global_batch(sh, dv)

    def _dist_shard_feed(self, name, dv, mesh):
        from .. import parallel
        if isinstance(dv, SeqValue):
            return SeqValue(self._dist_shard_feed(name, dv.data, mesh),
                            self._dist_shard_feed(name, dv.lengths, mesh),
                            dv.outer_lengths)
        if 'dp' not in mesh.shape:
            # pp-only mesh: feeds replicate; microbatching happens inside
            # the pipelined step
            return parallel.replicate(mesh, dv)
        dp = mesh.shape['dp']
        if dv.ndim == 0:
            return parallel.replicate(mesh, dv)
        if dv.shape[0] % dp:
            raise ValueError(
                "distributed feed %r batch size %d is not divisible by the "
                "dp mesh size %d; drop the remainder (e.g. "
                "paddle.batch(..., drop_last=True))" % (name, dv.shape[0], dp))
        return jax.device_put(dv, parallel.data_sharding(mesh, 'dp', dv.ndim))

    def _place_feed(self, program, feed, dist_mesh):
        """Device-place one step's feed dict (dtype coercion, LoD wrapping,
        mesh sharding). Shared by _prepare and run_bundle's per-step
        stacker."""
        feed_vals = {}
        block = program.global_block()
        annot = dist_mesh is not None and _is_annotated(program)
        for name, val in feed.items():
            var = block.vars.get(name)
            # annotated path: stay on the host — _annot_shard_feed /
            # parallel.global_batch place the value DIRECTLY into its
            # mesh sharding; committing the full global batch to one
            # device first would require single-chip HBM to hold it
            # (defeating pod-scale batches) and pay a second transfer
            dv = self._host_stage(val) if annot \
                else self._to_device(val, var)
            if var is not None and var.lod_level > 0 and not isinstance(dv, SeqValue):
                # dense feed for a lod var: treat every row as full-length
                lens = (jnp if isinstance(dv, jax.Array) else np).full(
                    (dv.shape[0],), dv.shape[1], 'int32')
                dv = SeqValue(dv, lens)
            if var is not None and not isinstance(dv, SeqValue):
                want = np.dtype(var.dtype) if var.dtype != 'bfloat16' else jnp.bfloat16
                if dv.dtype != want:
                    dv = dv.astype(want)
            if dist_mesh is not None:
                if _is_annotated(program):
                    dv = self._annot_shard_feed(name, dv, dist_mesh,
                                                program)
                else:
                    dv = self._dist_shard_feed(name, dv, dist_mesh)
            feed_vals[name] = dv
        return feed_vals

    def _prepare(self, program, feed, fetch_list, scope,
                 use_program_cache=True, verify_bundle=False):
        """Shared front half of run()/lowered_hlo(): device-place the feed,
        resolve the (program, feed-sig, fetch) cache key, and build or fetch
        the _CompiledStep. Returns (compiled, feed_vals, persist)."""
        dist_mesh = self._ensure_dist_placement(program, scope)
        feed_vals = self._place_feed(program, feed, dist_mesh)
        block = program.global_block()

        fetch_names = [_as_fetch_name(f) for f in fetch_list]
        feed_sig = tuple(sorted(_feed_signature(n, v) for n, v in feed_vals.items()))
        persist_in = tuple(sorted(
            v.name for v in program.list_vars()
            if v.persistable and scope._chain_get(v.name) is not None
            and v.name not in feed_vals))
        from . import amp as amp_mod
        from .passes import quant_pass as quant_mod
        amp = amp_mod.is_amp(program)
        quant = quant_mod.is_quant(program)
        guard = bool(getattr(program, '_anomaly_guard', False))
        from jax.sharding import NamedSharding
        persist_shardings = {}
        for n in persist_in:
            v = scope._chain_get(n)
            if isinstance(v, jax.Array) and isinstance(v.sharding,
                                                       NamedSharding):
                persist_shardings[n] = v.sharding
        shard_sig = tuple(sorted((n, str(s.spec), s.mesh)
                                 for n, s in persist_shardings.items()))
        # GSPMD annotation path: jit sharding trees from the ACTUAL
        # placements (persist values were just mesh-placed by
        # _annot_placement; feed values by _annot_shard_feed), plus the
        # raw annotations for persistables the step creates. The
        # _CompiledStep derives its in/out shardings + donation vector
        # from these through the memory plan.
        jit_shardings = None
        if _is_annotated(program) and dist_mesh is not None:
            def _sh_of(v):
                if isinstance(v, jax.Array) and isinstance(
                        v.sharding, NamedSharding):
                    return v.sharding
                return None
            jit_shardings = {
                'persist': {n: _sh_of(scope._chain_get(n))
                            for n in persist_in},
                'feed': {n: _sh_of(v) for n, v in feed_vals.items()},
                'specs': {v.name: v.sharding for v in program.list_vars()
                          if v.persistable and getattr(v, 'sharding',
                                                       None)},
            }
        from . import passes as passes_mod
        from ..ops import kernels as kernels_mod
        opt = passes_mod.opt_mode()
        # the enabled pallas-kernel set is a TRACE-time routing decision
        # (lowering.use_kernel): it must be part of the cache key or a
        # knob flip would be served the other variant's cached step.
        # `quant` mirrors `amp`: marking a program after it already ran
        # must recompile, not serve the cached fp32 module.
        key = (program._uid, program._version, feed_sig, tuple(fetch_names),
               persist_in, amp, quant,
               bool(getattr(program, '_use_remat', False)),
               shard_sig, dist_mesh, guard, opt, kernels_mod.signature())
        # short stable-within-process id naming this compiled module in
        # telemetry (step spans, compiled_op_table's header)
        key_id = '%08x' % (hash(key) & 0xFFFFFFFF)
        compiled = self._cache.get(key) if use_program_cache else None
        if compiled is None:
            self._cache_misses += 1
            _C_MISSES.inc()
            # place is None under ParallelExecutor (mesh placement via
            # shardings); the mesh devices set the platform then
            plat = (self._device().platform if self.place is not None
                    else jax.devices()[0].platform)
            # Ahead-of-lowering optimization (docs/passes.md):
            # PADDLE_TPU_OPT={off,default,aggressive}, applied ONCE per
            # compiled-step cache key exactly like verify — the steady
            # state re-optimizes nothing. The ORIGINAL program is never
            # mutated; the _CompiledStep lowers the optimized clone. An
            # optimizer failure must never take down a training run:
            # fall back to the unoptimized lowering, loudly.
            # a quant-marked program REQUIRES the pass pipeline: unlike
            # amp there is no ctx-flag fallback in the lowering, so
            # honoring the mark can't be conditional on PADDLE_TPU_OPT
            run_program, run_block = program, block
            if opt != 'off' or quant:
                try:
                    run_program, _opt_report = passes_mod.optimize(
                        program, feeds=set(feed_vals),
                        fetches=fetch_names,
                        level=opt if opt != 'off' else 'default',
                        where='executor')
                    run_block = run_program.global_block()
                except Exception as e:
                    import warnings
                    warnings.warn(
                        '%s=%s: program optimization failed (%s: %s) — '
                        'lowering the unoptimized program'
                        % (passes_mod.ENV_OPT, opt, type(e).__name__, e),
                        RuntimeWarning)
                    obs.event('passes.error', key=key_id,
                              error='%s: %s' % (type(e).__name__, e))
                    run_program, run_block = program, block
            # the amp ctx flag dies for IR-rewritten programs: their
            # casts are explicit ops now (passes.amp_pass), even when
            # the global amp_guard armed the flag
            step_amp = amp and not getattr(run_program, '_amp_ir', False)
            # the Program -> jittable-step build (op walk, sparse plan,
            # pipeline region checks); the XLA compile itself happens on
            # the first call and is timed as executor.compile in run().
            # When the OPTIMIZED clone fails to build (a pass bug the
            # optimizer's own self-check missed), fall back to the
            # unoptimized program rather than killing the run.
            with obs.span('executor.lowering', key=key_id):
                try:
                    compiled = _CompiledStep(
                        run_program, run_block, list(feed_vals),
                        fetch_names, persist_in, amp=step_amp,
                        platform=plat,
                        persist_shardings=persist_shardings,
                        mesh=dist_mesh, guard=guard,
                        jit_shardings=jit_shardings)
                    if run_program is not program:
                        # PROBE the optimized step by tracing it now
                        # (.lower() = trace to StableHLO, no XLA compile,
                        # no execution, no donation): a pass bug that
                        # slipped the optimizer's def-use self-check —
                        # e.g. a rule resolving env by attr name — must
                        # surface HERE, where the fallback below catches
                        # it, not on the first run() call where nothing
                        # does. Costs one extra trace per optimized
                        # cache key, a small slice of the XLA compile
                        # the key pays anyway.
                        probe_persist = {
                            n: scope._chain_get(n)
                            for n in compiled.persist_in}
                        compiled._jitted.lower(
                            *compiled.plan.split(probe_persist),
                            feed_vals, jax.random.key(0))
                except Exception as e:
                    if run_program is program:
                        raise
                    import warnings
                    warnings.warn(
                        '%s=%s: lowering the optimized program failed '
                        '(%s: %s) — lowering the unoptimized program'
                        % (passes_mod.ENV_OPT, opt, type(e).__name__, e),
                        RuntimeWarning)
                    obs.event('passes.error', key=key_id, stage='lowering',
                              error='%s: %s' % (type(e).__name__, e))
                    compiled = _CompiledStep(
                        program, block, list(feed_vals),
                        fetch_names, persist_in, amp=amp,
                        platform=plat,
                        persist_shardings=persist_shardings,
                        mesh=dist_mesh, guard=guard,
                        jit_shardings=jit_shardings)
            # sparse-embedding accounting (docs/embedding.md): the
            # rows-touched-per-step bound is static given the feed
            # signature, so resolve it once per compiled key — run()'s
            # hot loop only bumps a counter
            embed_rows = self._embed_rows_per_step(
                compiled, feed_vals, scope)
            compiled._embed_rows_step = sum(embed_rows.values())
            # report ONLY the tables whose sparse path actually arms —
            # a planned table with unresolvable ids falls back dense in
            # _grad_setup and must not be claimed sparse here
            active = sorted(w for w, r in embed_rows.items() if r)
            if active:
                obs.event(
                    'embedding.update_rows', key=key_id, tables=active,
                    rows_per_step=compiled._embed_rows_step,
                    sharded=dist_mesh is not None)
            # artifact identity (fluid/step_artifact.py): the placed-feed
            # signature + short key id + SOURCE program (compiled.program
            # may be the optimized clone) — what stable_signature() and
            # the AOT manifest are derived from
            compiled._feed_sig = feed_sig
            compiled._key_id = key_id
            compiled._source_program = program
            obs.event('executor.artifact', key=key_id,
                      feeds=len(feed_vals), fetches=len(fetch_names),
                      persistables=len(persist_in),
                      donates=len(compiled.donate_names),
                      mesh=dist_mesh is not None)
            if use_program_cache:
                self._cache[key] = compiled
            outcome = 'miss'
        else:
            self._cache_hits += 1
            _C_HITS.inc()
            outcome = 'hit'
        self._last_cache_lookup = {'outcome': outcome, 'key': key_id,
                                   'entries': len(self._cache)}
        # Ahead-of-lowering program verification (docs/analysis.md):
        # PADDLE_TPU_VERIFY={off,warn,error}, ONE analysis per cache key —
        # the steady-state loop never re-analyzes, so verify overhead
        # amortizes to zero (the analysis.verify span is the proof). The
        # env model is exact for this step: the real feed names, the real
        # scope-initialized persistables, and the _CompiledStep's actual
        # donation decision to cross-check.
        from . import analysis
        analysis.maybe_verify(
            program, key=('verify', verify_bundle) + key, where='executor',
            feeds=set(feed_vals), fetches=fetch_names,
            initialized=set(persist_in) | set(feed_vals),
            donates=compiled.mutates_persist, bundle=verify_bundle,
            dead_ops=False)
        # feed-transfer accounting: nbytes is metadata only (no device
        # sync); SeqValues carry their dense payload + length vectors
        fb = 0
        for dv in feed_vals.values():
            if isinstance(dv, SeqValue):
                fb += int(getattr(dv.data, 'nbytes', 0))
                fb += int(getattr(dv.lengths, 'nbytes', 0))
            else:
                fb += int(getattr(dv, 'nbytes', 0))
        _C_FEED_BYTES.inc(fb)
        self._last_feed_bytes = fb

        persist = {n: scope._chain_get(n) for n in compiled.persist_in}
        # pin the donated state's placement ONCE (the artifact's donate-
        # exactly-once contract, fluid/step_artifact.py#pin_state): an
        # uncommitted first call (fresh startup outputs, io.load host
        # arrays) would re-specialize the executable on call two — the
        # old run_bundle "warm twice" wart. Mesh-placed programs and
        # place-less executors own their placement and skip this.
        pin_dev = (self._device() if self.place is not None
                   and dist_mesh is None else None)
        for n in compiled.pin_state(persist, pin_dev):
            scope._chain_set(n, persist[n])
        return compiled, feed_vals, persist

    @staticmethod
    def _embed_rows_per_step(compiled, feed_vals, scope=None):
        """Static per-step bound on table rows the sparse-embedding plan
        touches: the total id count of the plan's lookups resolved from
        the feed shapes — or the scope, matching _grad_setup's own
        resolution order, so persist-resident id tensors count too (on-
        device merge collapses duplicates, so the true unique count is
        <= this; the dense path would touch the full vocab instead — the
        number docs/perf.md's 49x claim is about). Mirrors _grad_setup's
        ALL-OR-NOTHING activation per table: a table with ANY
        unresolvable ids tensor falls back to the dense path there, so
        it must contribute zero here — otherwise the counter/event/bench
        would claim touched-rows updates while the [vocab, dim] dense
        grad actually materializes. Returns {table: rows} with 0 for
        fallen-back tables."""
        per_table = {}
        for w, plan in compiled.sparse_plan.items():
            table_rows = 0
            for _, ids_name, _ in plan['lookups']:
                v = feed_vals.get(ids_name)
                if v is None and scope is not None:
                    v = scope._chain_get(ids_name)
                if v is None:
                    table_rows = 0
                    break   # dense fallback for this whole table
                arr = v.data if isinstance(v, SeqValue) else v
                shp = tuple(getattr(arr, 'shape', ()))
                if shp and shp[-1] == 1:
                    shp = shp[:-1]
                table_rows += int(np.prod(shp)) if shp else 1
            per_table[w] = table_rows
        return per_table

    # -- persistent-compile-cache probe -----------------------------------

    def _cc_entry_names(self):
        """Entry names in the persistent compilation cache dir (a set),
        or None when the cache is not wired. A cold compile writes
        exactly one new entry (the min-compile-time/min-size floors are
        zeroed at construction), so no-new-entries across a first jitted
        call means the executable was DESERIALIZED — a persistent hit;
        the new names also feed `_warm_entries`, the tracked set
        export_warm_signatures ships. Cost: one flat scandir (jax's
        cache is a flat directory), and only on FIRST calls — never in
        the steady-state loop. `-atime` sidecars are excluded (reads may
        touch them). Caveats (stats, not correctness): a concurrent
        writer inside the probe window can make a hit look like a
        compile, and a compile jax declines to serialize (cache-write
        error, uncacheable executable) against an already non-empty dir
        would read as a hit."""
        d = self._compile_cache_dir
        if not d:
            return None
        if not os.path.isdir(d):
            return set()
        try:
            with os.scandir(d) as it:
                return {e.name for e in it
                        if not e.name.endswith('-atime')}
        except OSError:
            return set()

    def _aot_sig_of(self, compiled):
        """The artifact's stable signature when the AOT set is armed
        (None otherwise — the hash is only worth computing when a loaded
        manifest could match it)."""
        if not self._aot_sigs:
            return None
        return _stable_sig(compiled)

    def _aot_warmed(self, aot_sig, entry):
        """Did the loaded AOT manifest warm THIS entry point of the
        signature? `entry` is 'step' or ('bundle', K) — a blob exported
        from a replica that only ever bundled at K=8 never serialized
        the K=4 scan or the plain step, so a first call for those must
        classify as an ordinary compile, not a stale blob."""
        if aot_sig is None or aot_sig not in (self._aot_sigs or ()):
            return False
        rec = (self._aot_entries or {}).get(aot_sig)
        if rec is None or entry is None:
            return True   # pre-entry-index manifest: signature-level only
        if entry == 'step':
            return rec['step']
        return entry[1] in rec['bundles']

    def _timed_first_call(self, fn, args, key_id, aot_sig=None,
                          aot_entry=None, **fields):
        """Run the first jitted call of a cache entry (trace + XLA compile
        OR persistent-cache deserialize happen synchronously inside it),
        classify which one happened, and record it: a real cold compile
        emits the `executor.compile` span; a persistent hit emits an
        `executor.compile.persistent_hit` event instead — so a warm-cache
        restart's run log shows ZERO compile spans for already-cached
        keys (docs/perf.md). A persistent hit whose stable signature was
        imported by load_warm_signatures classifies further as an
        `executor.compile.aot_hit` — the cold-replica zero-compile
        contract (docs/perf.md#aot); an armed signature that COMPILES
        anyway is a stale AOT blob and is flagged loudly. The compile
        window also tees fd-2 stderr to catch the SPMD partitioner's
        involuntary-rematerialization diagnostic (_scan_remat) — only on
        first calls, never in the steady-state loop."""
        pre = self._cc_entry_names()
        captured = []
        t0 = time.perf_counter()
        if _remat_capture_enabled():
            with _capture_fd2(captured):
                out = fn(*args)
        else:
            out = fn(*args)
        dt = time.perf_counter() - t0
        self._scan_remat(captured, key_id)
        post = self._cc_entry_names()
        hit = bool(pre) and post == pre
        if pre is not None and post:
            # the entries this first call wrote are THIS executor's warm
            # set — what an AOT export ships
            self._warm_entries.update(post - pre)
        warmed = self._aot_warmed(aot_sig, aot_entry)
        if hit:
            self._persistent_hits += 1
            _C_PERSISTENT_HITS.inc()
            outcome = 'aot_hit' if warmed else 'persistent_hit'
            if warmed:
                self._aot_hits += 1
                _C_AOT_HITS.inc()
            if self._last_cache_lookup is not None:
                self._last_cache_lookup['outcome'] = outcome
            obs.event('executor.compile.%s' % outcome, key=key_id,
                      seconds=round(dt, 6), **fields)
        else:
            outcome = 'compile'
            self._online_compiles += 1
            obs.span_record('executor.compile', dt, key=key_id, **fields)
            self._last_compile_s = dt
            _G_LAST_COMPILE.set(dt)
            if warmed:
                # the manifest PROMISED this signature was serialized but
                # the first call compiled online anyway (cache entry
                # missing/invalidated, jax/backend drift): a stale blob —
                # the exact silent failure program_lint --aot types
                self._aot_stale += 1
                obs.event('executor.aot.stale', key=key_id, sig=aot_sig,
                          seconds=round(dt, 6))
                import warnings
                warnings.warn(
                    'AOT warm signature %s (key %s) COMPILED online '
                    'despite the loaded warm-signature manifest claiming '
                    'it — the AOT blob is stale (re-export it; '
                    'program_lint --aot checks this statically)'
                    % (aot_sig, key_id), RuntimeWarning)
        return out, outcome

    def _scan_remat(self, captured, key_id):
        """Turn captured compile-time stderr into the
        `executor.remat_detected` signal: XLA's SPMD partitioner logged
        "Involuntary full rematerialization" — it could only satisfy a
        sharding transition by replicating the tensor and re-partitioning
        it, a full all-gather the program's annotations did not ask for.
        Counted per compile (event + counter + exe.remat_detected), so a
        sharding regression is a number in obs_report, not a line lost in
        a dryrun's stderr tail."""
        n = sum(c.count(_REMAT_MARKER) for c in captured)
        if not n:
            return
        self.remat_detected += n
        _C_REMAT.inc(n)
        obs.event('executor.remat_detected', key=key_id, count=n)
        import warnings
        warnings.warn(
            'XLA SPMD partitioner reported %d involuntary full '
            'rematerialization(s) while compiling key %s: a sharding '
            'transition could only be satisfied by replicate-then-'
            'repartition (a full all-gather per step). Check the in/out '
            'sharding consistency of the step (docs/parallel.md); '
            'program_lint --mesh flags the static cases.' % (n, key_id),
            RuntimeWarning, stacklevel=3)

    def run(self,
            program=None,
            feed=None,
            fetch_list=None,
            feed_var_name='feed',
            fetch_var_name='fetch',
            scope=None,
            return_numpy=True,
            use_program_cache=True,
            sync='auto'):
        """sync (docs/perf.md):
          'auto'  — current default behavior: fetches are materialized on
                    the host before run() returns (blocking); reserved to
                    let the executor pick the mode per call site.
          'block' — explicit blocking fetch (same as 'auto' today).
          'async' — return lazy FetchHandle objects immediately after
                    dispatch; the device-to-host sync happens at first
                    read (np.asarray/float), recorded as
                    executor.host_stall. Device errors defer to first
                    read. return_numpy decides what .block() yields for
                    sequence fetches (ndarray vs LoDTensor). NOTE: an
                    armed anomaly_guard needs a host decision per step,
                    so it syncs on the health vector before returning —
                    the wait is recorded as a host_stall
                    (cause=anomaly_guard) and mostly serializes the
                    async window."""
        if sync not in ('auto', 'block', 'async'):
            raise ValueError(
                "sync must be 'auto', 'block' or 'async', got %r" % (sync,))
        if program is None:
            program = default_main_program()
        if feed is None:
            feed = {}
        if fetch_list is None:
            fetch_list = []
        if scope is None:
            scope = global_scope()

        # Telemetry (docs/observability.md): the step span covers the
        # whole run — prepare, device dispatch, fetch sync. When
        # observability is off this is two perf_counter calls and an
        # in-memory histogram record; no file IO, no device syncs.
        with obs.span('executor.step') as step_sp:
            compiled, feed_vals, persist = self._prepare(
                program, feed, fetch_list, scope,
                use_program_cache=use_program_cache)
            self._run_counter += 1
            look = self._last_cache_lookup or {}
            step_sp.fields.update(run=self._run_counter,
                                  cache=look.get('outcome'),
                                  key=look.get('key'),
                                  feed_bytes=self._last_feed_bytes)
            rng = jax.random.key(np.uint32(
                ((program.random_seed or 0) * 2654435761 + self._run_counter)
                % (1 << 32)))
            from . import debugger as _dbg
            from . import profiler as _prof
            check = _dbg.nan_inf_check_active()
            op_hook = _prof.op_event_hook()
            if check or op_hook is not None:
                fetches, new_persist, health = compiled.debug_step(
                    persist, feed_vals, rng, check_nan_inf=check,
                    on_op=op_hook)
            elif not getattr(compiled, '_obs_compiled', False):
                # first jitted call of this cache entry: jax traces and
                # XLA-compiles (or persistent-cache-deserializes)
                # synchronously inside it; _timed_first_call measures it
                # and records executor.compile ONLY for real cold
                # compiles (plus one step's dispatch either way)
                (fetches, new_persist, health), outcome = \
                    self._timed_first_call(
                        compiled, (persist, feed_vals, rng),
                        look.get('key'),
                        aot_sig=self._aot_sig_of(compiled),
                        aot_entry='step')
                compiled._obs_compiled = True
                step_sp.fields['compiled'] = (outcome == 'compile')
                if outcome != 'compile':
                    step_sp.fields['cache'] = outcome
            else:
                fetches, new_persist, health = compiled(
                    persist, feed_vals, rng)
            if compiled.sparse_plan:
                _C_EMBED_ROWS.inc(getattr(compiled, '_embed_rows_step', 0))
            for n, v in new_persist.items():
                scope._chain_set(n, v)
            if health is not None:
                # the guard's contract is a HOST decision per step, so
                # this syncs on the (tiny) health vector — which waits
                # for the step itself. Under sync='async' that wait is
                # the step's real host stall: record it, or the overlap
                # histogram would read ~0 and lie (the guard largely
                # serializes the async window; docs/perf.md).
                if sync == 'async':
                    with obs.span('executor.host_stall',
                                  cause='anomaly_guard'):
                        self._observe_health(program, health)
                else:
                    self._observe_health(program, health)

            fetch_f32 = bool(getattr(program, '_fetch_f32', False))

            # fetch conversion is where the device-to-host sync happens
            # (np.asarray blocks on the step's outputs) — unless
            # sync='async', which wraps each output in a lazy FetchHandle
            # and returns without waiting on the device
            with obs.span('executor.fetch', sync=sync):
                out = [self._convert_fetch(v, fetch_f32, return_numpy,
                                           sync == 'async')
                       for v in fetches]
        return out

    def acquire_step(self, program=None, feed=None, fetch_list=None,
                     scope=None):
        """Resolve (program, feed-sig, fetch) ONCE and return a pinned
        StepHandle whose repeated `.step()` calls skip the per-run
        prepare pass entirely — the hot-loop entry point for per-step
        state machines like the continuous-batching decode engine
        (docs/serving.md). `feed` is an EXAMPLE fixing the signature
        (may be empty/None for a feedless state-update program); the
        donated persistable state is held inside the handle between
        calls (in-place updates per the memory plan) with the scope kept
        in sync. The compiled module is the same one run() would build
        and lives in the same cache (warmup via run() or a prior handle
        carries over; `cache_stats` counts the single lookup)."""
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        compiled, feed_vals, persist = self._prepare(
            program, feed, fetch_list, scope)
        gap = compiled.plan.uninitialized(compiled.persist_in)
        if gap:
            raise ValueError(
                'acquire_step: program writes persistable(s) %r that have '
                'no scope value yet — a handle needs a stable donated '
                'state structure; run the startup program first' % gap)
        look = self._last_cache_lookup or {}
        return StepHandle(self, compiled, scope, program, persist,
                          look.get('key'))

    def step_artifact(self, program=None, feed=None, fetch_list=None,
                      scope=None):
        """The cached StepArtifact for (program, feed-sig, fetch) —
        resolved through the same _prepare pass run() uses (a cache HIT
        after the first step, so calling this in a hot loop costs a
        dict lookup). Public seam for consumers of artifact metadata
        that must not rebuild it: the streaming delta publisher reads
        `touched_rows`/`sparse_plan` here (docs/embedding.md
        "streaming ids")."""
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        compiled, _, _ = self._prepare(program, feed or {},
                                       fetch_list or [], scope)
        return compiled

    def _convert_fetch(self, v, fetch_f32, return_numpy, lazy):
        """One fetched value -> what run()/run_bundle() hand back: numpy /
        device array / LoDTensor, or a lazy FetchHandle over the same
        conversion when lazy."""
        def _cast_back(x):
            # Float16Transpiler contract: users keep fetching float32
            if fetch_f32 and hasattr(x, 'dtype') and str(x.dtype) == 'bfloat16':
                return x.astype(jnp.float32)
            return x

        if isinstance(v, SeqValue):
            from .lod_tensor import LoDTensor
            sv = SeqValue(_cast_back(v.data), v.lengths, v.outer_lengths)

            def mat(sv=sv):
                lt = LoDTensor.from_seq_value(sv)
                return np.asarray(lt.data) if return_numpy else lt

            if lazy:
                return FetchHandle(sv.data, mat)
            return mat()
        v = _cast_back(v)
        if lazy:
            if return_numpy:
                return FetchHandle(v)
            # return_numpy=False keeps the value ON DEVICE in blocking
            # mode; the async handle honors that — block() waits for
            # completion but hands back the device array, no host copy
            return FetchHandle(v, lambda v=v: jax.block_until_ready(v))
        return np.asarray(v) if return_numpy else v

    def run_bundle(self, program=None, feeds=None, fetch_list=None,
                   steps=None, scope=None, return_numpy=True,
                   use_program_cache=True, sync='auto'):
        """Run K training steps as ONE compiled XLA module: a lax.scan of
        the exact step body run() jits, amortizing the Python prepare
        pass, the device dispatch, and the host round-trip over K steps —
        the hot-loop pipelining lever for small/host-bound models
        (docs/perf.md).

        feeds: a list of K per-step feed dicts with identical signatures
        (shapes/dtypes); they are stacked on a new leading axis and
        scanned over. steps, when given, must equal len(feeds).

        Semantics vs K unbundled run() calls — identical by construction:
          * per-step RNG seeds advance exactly as run()'s counter does
            (a dropout mask at bundled step j equals unbundled run j);
          * the anomaly guard (when armed) evaluates health PER inner
            step, rolls back that step's persistables in-graph, and skips
            are observed/escalated per step on the host afterwards;
          * persistables land back in the scope once, at bundle end.
        One documented divergence: max_consecutive_skips escalation
        raises AFTER the bundle's module ran — inner steps past the
        escalation point already executed in-graph (each unhealthy one
        individually rolled back), so the scope holds bundle-end state,
        whereas K unbundled runs would have stopped at the raising step.
        Divergence is a stop-the-run condition either way; the state is
        consistent, just K-j steps further along.

        Returns one entry per fetch, STACKED per step: ndarray/device
        array with a leading K axis (sequence fetches: a list of K
        LoDTensors), or lazy FetchHandles over the same when
        sync='async'."""
        if sync not in ('auto', 'block', 'async'):
            raise ValueError(
                "sync must be 'auto', 'block' or 'async', got %r" % (sync,))
        if program is None:
            program = default_main_program()
        if fetch_list is None:
            fetch_list = []
        if scope is None:
            scope = global_scope()
        feeds = list(feeds or [])
        if not feeds:
            raise ValueError('run_bundle needs a non-empty list of '
                             'per-step feed dicts')
        K = len(feeds)
        if steps is not None and int(steps) != K:
            raise ValueError('steps=%d but %d feed dicts were given'
                             % (steps, K))
        with obs.span('executor.bundle', steps=K) as bsp:
            compiled, feed0, persist = self._prepare(
                program, feeds[0], fetch_list, scope,
                use_program_cache=use_program_cache, verify_bundle=True)
            look = self._last_cache_lookup or {}
            bsp.fields.update(cache=look.get('outcome'),
                              key=look.get('key'))
            extras = compiled.plan.uninitialized(compiled.persist_in)
            if extras:
                raise ValueError(
                    'run_bundle: persistable output(s) %r have no value '
                    'in the scope yet, so they cannot thread through the '
                    'scan carry; run the startup program (or one '
                    'unbundled step) first so every persistable is '
                    'initialized' % (sorted(extras),))
            mesh = compiled.mesh
            names0 = set(feed0)
            for j, f in enumerate(feeds[1:], start=1):
                if set(f) != names0:
                    raise ValueError(
                        'run_bundle feed %d has names %r, expected %r — '
                        'a bundle is ONE compiled module over a uniform '
                        'feed set' % (j, sorted(f), sorted(names0)))
            stacked = {}
            slow_names = []
            for name, v0 in feed0.items():
                # fast path (the hot Trainer/bench case): K host ndarrays,
                # no mesh, no sequence structure — ONE np.stack and ONE
                # device transfer per feed name instead of K device_puts
                # plus a device-side stack
                if (mesh is None and not isinstance(v0, SeqValue)
                        and all(isinstance(f[name], np.ndarray)
                                for f in feeds)):
                    vals = []
                    for j, f in enumerate(feeds):
                        a = f[name]
                        if a.shape != v0.shape:
                            raise ValueError(
                                'run_bundle feed %d input %r has shape '
                                '%r, expected %r (step 0) — a bundle is '
                                'ONE compiled module over uniform shapes'
                                % (j, name, a.shape, tuple(v0.shape)))
                        vals.append(a)
                    arr = np.stack(vals)
                    if arr.dtype != v0.dtype:
                        arr = arr.astype(v0.dtype)
                    stacked[name] = jax.device_put(
                        arr, self._device() if self.place is not None
                        else None)
                else:
                    slow_names.append(name)
            if slow_names:
                # general path: place each step's feed like run() would
                # and stack leaf-wise on device (SeqValue is a pytree, so
                # sequence feeds stack their data and length planes
                # together; mesh feeds keep their sharding pipeline)
                sig0 = tuple(sorted(_feed_signature(n, feed0[n])
                                    for n in slow_names))
                per_step = [{n: feed0[n] for n in slow_names}]
                for j, f in enumerate(feeds[1:], start=1):
                    fv = self._place_feed(
                        program, {n: f[n] for n in slow_names}, mesh)
                    sig = tuple(sorted(_feed_signature(n, v)
                                       for n, v in fv.items()))
                    if sig != sig0:
                        raise ValueError(
                            'run_bundle feed %d has signature %r, '
                            'expected every step to match step 0 (%r) — '
                            'a bundle is ONE compiled module over '
                            'uniform shapes' % (j, sig, sig0))
                    per_step.append(fv)
                stacked.update(jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *per_step))
            # feed-transfer accounting: _prepare counted ONLY step 0's
            # payload (its placed feed also pays one duplicate small
            # transfer — the price of sharing run()'s signature/cache
            # path); top up the counter to the full stacked volume so
            # executor.feed.bytes doesn't under-report bundles K-fold
            fb = sum(int(getattr(leaf, 'nbytes', 0))
                     for leaf in jax.tree_util.tree_leaves(stacked))
            _C_FEED_BYTES.inc(max(0, fb - self._last_feed_bytes))
            self._last_feed_bytes = fb
            # per-step RNG seeds: exactly the integers K successive run()
            # calls would derive from the shared counter
            base = (program.random_seed or 0) * 2654435761
            seeds = np.asarray(
                [(base + self._run_counter + j + 1) % (1 << 32)
                 for j in range(K)], np.uint32)
            run_base = self._run_counter
            self._run_counter += K
            _C_BUNDLED_STEPS.inc(K)
            bundle_fn = compiled.bundle(K)
            donated, readonly = compiled.plan.split(persist)
            obs_key = ('bundle', K)
            if obs_key not in getattr(compiled, '_obs_bundles', set()):
                (new_persist, (fetches, healths)), outcome = \
                    self._timed_first_call(
                        bundle_fn, (donated, readonly, stacked, seeds),
                        look.get('key'), bundle_steps=K,
                        aot_sig=self._aot_sig_of(compiled),
                        aot_entry=('bundle', K))
                if not hasattr(compiled, '_obs_bundles'):
                    compiled._obs_bundles = set()
                compiled._obs_bundles.add(obs_key)
                bsp.fields['compiled'] = (outcome == 'compile')
                if outcome != 'compile':
                    bsp.fields['cache'] = outcome
            else:
                new_persist, (fetches, healths) = bundle_fn(
                    donated, readonly, stacked, seeds)
            if compiled.sparse_plan:
                _C_EMBED_ROWS.inc(
                    K * getattr(compiled, '_embed_rows_step', 0))
            for n, v in new_persist.items():
                scope._chain_set(n, v)
            if healths is not None:
                # ONE host sync of the tiny [K] health matrix; skips are
                # then observed (and escalated) per inner step, exactly
                # as K unbundled runs would have. Under sync='async' the
                # wait on the bundle's outputs happens HERE — record it
                # as the host stall it is.
                if sync == 'async':
                    with obs.span('executor.host_stall',
                                  cause='anomaly_guard', steps=K):
                        h_np = {k: np.asarray(v)
                                for k, v in healths.items()}
                else:
                    h_np = {k: np.asarray(v) for k, v in healths.items()}
                for j in range(K):
                    self._observe_health(
                        program, {k: v[j] for k, v in h_np.items()},
                        run_id=run_base + j + 1)

            fetch_f32 = bool(getattr(program, '_fetch_f32', False))
            with obs.span('executor.fetch', sync=sync, steps=K):
                out = []
                for v in fetches:
                    if isinstance(v, SeqValue):
                        # stacked [K, batch, ...] sequence fetch -> K
                        # per-step values (LoDTensor conversion is
                        # per-step by construction)
                        def mat_steps(v=v):
                            return [self._convert_fetch(
                                SeqValue(v.data[j], v.lengths[j],
                                         tuple(o[j] for o in
                                               v.outer_lengths)
                                         if v.outer_lengths else None),
                                fetch_f32, return_numpy, False)
                                for j in range(K)]
                        if sync == 'async':
                            out.append(FetchHandle(v.data, mat_steps))
                        else:
                            out.append(mat_steps())
                    else:
                        out.append(self._convert_fetch(
                            v, fetch_f32, return_numpy, sync == 'async'))
        return out

    def _observe_health(self, program, health, run_id=None):
        """Host side of the anomaly guard: record the health vector, count
        skips, warn per skipped step, and escalate persistent divergence
        (max_consecutive_skips) to a FloatingPointError."""
        h = {k: np.asarray(v) for k, v in health.items()}
        self.last_step_health = h
        if run_id is None:
            run_id = self._run_counter
        # telemetry from the health vector ALREADY on the host — reusing
        # it costs no extra device sync (the guard's design invariant)
        _G_GRAD_NORM.set(float(h['grad_norm']))
        if bool(h['healthy']):
            self._consecutive_skips = 0
            return
        self.skipped_steps += 1
        self._consecutive_skips += 1
        _C_SKIPPED.inc()
        obs.event('anomaly.skip', run=run_id,
                  grad_norm=float(h['grad_norm']),
                  loss_finite=bool(h['loss_finite']),
                  grads_finite=bool(h['grads_finite']),
                  consecutive=self._consecutive_skips)
        import warnings
        warnings.warn(
            'anomaly guard: step %d skipped (loss_finite=%s '
            'grads_finite=%s grad_norm=%s) — parameters and optimizer '
            'state were rolled back' % (
                run_id, bool(h['loss_finite']),
                bool(h['grads_finite']), float(h['grad_norm'])),
            RuntimeWarning, stacklevel=3)
        max_skips = getattr(program, '_anomaly_guard_max_skips', None)
        if max_skips is not None and self._consecutive_skips >= max_skips:
            raise FloatingPointError(
                'anomaly guard: %d consecutive unhealthy steps (limit %d) '
                '— the run has diverged, not hit a transient; last health: '
                '%r' % (self._consecutive_skips, max_skips,
                        {k: v.tolist() for k, v in h.items()}))

    def lowered_hlo(self, program=None, feed=None, fetch_list=None,
                    scope=None, optimized=False):
        """HLO text of the EXACT fused step run() would execute for this
        (program, feed, fetch) combination — each instruction's metadata
        op_name carries the `<fluid_op_type>_<index>` named scope stamped
        by lowering.run_op, so profiler traces and this dump attribute the
        compiled module back to Fluid ops (the reference's per-op tracer
        attributes the real run; profiler.py:81-130). optimized=True
        returns post-XLA-pass HLO (what actually executes, fusions and
        all); False returns the stable pre-optimization module."""
        _, lowered = self._lower_current_step(program, feed, fetch_list,
                                              scope)
        if optimized:
            return lowered.compile().as_text()
        return lowered.as_text()

    def _lower_current_step(self, program, feed, fetch_list, scope):
        """Shared prep for the step diagnostics (lowered_hlo /
        compiled_memory_stats): resolve defaults, build-or-fetch the
        cached compiled step, and lower the EXACT jitted call run()
        would make. Returns (compiled, jax Lowered)."""
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        compiled, feed_vals, persist = self._prepare(
            program, feed or {}, fetch_list or [], scope)
        donated, readonly = compiled.plan.split(persist)
        return compiled, compiled._jitted.lower(
            donated, readonly, feed_vals, jax.random.key(0))

    def compiled_memory_stats(self, program=None, feed=None,
                              fetch_list=None, scope=None):
        """XLA's CompiledMemoryStats for the EXACT fused step run() would
        execute for this (program, feed, fetch) combination — argument/
        output/temp byte sizes of the compiled module. The temp figure is
        the per-step scratch footprint the docs/perf.md and
        docs/embedding.md sparse-vs-dense claims are measured with
        (`bench.py --phase embedding`). Costs one lowering + compile
        (absorbed by the persistent compile cache when wired); the
        compiled-step cache itself is shared with run()."""
        _, lowered = self._lower_current_step(program, feed, fetch_list,
                                              scope)
        return lowered.compile().memory_analysis()

    def embed_rows_per_step(self, program=None, feed=None,
                            fetch_list=None, scope=None):
        """Static rows-touched-per-step bound of this step's ACTIVE
        sparse-embedding plan (docs/embedding.md): the number the
        embedding.rows_touched counter advances by per run. 0 means the
        step updates its tables densely (no plan, or every planned
        table fell back). Resolves through the same compiled-step cache
        as run()."""
        if program is None:
            program = default_main_program()
        if scope is None:
            scope = global_scope()
        compiled, _, _ = self._prepare(
            program, feed or {}, fetch_list or [], scope)
        return getattr(compiled, '_embed_rows_step', 0)

    # -- elastic checkpoint seam (docs/robustness.md#elastic) --------------

    def state_dict(self, program=None, scope=None):
        """The scope's persistable train state, placement-true: {name:
        jax.Array} for every scope-initialized persistable of `program`,
        each carrying its LIVE sharding (mesh placement is (re)asserted
        first, so an annotated program's arrays are NamedSharding-placed
        per their annotations — a vocab-sharded table comes back as 8
        device shards, never a gathered dense host array). This is what
        utils.checkpoint.save_sharded consumes: each host then writes
        only the shards it can address. LoD (SeqValue) persistables are
        skipped with a warning — the dense npz path owns those."""
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        self._ensure_dist_placement(program, scope)
        out = {}
        for v in program.list_vars():
            if not v.persistable:
                continue
            val = scope._chain_get(v.name)
            if val is None:
                continue
            if isinstance(val, SeqValue):
                import warnings
                warnings.warn(
                    'state_dict skips LoD persistable %r (SeqValue '
                    'state has no sharded-checkpoint representation)'
                    % v.name, RuntimeWarning)
                continue
            out[v.name] = (val if isinstance(val, jax.Array)
                           else jnp.asarray(val))
        return out

    def load_state_dict(self, state, program=None, scope=None):
        """Restore a state_dict into the scope, re-placed per the
        program's CURRENT annotations — the reshard-on-restore seam: the
        arrays may arrive from utils.checkpoint.load_sharded on a
        different mesh shape than they were saved on (8 devices -> 4
        after an elastic restart); each is device_put into the
        annotation's NamedSharding over the program's own mesh, so the
        step's sharding fixed point holds from the first post-restore
        run. Entries that are not persistables of the program are
        skipped with a warning; program persistables absent from `state`
        keep their scope values. Returns the restored names."""
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        mesh = self._ensure_dist_placement(program, scope)
        annot = mesh is not None and _is_annotated(program)
        from jax.sharding import NamedSharding, PartitionSpec as P
        pvars = {v.name: v for v in program.list_vars() if v.persistable}
        restored, unknown = [], []
        for name, val in state.items():
            v = pvars.get(name)
            if v is None:
                unknown.append(name)
                continue
            if annot:
                spec = (P(*v.sharding) if getattr(v, 'sharding', None)
                        else P())
                try:
                    val = jax.device_put(val, NamedSharding(mesh, spec))
                except ValueError as e:
                    import warnings
                    warnings.warn(
                        'load_state_dict: annotation %r on %r does not '
                        'fit the mesh (%s); replicating instead'
                        % (getattr(v, 'sharding', None), name, e))
                    val = jax.device_put(val, NamedSharding(mesh, P()))
            elif mesh is not None:
                # legacy-dist mesh: keep an already-mesh-placed array's
                # layout (ZeRO/FSDP state restored by load_sharded);
                # single-device values replicate and _replace_strays /
                # the placement pass re-assert specifics on the next run
                if not (isinstance(val, jax.Array)
                        and len(val.sharding.device_set) > 1):
                    from .. import parallel
                    val = parallel.replicate(mesh, val)
            else:
                val = self._to_device(val)
            scope._chain_set(name, val)
            restored.append(name)
        if unknown:
            import warnings
            warnings.warn(
                'load_state_dict: %d checkpoint entr(ies) are not '
                'persistables of this program and were skipped: %s'
                % (len(unknown), sorted(unknown)[:8]), RuntimeWarning)
        obs.event('executor.load_state_dict', restored=len(restored),
                  skipped=len(unknown),
                  mesh=sorted(dict(mesh.shape).items()) if mesh else None)
        return restored

    @property
    def cache_stats(self):
        """THIS executor's compile-cache statistics
        (docs/observability.md): hits/misses/entries, evictions (close()
        drops), and the last XLA compile's wall seconds (None until
        something compiled). Process-wide aggregates of the same series
        live in the registry (executor.cache.*)."""
        return {'hits': self._cache_hits,
                'misses': self._cache_misses,
                'entries': len(self._cache),
                'evictions': self._cache_evictions,
                'persistent_hits': self._persistent_hits,
                'online_compiles': self._online_compiles,
                'aot_hits': self._aot_hits,
                'aot_stale': self._aot_stale,
                'aot_signatures': (len(self._aot_sigs)
                                   if self._aot_sigs is not None else None),
                'compile_cache_dir': self._compile_cache_dir,
                'last_compile_seconds': self._last_compile_s,
                'remat_detected': self.remat_detected}

    # -- AOT warm signatures (docs/perf.md#aot) -----------------------------

    def export_warm_signatures(self, dirname):
        """Serialize this executor's WARMED signature set as a portable
        AOT blob: a typed manifest of every compiled step artifact (feed
        names/shapes/dtypes, fetches, donation plan, program fingerprint,
        bundle lengths) plus the persistent compilation cache's
        serialized XLA executables. A cold replica / elastic restart
        calls `load_warm_signatures(dirname)` before its own warmup and
        reaches first step / first token with ZERO online compiles —
        the PR 4 per-machine persistent cache, extended across machines
        through the artifact. Requires PADDLE_TPU_COMPILE_CACHE to have
        been set when this executor was constructed. Returns the
        manifest path; `tools/program_lint.py --aot DIR` lints the
        exported signature set against a saved program artifact."""
        from . import step_artifact
        path, man = step_artifact.write_aot(dirname, self)
        obs.event('executor.aot.exported', dir=os.path.basename(dirname),
                  signatures=len(man['signatures']),
                  cache_entries=len(man.get('cache_entries', [])))
        return path

    def load_warm_signatures(self, dirname):
        """Import an exported AOT blob: seed the persistent compilation
        cache with the blob's serialized executables and arm the stable-
        signature set, so every matching first call classifies as an
        `aot_hit` (cache_stats / executor.compile.aot_hit) instead of a
        cold compile. When no PADDLE_TPU_COMPILE_CACHE is wired yet, a
        fresh cache dir is created next to nothing — the import NEVER
        writes into the artifact itself, so the blob stays pristine.
        Returns the number of imported signatures."""
        import shutil
        import tempfile
        from . import step_artifact
        man = step_artifact.read_aot(dirname)
        src = os.path.join(dirname, step_artifact.AOT_CACHE_DIR)
        if self._compile_cache_dir is None:
            # wire a private cache dir now (the constructor's wiring,
            # via the shared helper, plus the cache-object reset that
            # late wiring needs — in a cold replica something always
            # jitted already) — the artifact dir itself stays read-only
            cc = tempfile.mkdtemp(prefix='paddle_tpu_aot_cc_')
            # the private dir holds a copy of the blob's executables:
            # reclaim it at interpreter exit, or repeated cold-replica
            # imports on one host would grow /tmp without bound
            import atexit
            import shutil
            atexit.register(shutil.rmtree, cc, ignore_errors=True)
            try:
                self._wire_compile_cache(cc, reset=True)
            except Exception as e:
                import warnings
                warnings.warn(
                    'load_warm_signatures(%r): persistent compilation '
                    'cache unavailable in this jax (%s: %s) — the AOT '
                    'executables cannot deserialize; first calls will '
                    'compile online' % (dirname, type(e).__name__, e),
                    RuntimeWarning)
        imported = 0
        if os.path.isdir(src) and self._compile_cache_dir is not None:
            os.makedirs(self._compile_cache_dir, exist_ok=True)
            for name in os.listdir(src):
                dst = os.path.join(self._compile_cache_dir, name)
                if not os.path.exists(dst):
                    shutil.copy2(os.path.join(src, name), dst)
                    imported += 1
        self._aot_sigs = {s['sig'] for s in man['signatures']}
        # per-entry-point warm index (see _aot_warmed): which of each
        # signature's entry points the blob actually serialized
        self._aot_entries = {
            s['sig']: {'step': bool(s.get('warmed_step', True)),
                       'bundles': {int(k) for k in s.get('bundles', [])}}
            for s in man['signatures']}
        self._aot_manifest = man
        if man.get('jax') != jax.__version__:
            import warnings
            warnings.warn(
                'AOT blob %r was exported under jax %s but this process '
                'runs %s — serialized executables will not deserialize '
                'and every first call will compile online (and be '
                'flagged executor.aot.stale)'
                % (dirname, man.get('jax'), jax.__version__),
                RuntimeWarning)
        obs.event('executor.aot.loaded', dir=os.path.basename(dirname),
                  signatures=len(self._aot_sigs),
                  cache_entries_imported=imported)
        return len(self._aot_sigs)

    def close(self):
        """Release compiled executables and drop cached jit state
        (reference executor.py:close tears down the C++ scope/comm; here
        the compiled-step cache holds the device buffers XLA pinned)."""
        self._cache_evictions += len(self._cache)
        _C_EVICTIONS.inc(len(self._cache))
        for step in self._cache.values():
            for fn in [getattr(step, '_jitted', None)] + \
                    list(getattr(step, '_bundles', {}).values()):
                if hasattr(fn, 'clear_cache'):
                    fn.clear_cache()
            step._bundles = {}
        self._cache.clear()
        import gc
        gc.collect()
