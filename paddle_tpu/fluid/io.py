"""Model persistence. Parity: reference python/paddle/fluid/io.py.

The reference saves each var through C++ save/load ops into separate files
(or one combined file). Here persistence is host-side: params come out of
the Scope as numpy arrays into an .npz (portable) and programs serialize to
JSON (framework.Program._to_dict) — the TPU equivalent of ProgramDesc
protobuf + LoDTensor files. Orbax-backed sharded checkpointing for large
multi-host models lives in paddle_tpu.utils.checkpoint.
"""
import json
import os

import numpy as np

from .. import obs
from .framework import Program, Parameter, Variable, default_main_program
from .executor import global_scope

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
    'save_checkpoint', 'load_checkpoint', 'list_checkpoint_serials',
]

_PARAMS_FILE = '__params__.npz'
_PROGRAM_FILE = '__model__.json'


def is_parameter(var):
    return isinstance(var, Parameter)


def is_persistable(var):
    return var.persistable


def _save_var_file(dirname, filename, arrays):
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    np.savez(path, **arrays)
    if not path.endswith('.npz'):
        os.replace(path + '.npz', path)
    return path


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference io.py:save_vars."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    arrays = {}
    for var in vars:
        name = var.name if isinstance(var, Variable) else str(var)
        v = scope.vars.get(name)
        if v is None:
            raise RuntimeError("variable %s is not initialized in scope" % name)
        from .lowering import SeqValue
        if isinstance(v, SeqValue):
            arrays[name] = np.asarray(v.data)
        else:
            arrays[name] = np.asarray(v)
    if filename is None:
        filename = _PARAMS_FILE
    return _save_var_file(dirname, filename, arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, None, is_parameter,
                     filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, None, is_persistable,
                     filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """reference io.py:load_vars. `scope` defaults to the process-global
    scope (the compat path); callers that own a private Scope — Predictor,
    Inferencer, the serving engine — pass it explicitly so concurrent
    loads never race on the global scope_guard."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    data = np.load(path)
    if scope is None:
        scope = global_scope()
    for var in vars:
        name = var.name if isinstance(var, Variable) else str(var)
        if name not in data:
            raise RuntimeError("variable %s not found in %s" % (name, path))
        scope.vars[name] = jnp.asarray(data[name])


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    load_vars(executor, dirname, main_program, None, is_parameter, filename,
              scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    load_vars(executor, dirname, main_program, None, is_persistable, filename,
              scope=scope)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    return main_program.clone(for_test=True)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """reference io.py:save_inference_model: prunes to inference graph and
    saves program + params. Also exports StableHLO when possible
    (paddle_tpu.inference)."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    inference_program = main_program.clone(for_test=True).prune(target_vars)
    meta = {
        'program': inference_program._to_dict(),
        'feed_names': list(feeded_var_names),
        'fetch_names': [v.name if isinstance(v, Variable) else str(v)
                        for v in target_vars],
    }
    with open(os.path.join(dirname, model_filename or _PROGRAM_FILE), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, inference_program, params_filename)
    return None


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    """reference io.py:load_inference_model -> (program, feed_names,
    fetch_vars). `scope` as in load_vars: None keeps the global-scope
    compat behavior; Predictor passes its private scope."""
    with open(os.path.join(dirname, model_filename or _PROGRAM_FILE)) as f:
        meta = json.load(f)
    program = Program._from_dict(meta['program'])
    load_persistables(executor, dirname, program, params_filename,
                      scope=scope)
    fetch_vars = [program.global_block()._var_recursive(n)
                  for n in meta['fetch_names']]
    return [program, meta['feed_names'], fetch_vars]


def _file_crc32(path):
    # single CRC implementation for both checkpoint formats
    from ..utils.checkpoint import _crc32_file
    return _crc32_file(path)


def save_checkpoint(executor, checkpoint_dir, trainer_id=0, main_program=None,
                    step=0, max_num_checkpoints=3, trainer_args=None):
    """Failure-recovery checkpoint: persistables + step counter + optional
    trainer args like {'epoch_id', 'step_id'} (reference io.py checkpoint
    utilities / trainer.py:641 save_checkpoint)."""
    prog = main_program if main_program is not None \
        else default_main_program()
    from .executor import _is_annotated
    if _is_annotated(prog):
        # this path np.asarray()s every persistable DENSE on this host:
        # for a mesh-annotated program that gathers a vocab-sharded table
        # whole (the 92x footprint win undone; OOM on a real pod)
        import warnings
        warnings.warn(
            'save_checkpoint gathers every persistable dense on this '
            'host, but the program is mesh-annotated (set_mesh) — a '
            'sharded table materializes whole here. Use '
            'utils.checkpoint.save_sharded (the Trainer routes annotated '
            'programs there automatically; docs/robustness.md#elastic).',
            RuntimeWarning, stacklevel=2)
    serial_dir = os.path.join(checkpoint_dir, 'checkpoint_%d' % step)
    with obs.span('checkpoint.save', serial=step):
        params_path = save_persistables(executor, serial_dir, main_program)
    # meta written atomically and LAST: its presence marks a complete
    # snapshot (reference writes a _SUCCESS marker, trainer.py:1190). It
    # records the params file's size AND content CRC32, so load_checkpoint
    # can tell a torn/bit-rotted snapshot from an intact one and the
    # Trainer can fall back to the previous serial instead of silently
    # resuming from corrupted weights.
    tmp = os.path.join(serial_dir, 'meta.json.tmp')
    with open(tmp, 'w') as f:
        json.dump({'step': step, 'trainer_id': trainer_id,
                   'trainer_args': trainer_args or {},
                   'params_file': os.path.basename(params_path),
                   'params_bytes': os.path.getsize(params_path),
                   'params_crc32': _file_crc32(params_path)}, f)
    os.replace(tmp, os.path.join(serial_dir, 'meta.json'))
    # prune old checkpoints
    for s in list_checkpoint_serials(checkpoint_dir)[:-max_num_checkpoints]:
        import shutil
        shutil.rmtree(os.path.join(checkpoint_dir, 'checkpoint_%d' % s),
                      ignore_errors=True)
    return serial_dir


def list_checkpoint_serials(checkpoint_dir):
    """Sorted serial numbers of checkpoint_<n> subdirs (may be torn)."""
    import re
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for d in os.listdir(checkpoint_dir):
        m = re.fullmatch(r'checkpoint_(\d+)', d)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def load_checkpoint(executor, checkpoint_dir, serial=None, main_program=None):
    if serial is None:
        cands = list_checkpoint_serials(checkpoint_dir)
        if not cands:
            raise RuntimeError("no checkpoints in %s" % checkpoint_dir)
        serial = cands[-1]
    serial_dir = os.path.join(checkpoint_dir, 'checkpoint_%d' % serial)
    with open(os.path.join(serial_dir, 'meta.json')) as f:
        meta = json.load(f)
    # integrity gate BEFORE any value reaches the scope: a truncated or
    # bit-rotted params file raises here (the Trainer's resume loop
    # catches it and falls back to the previous serial, loudly). The
    # verify duration and outcome land in checkpoint.verify telemetry.
    if meta.get('params_crc32') is not None:
        with obs.span('checkpoint.verify', serial=serial):
            params_path = os.path.join(
                serial_dir, meta.get('params_file') or _PARAMS_FILE)
            if not os.path.exists(params_path):
                obs.counter('checkpoint.crc_verify', outcome='fail').inc()
                raise RuntimeError(
                    'checkpoint serial %d: params file %r is missing'
                    % (serial, params_path))
            want_bytes = meta.get('params_bytes')
            if want_bytes is not None \
                    and os.path.getsize(params_path) != want_bytes:
                obs.counter('checkpoint.crc_verify', outcome='fail').inc()
                raise RuntimeError(
                    'checkpoint serial %d is corrupt: params file %r '
                    'holds %d bytes, meta recorded %d (truncated write?)'
                    % (serial, params_path, os.path.getsize(params_path),
                       want_bytes))
            got = _file_crc32(params_path)
            if got != meta['params_crc32']:
                obs.counter('checkpoint.crc_verify', outcome='fail').inc()
                raise RuntimeError(
                    'checkpoint serial %d is corrupt: params CRC32 %08x '
                    'does not match the meta record %08x'
                    % (serial, got, meta['params_crc32']))
            obs.counter('checkpoint.crc_verify', outcome='ok').inc()
    load_persistables(executor, serial_dir, main_program)
    return meta
