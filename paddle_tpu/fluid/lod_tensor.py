"""LoDTensor: host-side ragged tensor with recursive sequence lengths.

Parity: reference python/paddle/fluid/lod_tensor.py +
paddle/fluid/framework/lod_tensor.h. The reference stores sequences
flattened [total_tokens, d] plus a level-of-detail offset table; on device
we use the TPU-friendly dense-padded SeqValue (see lowering.py) and this
class converts between the two at the host boundary.
"""
import numpy as np

__all__ = ['LoDTensor', 'LoDTensorArray', 'create_lod_tensor',
           'create_random_int_lodtensor']


def _lengths_to_offsets(lengths):
    out = [0]
    for l in lengths:
        out.append(out[-1] + l)
    return out


class LoDTensor(object):
    def __init__(self, data=None, recursive_seq_lens=None):
        self.data = None if data is None else np.asarray(data)
        self._lengths = recursive_seq_lens or []

    # -- reference API --
    def set(self, data, place=None):
        self.data = np.asarray(data)

    def set_recursive_sequence_lengths(self, lengths):
        self._lengths = lengths

    def recursive_sequence_lengths(self):
        return self._lengths

    def set_lod(self, lod):
        self._lengths = [list(np.diff(level)) for level in lod]

    def lod(self):
        return [_lengths_to_offsets(level) for level in self._lengths]

    def has_valid_recursive_sequence_lengths(self):
        """Full recursive check (reference lod_tensor.h CheckLoD): level k's
        entry count must equal the sum of level k-1's lengths (each outer
        sequence is a run of inner sequences), and the innermost level's
        lengths must sum to the number of data rows."""
        if not self._lengths:
            return True
        for outer, inner in zip(self._lengths, self._lengths[1:]):
            if len(inner) != sum(outer):
                return False
        total = sum(self._lengths[-1])
        return total == (self.data.shape[0] if self.data is not None else 0)

    def __array__(self, dtype=None):
        a = self.data
        return a.astype(dtype) if dtype is not None else a

    def shape(self):
        return list(self.data.shape)

    # -- device conversion: flattened+lod <-> dense padded SeqValue --
    def to_seq_value(self, pad_to=None):
        from .lowering import SeqValue
        import jax.numpy as jnp
        if not self._lengths:
            return jnp.asarray(self.data)
        lens = np.asarray(self._lengths[-1], dtype=np.int32)
        b = len(lens)
        maxlen = int(lens.max()) if b else 0
        if pad_to:
            maxlen = pad_to
        trail = self.data.shape[1:]
        padded = np.zeros((b, maxlen) + tuple(trail), dtype=self.data.dtype)
        off = 0
        for i, l in enumerate(lens):
            padded[i, :l] = self.data[off:off + l]
            off += l
        # Every level above the innermost rides along as a tuple of int32
        # vectors (outermost first) — arbitrary-depth LoD, matching the
        # reference's recursive LoD table (lod_tensor.h).
        outer = None
        if len(self._lengths) > 1:
            outer = tuple(jnp.asarray(np.asarray(lv, np.int32))
                          for lv in self._lengths[:-1])
        return SeqValue(jnp.asarray(padded), jnp.asarray(lens), outer)

    @staticmethod
    def from_seq_value(sv):
        data = np.asarray(sv.data)
        lens = np.asarray(sv.lengths)
        outer = [np.asarray(lv) for lv in (sv.outer_lengths or ())]
        if len(outer) == 1 and int(outer[0].sum()) < len(lens) \
                and len(lens) % len(outer[0]) == 0:
            # capacity-form 2-level value (the LoD beam decoder,
            # ops_impl/lod_beam.py): each source owns a fixed block of
            # len(lens)/n_src row slots with only the first outer[s] live —
            # compact to the reference's ragged LoD layout
            n_src = len(outer[0])
            k = len(lens) // n_src
            keep = np.concatenate(
                [np.arange(s * k, s * k + int(outer[0][s]))
                 for s in range(n_src)]).astype(int) \
                if int(outer[0].sum()) else np.zeros((0,), int)
            data = data[keep]
            lens = lens[keep]
        rows = []
        for i, l in enumerate(lens):
            rows.append(data[i, :int(l)])
        flat = np.concatenate(rows, axis=0) if rows else data.reshape((0,) + data.shape[2:])
        lengths = [list(int(l) for l in lens)]
        for lv in reversed(outer):
            lengths = [list(int(l) for l in lv)] + lengths
        return LoDTensor(flat, lengths)


class LoDTensorArray(list):
    """Host-side array of LoDTensor (reference
    paddle/fluid/framework/lod_tensor_array.h — a std::vector<LoDTensor>
    exposed through pybind as `core.LoDTensorArray`; python/paddle/fluid/
    __init__.py:48 re-exports it). The reference API is append/len/index,
    which `list` already provides; every mutation path coerces raw
    arrays so feed code can push numpy directly and indexing always
    yields LoDTensor. The DEVICE analogue is `lowering.ArrayValue`
    (fixed-capacity stacked buffers for array_write/array_read inside
    While loops) — this class is the feed/fetch-side container."""

    @staticmethod
    def _coerce(value):
        if not isinstance(value, LoDTensor):
            value = LoDTensor(np.asarray(value))
        return value

    def __init__(self, iterable=()):
        super(LoDTensorArray, self).__init__(
            self._coerce(v) for v in iterable)

    def append(self, value):
        super(LoDTensorArray, self).append(self._coerce(value))

    def extend(self, iterable):
        super(LoDTensorArray, self).extend(
            self._coerce(v) for v in iterable)

    def insert(self, index, value):
        super(LoDTensorArray, self).insert(index, self._coerce(value))

    def __setitem__(self, index, value):
        if isinstance(index, slice):
            value = [self._coerce(v) for v in value]
        else:
            value = self._coerce(value)
        super(LoDTensorArray, self).__setitem__(index, value)

    def __iadd__(self, iterable):
        self.extend(iterable)
        return self


def _nested_levels(data):
    """Walk a nested list down to its innermost sequences. Returns
    (levels, flat): `levels` is the recursive_seq_lens derived from the
    nesting (one level per list depth above the innermost), `flat` the
    innermost sequences as [len, d] arrays, in order."""
    if isinstance(data[0], list) and data[0] and isinstance(data[0][0], list):
        # one level of grouping above sequences: recurse per group
        group_lens = []
        sub_levels = None
        flat = []
        for group in data:
            levels, seqs = _nested_levels(group)
            group_lens.append(len(levels[0]) if levels else len(seqs))
            if sub_levels is None:
                sub_levels = [list(lv) for lv in levels]
            else:
                for acc, lv in zip(sub_levels, levels):
                    acc.extend(lv)
            flat.extend(seqs)
        return [group_lens] + (sub_levels or []), flat
    # innermost: a list of sequences (1-D scalar runs or [len, d] rows)
    lens, flat = [], []
    for seq in data:
        seq = np.asarray(seq)
        if seq.ndim == 1:
            seq = seq[:, None]
        lens.append(seq.shape[0])
        flat.append(seq)
    return [lens], flat


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """reference python/paddle/fluid/lod_tensor.py:create_lod_tensor.

    List `data` is interpreted as nested sequences of SCALARS (word ids
    etc., the reference's documented list form): each nesting level above
    the innermost becomes one LoD level. Pass an ndarray plus explicit
    `recursive_seq_lens` for multi-dimensional rows."""
    if isinstance(data, list):
        # Nested list of sequences: each nesting level above the innermost
        # contributes one LoD level (reference create_lod_tensor derives
        # the recursive structure from the list shape).
        levels, flat = _nested_levels(data)
        if recursive_seq_lens is not None:
            # the reference asserts the caller's lens against the ones the
            # nesting derives ("data and recursive_seq_lens do not match");
            # accepting a mismatched feed silently would change lengths
            given = [list(lv) for lv in recursive_seq_lens]
            if given != [list(lv) for lv in levels]:
                raise ValueError(
                    "data and recursive_seq_lens do not match: the nested "
                    "list derives %r but recursive_seq_lens is %r"
                    % (levels, given))
        arr = np.concatenate(flat, axis=0)
        if arr.dtype.kind in 'iu':
            # reference create_lod_tensor flattens list data to int64
            arr = arr.astype(np.int64)
        return LoDTensor(arr, levels)
    arr = np.asarray(data)
    t = LoDTensor(arr, recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError("invalid recursive_seq_lens %s for data of %d rows"
                         % (recursive_seq_lens, arr.shape[0]))
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low, high):
    total = sum(recursive_seq_lens[-1])
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype('int64')
    return LoDTensor(data, recursive_seq_lens)
