"""ParallelExecutor: data-parallel training over the device mesh.

Parity: reference python/paddle/fluid/parallel_executor.py + the C++ SSA
graph executor (paddle/fluid/framework/details/*) that scatters the batch
over GPUs and NCCL-allreduces gradients.

DEPRECATED shim (docs/parallel.md, docs/migration.md): data parallelism is
a first-class Program concern now — ``program.set_mesh({'dp': N})`` (plus
``ParamAttr(sharding=...)`` for parameter layouts) and plain
``Executor.run``/``run_bundle`` lower the annotated Program through ONE
GSPMD-partitioned XLA module. This class survives as a thin wrapper that
emits exactly those annotations for the duration of each ``run`` call:
``BuildStrategy.ReduceStrategy.Reduce`` becomes per-parameter ZeRO-3
sharding annotations, the feed shards over the mesh's data axis, and the
compiled step carries explicit in/out shardings + the memory plan's
donation vector — the same code path ``run_bundle`` and the Trainer use.
"""
import warnings

import numpy as np

import jax
from jax.sharding import Mesh

from . import core
from .executor import Executor, global_scope
from .framework import default_main_program

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']

# ZeRO-3 floor for the Reduce build strategy's emitted annotations —
# mirrors parallel.fsdp_shard_params(min_size=1024): gather latency on a
# tiny tensor outweighs the bytes saved.
_FSDP_MIN_SIZE = 1024

_warned = [False]


def _warn_deprecated():
    if _warned[0]:
        return
    _warned[0] = True
    warnings.warn(
        "ParallelExecutor is deprecated: declare the mesh on the Program "
        "instead — program.set_mesh({'dp': N}) (ParamAttr(sharding=...) "
        "for parameter layouts) and run it through the plain "
        "Executor.run/run_bundle/Trainer. See docs/parallel.md and "
        "docs/migration.md.", DeprecationWarning, stacklevel=3)


class ExecutionStrategy(object):
    """Shim of the reference ExecutionStrategy pybind struct."""

    def __init__(self):
        self.num_threads = 0
        self.use_event = True
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1


class BuildStrategy(object):
    """Shim of the reference BuildStrategy pybind struct."""

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor(object):
    """reference parallel_executor.py:ParallelExecutor — now a shim that
    emits GSPMD annotations (module docstring).

    Single-host surface: the dp mesh spans this process's visible devices.
    The reference's `num_trainers`/`trainer_id` multi-node path
    (parallel_executor.py:43-46,74 — one NCCL clique across nodes) is
    accepted for API compatibility but does not grow the mesh here;
    multi-host scale-out is `parallel.init_distributed()`
    (jax.distributed) BEFORE building the executor, after which the same
    GSPMD program spans every host's devices (tests/test_multihost.py)."""

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, devices=None,
                 num_devices=None, use_tpu=None, **kwargs):
        _warn_deprecated()
        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._scope = scope or global_scope()
        self._build_strategy = build_strategy
        devs = devices or jax.devices()
        if num_devices is not None:
            if num_devices > len(devs):
                raise ValueError("num_devices=%d > %d visible devices"
                                 % (num_devices, len(devs)))
            devs = devs[:num_devices]
        self._mesh = Mesh(np.asarray(devs), ('dp',))
        self._ndev = len(devs)
        self._axes = (('dp', self._ndev),)
        self._exe = Executor(core.TPUPlace(0) if core.is_compiled_with_tpu()
                             else core.CPUPlace())
        if share_vars_from is not None:
            self._scope = share_vars_from._scope

    @property
    def device_count(self):
        return self._ndev

    def _emit_annotations(self):
        """Translate the build strategy into per-tensor sharding
        annotations: ReduceStrategy.Reduce (the reference's partitioned
        parameter updates) becomes ZeRO-3 — each large persistable
        annotated ('dp' on its first divisible dim), exactly
        parallel.fsdp_shard_params' placement rule. Returns the vars WE
        annotated so run() can revert them: like the mesh attrs, the
        annotations are armed per call — they must not leak onto the
        user's Program (or into its clones / saved artifacts) after this
        deprecated shim returns."""
        bs = self._build_strategy
        if bs is None or bs.reduce_strategy != \
                BuildStrategy.ReduceStrategy.Reduce:
            return []
        emitted = []
        for v in self._program.global_block().vars.values():
            if not v.persistable or v.sharding or v.shape is None:
                continue
            if any(d < 0 for d in v.shape):
                continue
            if int(np.prod(v.shape or (1,))) < _FSDP_MIN_SIZE:
                continue
            for d, size in enumerate(v.shape):
                if size % self._ndev == 0:
                    v.sharding = (None,) * d + ('dp',)
                    emitted.append(v)
                    break
        return emitted

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        """reference parallel_executor.py:run. The feed is ONE global batch
        (sharded over the mesh), matching feed_dict semantics.

        Implementation: arm the Program's mesh annotation for THIS call
        only (a later plain Executor.run on the same program must stay
        single-device — the scope's mesh-placed params are a separate,
        documented GSPMD property) and dispatch through the one annotated
        executor path."""
        feed = feed if feed is not None else feed_dict or {}
        p = self._program
        emitted = self._emit_annotations()
        prev = (getattr(p, '_mesh_axes', None),
                getattr(p, '_mesh_data_axis', None),
                getattr(p, '_dist_mesh', None),
                getattr(p, '_annot_axes', None))
        p._mesh_axes = self._axes
        p._mesh_data_axis = 'dp'
        p._dist_mesh = self._mesh   # pre-built: first n devices only
        p._annot_axes = self._axes
        try:
            return self._exe.run(p, feed=feed, fetch_list=fetch_list,
                                 scope=self._scope,
                                 return_numpy=return_numpy)
        finally:
            (p._mesh_axes, p._mesh_data_axis, p._dist_mesh,
             p._annot_axes) = prev
            for v in emitted:
                v.sharding = None

    def bcast_params(self):
        """Parity shim: with GSPMD-replicated params there is nothing to
        broadcast — XLA keeps replicas consistent."""
        return None
