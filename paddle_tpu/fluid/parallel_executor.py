"""ParallelExecutor: data-parallel training over the device mesh.

Parity: reference python/paddle/fluid/parallel_executor.py + the C++ SSA
graph executor (paddle/fluid/framework/details/*) that scatters the batch
over GPUs and NCCL-allreduces gradients.

TPU-first redesign (GSPMD): the SAME lowered program is jitted once over a
1-D `dp` jax.sharding.Mesh — the feed is sharded on the batch axis, the
persistables (params/optimizer state) are replicated, and XLA's SPMD
partitioner inserts the gradient all-reduce on ICI automatically. No
per-device program copies, no explicit allreduce graph: scaling to a
multi-host mesh is the same code with more devices.
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from .executor import Executor, global_scope
from .framework import default_main_program
from .lowering import SeqValue

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


class ExecutionStrategy(object):
    """Shim of the reference ExecutionStrategy pybind struct."""

    def __init__(self):
        self.num_threads = 0
        self.use_event = True
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1


class BuildStrategy(object):
    """Shim of the reference BuildStrategy pybind struct."""

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor(object):
    """reference parallel_executor.py:ParallelExecutor.

    Single-host surface: the dp mesh spans this process's visible devices.
    The reference's `num_trainers`/`trainer_id` multi-node path
    (parallel_executor.py:43-46,74 — one NCCL clique across nodes) is
    accepted for API compatibility but does not grow the mesh here;
    multi-host scale-out is `parallel.init_multihost()` (jax.distributed)
    BEFORE building the executor, after which the same GSPMD program spans
    every host's devices (tests/test_multihost.py)."""

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, devices=None,
                 num_devices=None, use_tpu=None, **kwargs):
        self._program = main_program or default_main_program()
        self._loss_name = loss_name
        self._scope = scope or global_scope()
        self._build_strategy = build_strategy
        devs = devices or jax.devices()
        if num_devices is not None:
            if num_devices > len(devs):
                raise ValueError("num_devices=%d > %d visible devices"
                                 % (num_devices, len(devs)))
            devs = devs[:num_devices]
        self._mesh = Mesh(np.asarray(devs), ('dp',))
        self._ndev = len(devs)
        self._exe = Executor(core.TPUPlace(0) if core.is_compiled_with_tpu()
                             else core.CPUPlace())
        self._exe.place = None  # device placement handled via shardings
        self._data_sharding = NamedSharding(self._mesh, P('dp'))
        self._repl_sharding = NamedSharding(self._mesh, P())
        self._placed = False
        if share_vars_from is not None:
            self._scope = share_vars_from._scope

    @property
    def device_count(self):
        return self._ndev

    def _shard_batch(self, val):
        def put(x, spec_dims):
            n = x.shape[0]
            if n % self._ndev:
                # Padding by duplicating rows would silently change the
                # loss/gradients (duplicated examples get double weight).
                raise ValueError(
                    "ParallelExecutor feed batch size %d is not divisible "
                    "by the %d mesh devices; drop the remainder (e.g. wrap "
                    "the reader in paddle.batch(..., drop_last=True)) or "
                    "pad+mask it yourself" % (n, self._ndev))
            sh = NamedSharding(self._mesh, P('dp', *([None] * (x.ndim - 1))))
            return jax.device_put(jnp_asarray(x), sh)

        import jax.numpy as jnp

        def jnp_asarray(x):
            return jnp.asarray(np.asarray(x))

        if isinstance(val, SeqValue):
            return SeqValue(put(val.data, None), put(val.lengths, None),
                            val.outer_lengths)
        from .lod_tensor import LoDTensor
        if isinstance(val, LoDTensor):
            return self._shard_batch(val.to_seq_value())
        return put(np.asarray(val), None)

    def _replicate_persistables(self):
        import jax.numpy as jnp
        bs = self._build_strategy
        # reference BuildStrategy.ReduceStrategy.Reduce partitioned each
        # parameter's update onto one device; the GSPMD equivalent is
        # ZeRO-3 — shard the parameters themselves over dp
        fsdp = (bs is not None and bs.reduce_strategy ==
                BuildStrategy.ReduceStrategy.Reduce)
        if fsdp:
            from .. import parallel
            dense = {n: v for n, v in self._scope.vars.items()
                     if v is not None and not isinstance(v, SeqValue)}
            self._scope.vars.update(
                parallel.fsdp_shard_params(dense, self._mesh))
            self._placed = True
            return
        for name, v in list(self._scope.vars.items()):
            if v is None or isinstance(v, SeqValue):
                continue
            self._scope.vars[name] = jax.device_put(jnp.asarray(v),
                                                    self._repl_sharding)
        self._placed = True

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        """reference parallel_executor.py:run. The feed is ONE global batch
        (sharded over the mesh), matching feed_dict semantics."""
        feed = feed if feed is not None else feed_dict or {}
        if not self._placed:
            self._replicate_persistables()
        dev_feed = {k: self._shard_batch(v) for k, v in feed.items()}
        prev = self._exe._to_device
        self._exe._to_device = lambda v, var=None: v  # already placed
        # expose the dp mesh to mesh-aware op lowerings (moe_mlp dispatches
        # experts over this axis) for THIS run only — a later plain
        # Executor.run on the same program must stay single-device
        prev_mesh = getattr(self._program, '_dist_mesh', None)
        self._program._dist_mesh = self._mesh
        try:
            return self._exe.run(self._program, feed=dev_feed,
                                 fetch_list=fetch_list, scope=self._scope,
                                 return_numpy=return_numpy)
        finally:
            self._exe._to_device = prev
            self._program._dist_mesh = prev_mesh

    def bcast_params(self):
        """Parity shim: with GSPMD-replicated params there is nothing to
        broadcast — XLA keeps replicas consistent."""
        return None
