"""LayerHelper: shared plumbing for layer functions.

Parity: reference python/paddle/fluid/layer_helper.py — creates parameters
(registering their init op on the startup program), temp variables, bias and
activation epilogues.
"""
import copy

from . import unique_name
from .framework import Variable, Parameter, default_main_program, \
    default_startup_program
from .initializer import Constant, Xavier
from .param_attr import ParamAttr, WeightNormParamAttr

__all__ = ['LayerHelper']


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get('name')
        if name is None:
            self.kwargs['name'] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs['name']

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name='input'):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get('param_attr', None))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get('bias_attr', None))

    def multiple_param_attr(self, length):
        param_attr = self.param_attr
        if isinstance(param_attr, ParamAttr):
            param_attr = [param_attr]
        if len(param_attr) != 1 and len(param_attr) != length:
            raise ValueError("parameter number mismatch")
        elif len(param_attr) == 1 and length != 1:
            tmp = [None] * length
            for i in range(length):
                tmp[i] = copy.deepcopy(param_attr[0])
            param_attr = tmp
        return param_attr

    def iter_inputs_and_params(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        param_attrs = self.multiple_param_attr(len(inputs))
        for ipt, param_attr in zip(inputs, param_attrs):
            yield ipt, param_attr

    def input_dtype(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("data type mismatch in inputs")
        return dtype

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        """Creates the Parameter in the main program's global block AND a
        same-named var + init op in the startup program (reference
        layer_helper.py:create_parameter)."""
        assert isinstance(attr, ParamAttr), (
            "expected a ParamAttr, got %r — note param_attr/bias_attr=False "
            "suppresses the parameter only in layers that support it "
            "(fc/conv bias via append_bias_op), matching the reference"
            % (attr,))
        suffix = 'b' if is_bias else 'w'
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, suffix]))
        if default_initializer is None and attr.initializer is None:
            if is_bias:
                attr.set_default_bias_initializer()
            else:
                attr.set_default_param_initializer()
        else:
            attr.set_default_initializer(default_initializer)

        shape = [int(s) for s in shape]
        if isinstance(attr, WeightNormParamAttr):
            # weight-norm reparameterization w = v * g / ||v|| (reference
            # layer_helper.py:_create_weight_normalize, arXiv:1602.07868)
            return self._create_weight_normalize(attr, shape, dtype)
        startup_blk = self.startup_program.global_block()
        sp_var = startup_blk.create_parameter(
            name=attr.name, shape=shape, dtype=dtype,
            **{k: v for k, v in attr.to_kwargs(with_initializer=False).items()
               if k != 'name'})
        attr.initializer(sp_var, startup_blk)
        main_blk = self.main_program.global_block()
        if attr.name in main_blk.vars:
            return main_blk.vars[attr.name]
        return main_blk.create_parameter(
            name=attr.name, shape=shape, dtype=dtype,
            **{k: v for k, v in attr.to_kwargs().items() if k != 'name'})

    def _append_norm_except_dim(self, block, v, dim, out):
        """Append ops computing ||v|| over every axis except `dim` (all
        axes when dim is None), keepdims, into var `out`. The ops run with
        real shape inference (square/reduce_sum/sqrt all have lowering
        rules), so the wn temps carry inferred shapes/dtypes and the
        analysis shape pass can check the whole reparameterization."""
        sq = block.create_var(
            name=unique_name.generate(self.name + '.wn_sq'),
            shape=None, dtype=v.dtype)
        block.append_op(type='square', inputs={'X': [v]},
                        outputs={'Out': [sq]})
        red = block.create_var(
            name=unique_name.generate(self.name + '.wn_red'),
            shape=None, dtype=v.dtype)
        ndim = len(v.shape)
        axes = [i for i in range(ndim) if dim is None or i != dim]
        block.append_op(type='reduce_sum', inputs={'X': [sq]},
                        outputs={'Out': [red]},
                        attrs={'dim': axes, 'keep_dim': True})
        block.append_op(type='sqrt', inputs={'X': [red]},
                        outputs={'Out': [out]})
        return out

    def _create_weight_normalize(self, attr, shape, dtype):
        """w = v * (g / ||v||_except_dim): v carries the direction with the
        user's initializer, g the magnitude, initialized in the startup
        program to ||v_init|| so the initial w equals v_init (reference
        layer_helper.py:232)."""
        dim = attr.dim
        g_shape = [1] * len(shape)
        if dim is not None:
            g_shape[dim] = shape[dim]

        v_attr = copy.deepcopy(attr)
        v_attr.__class__ = ParamAttr
        v_attr.name = attr.name + '_v'
        v = self.create_parameter(v_attr, shape, dtype)

        g_attr = copy.deepcopy(attr)
        g_attr.__class__ = ParamAttr
        g_attr.name = attr.name + '_g'
        g_attr.initializer = Constant(0.0)  # overwritten by startup ops
        g = self.create_parameter(g_attr, g_shape, dtype)

        # startup: g <- ||v_init||
        startup_blk = self.startup_program.global_block()
        self._append_norm_except_dim(startup_blk,
                                     startup_blk.vars[v.name], dim,
                                     startup_blk.vars[g.name])

        # main: w = v * (g / ||v||), recomputed each step inside the jit
        blk = self.main_program.current_block()
        norm = blk.create_var(
            name=unique_name.generate(self.name + '.wn_norm'),
            shape=None, dtype=dtype)
        self._append_norm_except_dim(blk, v, dim, norm)
        scale = blk.create_var(
            name=unique_name.generate(self.name + '.wn_scale'),
            shape=None, dtype=dtype)
        blk.append_op(type='elementwise_div', inputs={'X': [g], 'Y': [norm]},
                      outputs={'Out': [scale]}, attrs={'axis': -1})
        w = blk.create_var(name=attr.name, shape=shape, dtype=dtype)
        blk.append_op(type='elementwise_mul', inputs={'X': [v], 'Y': [scale]},
                      outputs={'Out': [w]}, attrs={'axis': -1})
        return w

    def get_or_create_parameter(self, name, shape, dtype, is_bias=False):
        """Fetch a named parameter if this program already has it, else
        create it (used by inference graphs that share weights with the
        training graph by name)."""
        main_blk = self.main_program.global_block()
        var = main_blk.vars.get(name)
        if var is not None:
            if not isinstance(var, Parameter):
                raise ValueError(
                    "var %r exists but is not a Parameter" % name)
            if tuple(var.shape) != tuple(int(s) for s in shape):
                raise ValueError(
                    "shared parameter %r has shape %s, requested %s"
                    % (name, var.shape, shape))
            return var
        return self.create_parameter(ParamAttr(name=name), shape=shape,
                                     dtype=dtype, is_bias=is_bias)

    def get_parameter(self, name):
        param = self.main_program.global_block().var(name)
        if not isinstance(param, Parameter):
            raise ValueError("no Parameter named %s" % name)
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False,
                                           shape=None, lod_level=0):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, 'tmp'])),
            dtype=dtype, shape=shape, persistable=False,
            lod_level=lod_level, stop_gradient=stop_gradient)

    # reference name
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        startup_blk = self.startup_program.global_block()
        if var.name not in startup_blk.vars:
            startup_blk.create_var(name=var.name, shape=var.shape,
                                   dtype=var.dtype, persistable=True)
        initializer(startup_blk.vars[var.name], startup_blk)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        """Add a bias over dims [dim_start, dim_end) of the input."""
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr and any(d == -1 for d in size):
            raise ValueError(
                "bias shape %s contains a dynamic dim; pass dim_start/"
                "dim_end selecting only static dims (e.g. dim_start=-1 for "
                "the feature axis of a sequence)" % (size,))
        if not bias_attr:
            return input_var
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        # elementwise: shape/lod carry through
        tmp = self.create_variable_for_type_inference(
            dtype=input_var.dtype, shape=input_var.shape,
            lod_level=input_var.lod_level)
        self.append_op(
            type='elementwise_add',
            inputs={'X': [input_var], 'Y': [b]},
            outputs={'Out': [tmp]},
            attrs={'axis': dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get('act', None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {'type': act}
        else:
            act = copy.deepcopy(act)
        act_type = act.pop('type')
        # activations are elementwise: shape/lod carry through
        tmp = self.create_variable_for_type_inference(
            dtype=input_var.dtype, shape=input_var.shape,
            lod_level=input_var.lod_level)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name, None)
        if not isinstance(param, cls):
            raise TypeError("%s of %s must be %s" %
                            (param_name, self.layer_type, cls))
