"""fluid.analysis — ahead-of-lowering static analysis of Fluid IR.

The TPU path lowers a whole Program into ONE XLA module, so there is no
per-op InferShape interpreter to reject a malformed graph at dispatch time
(the reference's C++ executor validated every op as it ran). This package
is that validation, moved to BUILD time: multi-pass static analysis over
the Program/Block/Operator IR that returns structured `Finding`s with op
provenance, before jit ever sees the graph.

Passes (docs/analysis.md has the catalog):
  1. dataflow/def-use      — dangling inputs, writes to feeds, dead ops,
                             unreachable fetches, use-before-write of
                             persistables (incl. run_bundle's scan carry);
  2. shape/dtype inference — ShapeDtypeStruct propagation through every
                             block via the per-op infer-rule registry
                             (defaulting to eval_shape over the lowering
                             rules — one definition of op semantics);
  3. donation safety       — the persistable write-set vs the executor's
                             buffer-donation decision (the PR-3
                             donated-read-only-step bug class);
  4. concurrency           — scope races: persistable writes in programs
                             declared to run concurrently over a shared
                             scope (serving Predictors, async windows);
  5. sharding              — annotation consistency against the mesh
                             spec, incl. the DimSharding refusal of a
                             dim-sharded TIERED table;
  6. cost model            — per-device HBM residency / collective
                             bytes / FLOPs from declared metadata
                             (costmodel.cost_report), ImplicitReshard
                             hotspots, HbmOverBudget vs --hbm-budget;
  7. collective safety     — the statically-derived collective sequence
                             vs divergent control flow and concurrent
                             co-hosted modules (CollectiveDivergence,
                             ConcurrentCollectives).

Entry points:
  * analyze(program, ...)        -> [Finding]   (pure, never raises)
  * Program.verify(level=...)    -> [Finding]   (raises/warns per level)
  * maybe_verify(...)            — the PADDLE_TPU_VERIFY={off,warn,error}
    gate the Executor and Predictor call once per program key; records the
    `analysis.verify` obs span and the `analysis.findings` counter.
  * tools/program_lint.py        — the same analysis over a saved
    __model__ artifact.
"""
import os

from ... import obs
from . import collectives as _collectives
from . import concurrency as _concurrency
from . import costmodel as _costmodel
from . import dataflow as _dataflow
from . import donation as _donation
from . import shapes as _shapes
from . import sharding as _sharding
from .collectives import collective_sequence  # noqa: F401
from .costmodel import CostReport, cost_report  # noqa: F401
from .dataflow import live_mask  # noqa: F401  (re-export: passes.dce)
from .donation import executor_donates, executor_write_set, \
    persistable_write_set  # noqa: F401  (re-export: executor uses these)
from .findings import (Finding, ProgramVerifyError, SEV_ERROR, SEV_WARNING,
                       sort_findings)
from .shapes import register_infer  # noqa: F401

__all__ = [
    'analyze', 'maybe_verify', 'report_findings', 'verify_mode',
    'Finding', 'ProgramVerifyError', 'SEV_ERROR', 'SEV_WARNING',
    'executor_donates', 'executor_write_set', 'persistable_write_set',
    'live_mask', 'register_infer', 'ENV_VERIFY',
    'CostReport', 'cost_report', 'collective_sequence',
]

# PADDLE_TPU_VERIFY wires analyze() into Executor.run / Predictor load,
# once per program key:
#   off   (default) — no analysis on the run path;
#   warn            — findings become warnings, the run proceeds;
#   error           — error-severity findings raise ProgramVerifyError
#                     BEFORE lowering (warnings still warn).
ENV_VERIFY = 'PADDLE_TPU_VERIFY'

_C_FINDINGS = obs.counter('analysis.findings')
_C_VERIFIED = obs.counter('analysis.programs_verified')


def verify_mode():
    v = os.environ.get(ENV_VERIFY, 'off').strip().lower()
    if v in ('', '0', 'off', 'false', 'no', 'none'):
        return 'off'
    if v in ('warn', 'warning'):
        return 'warn'
    if v in ('error', 'raise', '1', 'on', 'true'):
        return 'error'
    raise ValueError(
        '%s must be one of off|warn|error, got %r' % (ENV_VERIFY, v))


def analyze(program, startup=None, feeds=None, fetches=None,
            initialized=None, concurrent=False, donates=None, bundle=False,
            dead_ops=True, stats=None, mesh_axes=None, cost=False,
            hbm_budget=None):
    """Run every pass over `program`; returns sorted [Finding]. Pure: the
    program is never mutated and nothing is raised for findings.

    startup     — the matching startup Program; enables the
                  use-before-write check (which persistables it
                  initializes is unknowable without it).
    feeds       — iterable of names actually fed (None: every is_data var
                  counts as feedable).
    fetches     — fetch target names; enables unreachable-fetch and
                  dead-op detection (None: any terminal output may be a
                  fetch, so neither check can fire).
    initialized — names holding scope values at step entry (the executor
                  passes its persist_in + feed names for a precise env
                  model; None: assume every persistable is initialized).
    concurrent  — the program will run concurrently over a shared scope
                  (serving); arms the scope-race pass.
    donates     — the executor's actual donation decision to cross-check
                  (None: re-derive from the executor's own rule).
    bundle      — the step will run under run_bundle's scan carry.
    dead_ops    — False skips DeadOp liveness; the executor passes False
                  because one run's fetch subset is not dead-code
                  evidence (another call may fetch the rest). Lint and
                  standalone contexts keep it on.
    stats       — optional dict receiving shape-pass coverage counts.
    mesh_axes   — {'dp': 8}-style mesh override for the sharding-
                  consistency / cost / collective passes
                  (program_lint --mesh); None uses the program's own
                  set_mesh() spec.
    cost        — arm the cost-model pass's ImplicitReshard hotspot
                  findings (program_lint --cost; cost_report() is the
                  full-report surface).
    hbm_budget  — per-device HBM budget in bytes; the cost model emits
                  an HbmOverBudget ERROR when persistable residency
                  exceeds it (implies the cost pass).
    """
    findings = []
    findings += _dataflow.run_pass(program, feeds=feeds, fetches=fetches,
                                   initialized=initialized, startup=startup,
                                   bundle=bundle, dead_ops=dead_ops)
    findings += _shapes.run_pass(program, feeds=feeds, stats=stats)
    findings += _donation.run_pass(program, donates=donates)
    findings += _concurrency.run_pass(program, concurrent=concurrent)
    findings += _sharding.run_pass(program, mesh_axes=mesh_axes)
    if cost or hbm_budget is not None:
        findings += _costmodel.run_pass(program, mesh_axes=mesh_axes,
                                        hbm_budget=hbm_budget,
                                        feeds=feeds, fetches=fetches)
    findings += _collectives.run_pass(program, concurrent=concurrent,
                                      mesh_axes=mesh_axes)
    return sort_findings(findings)


def report_findings(findings, mode='warn', where=None):
    """Uniform disposition of a finding list: 'warn' warns each finding;
    'error' raises ProgramVerifyError when any error-severity finding
    exists (warnings still warn). Returns the findings."""
    import warnings
    if not findings:
        return findings
    errors = [f for f in findings if f.severity == SEV_ERROR]
    tag = ' (%s)' % where if where else ''
    if mode == 'error' and errors:
        raise ProgramVerifyError(
            'program verification failed%s: %d error finding(s) '
            '(%d total)\n%s' % (
                tag, len(errors), len(findings),
                '\n'.join('  %s' % f for f in findings)), findings)
    for f in findings:
        warnings.warn('program verifier%s: %s' % (tag, f), UserWarning,
                      stacklevel=3)
    return findings


# once-per-program-key memo for the run-path gate; bounded — program
# version bumps create new keys, so runaway program mutation is capped
_seen = set()
_SEEN_CAP = 8192


def maybe_verify(program, key=None, where=None, **ctx):
    """The run-path verification gate: no-op unless PADDLE_TPU_VERIFY is
    warn/error, and at most ONE analysis per (program uid, version,
    context) key — steady-state steps never re-analyze. Records the
    `analysis.verify` span (with findings count) and the
    analysis.findings counter. Returns the findings, or None when gated
    off / already verified."""
    mode = verify_mode()
    if mode == 'off':
        return None
    if key is None:
        key = (program._uid, program._version,
               tuple(sorted(ctx.get('feeds') or ())),
               tuple(ctx.get('fetches') or ()),
               bool(ctx.get('concurrent')), ctx.get('donates'),
               bool(ctx.get('bundle')))
    # the memo is per (mode, key): escalating PADDLE_TPU_VERIFY from warn
    # to error mid-process must re-judge already-seen programs, not skip
    key = (mode, key)
    if key in _seen:
        return None
    if len(_seen) > _SEEN_CAP:
        _seen.clear()
    with obs.span('analysis.verify', mode=mode,
                  where=where or 'executor') as sp:
        findings = analyze(program, **ctx)
        sp.fields['findings'] = len(findings)
        sp.fields['errors'] = sum(
            1 for f in findings if f.severity == SEV_ERROR)
    _C_VERIFIED.inc()
    _C_FINDINGS.inc(len(findings))
    if findings:
        obs.event('analysis.findings',
                  where=where or 'executor', mode=mode,
                  kinds=sorted({f.kind for f in findings}),
                  count=len(findings))
    # may raise (mode=error): memoize ONLY a verification that passed, so
    # a rejected program stays rejected on every retry of the same key —
    # otherwise the second attempt would bypass the verifier and run the
    # broken (or unsafe: scope-race, donation-gap) step anyway
    report_findings(findings, mode=mode, where=where)
    _seen.add(key)
    return findings
