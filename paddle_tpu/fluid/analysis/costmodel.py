"""Pass 6 — static sharding cost model (docs/analysis.md#pass-6).

Per-device HBM/comms/FLOP estimates from the Program's declared
metadata + its set_mesh spec, before jit ever sees the graph — the
missing piece ROADMAP items 3 (dim sharding) and 4 (fleet bin-packing)
both need. The pass walks the IR only: no jax import on the accounting
path, no device, no weights. What it computes:

  * per-device persistable RESIDENCY — every persistable's bytes at its
    declared dtype (64-bit declarations priced at the 32-bit width they
    execute at — the x64-narrowing policy the shape pass shares), with
    sharded dims divided by their mesh-axis extent when the axis tiles
    them (untileable dims replicate, exactly the executor's fallback)
    and int8 quant-marked weights priced at their quantized width
    (int8 bytes + the per-channel scale);
  * per-op ACTIVATION bytes and a peak-liveness TEMP estimate — def/
    last-use intervals over the global block (fetched names live to the
    end; `analysis.live_mask` drops dead ops from the accounting, the
    memplan write-set keeps written persistables in residency, not
    temps);
  * COLLECTIVE bytes implied by the sharding annotations — the
    all_to_all lookup wire priced by embedding.lookup.wire_stats, the
    dp gradient all-reduce over the grad payload, moe/ring exchanges,
    and resharding hotspots reported as `ImplicitReshard` findings
    naming both placements;
  * per-op FLOPs from a small registry (mul/matmul 2·M·K·N, conv2d
    2·out·Cin/g·kh·kw, elementwise ≈ out elems; default: output
    elements).

Entry points: `analysis.cost_report(program, mesh_axes=)` returns the
typed `CostReport` (per-table, per-op-kind, totals; records the
`analysis.cost` obs span); `run_pass` (wired into `analyze(cost=...)`)
emits the `ImplicitReshard` findings plus `HbmOverBudget` when an
`hbm_budget` is declared (program_lint --cost --hbm-budget).

The VALIDATION CONTRACT (drilled by tests/test_analysis.py): on a
program whose vars carry declared shapes, `residency_per_device` agrees
with `Executor.compiled_memory_stats().argument_size_in_bytes` minus
the feed bytes to within max(2 KiB, 5%) — argument bytes ARE the
persistables (shard-sized for sharded modules) plus feeds, so the
static number is load-bearing for bin-packing, not decorative.
"""
from ... import obs
from . import collectives as _collectives
from .dataflow import live_mask, op_reads, op_writes
from .findings import (Finding, HBM_OVER_BUDGET, IMPLICIT_RESHARD,
                       SEV_ERROR, SEV_WARNING)
from .shapes import _canon_dtype

__all__ = ['CostReport', 'cost_report', 'run_pass', 'var_bytes']

# canonical itemsizes at EXECUTED width (x64 narrows — _canon_dtype)
_ITEMSIZE = {
    'float32': 4, 'float16': 2, 'bfloat16': 2,
    'int32': 4, 'uint32': 4, 'int16': 2, 'uint16': 2,
    'int8': 1, 'uint8': 1, 'bool': 1,
}


def _itemsize(dtype):
    return _ITEMSIZE.get(_canon_dtype(dtype), 4)


def _elems(shape, batch):
    """Element count of a declared shape, -1 (dynamic batch) -> batch.
    None shapes (undeclared) price as 0 — report what is provable."""
    if shape is None:
        return 0
    n = 1
    for d in shape:
        try:
            d = int(d)
        except (TypeError, ValueError):
            return 0
        n *= batch if d < 0 else d
    return n


def _axes_of_entry(entry):
    return entry if isinstance(entry, tuple) else (entry,)


def var_bytes(v, axes=None, batch=1):
    """Per-device bytes of one Variable under mesh `axes`: sharded dims
    divide by their axis extent when it tiles them; untileable dims
    replicate (the executor's fallback, flagged separately by the
    sharding pass)."""
    if v.shape is None:
        return 0
    shape = [batch if int(d) < 0 else int(d) for d in v.shape]
    spec = getattr(v, 'sharding', None)
    if axes and spec:
        for d, entry in enumerate(tuple(spec)[:len(shape)]):
            if entry is None:
                continue
            tile = 1
            for ax in _axes_of_entry(entry):
                tile *= int(axes.get(ax, 1))
            if tile > 1 and shape[d] % tile == 0:
                shape[d] //= tile
    n = 1
    for d in shape:
        n *= d
    return n * _itemsize(v.dtype)


def _quant_widths(program):
    """weight name -> (int8 elems-stand-in itemsize, scale bytes) for a
    QUANT-MARKED program (passes.quant_pass.mark_quant): optimize()
    will rewrite these weights to int8 + per-channel scale, so the
    deployment residency prices them at the quantized width. Offline-
    quantized programs (quantize_weights) need no special casing — the
    int8/scale persistables already carry their true dtypes."""
    try:
        from ..passes import quant_pass
    except Exception:
        return {}
    if not quant_pass.is_quant(program):
        return {}
    types = set(getattr(program, '_quant_ops', None) or
                quant_pass.QUANT_SLOTS)
    out = {}
    blk = program.global_block()
    for op in blk.ops:
        target = quant_pass._weight_target(blk, op, types)
        if target is None:
            continue
        _, axis, v = target
        if v.shape is None:
            continue
        scale_elems = int(v.shape[axis]) if axis < len(v.shape) else 1
        out[v.name] = (1, scale_elems * 4)
    return out


def _tables(program):
    """table name -> [(op, dist_axis-or-None)] over every lookup op."""
    tables = {}
    for blk in program.blocks:
        for op in blk.ops:
            if op.type not in ('lookup_table', 'quant_lookup_table'):
                continue
            ax = op.attrs.get('dist_axis') \
                if op.attrs.get('is_distributed') else None
            for v in op.inputs.get('W', []):
                tables.setdefault(v.name, []).append((op, ax))
    return tables


# -- FLOP registry ---------------------------------------------------------

def _flops_matmul(op, batch):
    xs = op.inputs.get('X', [])
    ys = op.inputs.get('Y', [])
    if not xs or not ys or xs[0].shape is None or ys[0].shape is None:
        return 0
    k = int(ys[0].shape[0])
    n = _elems(ys[0].shape, batch) // max(k, 1)
    m = _elems(xs[0].shape, batch) // max(k, 1)
    return 2 * m * k * n


def _flops_conv2d(op, batch):
    outs = op.outputs.get('Output', []) or op.outputs.get('Out', [])
    filts = op.inputs.get('Filter', [])
    if not outs or not filts or filts[0].shape is None:
        return 0
    fshape = filts[0].shape      # [Cout, Cin/groups, kh, kw]
    per_out = 2
    for d in fshape[1:]:
        per_out *= int(d)
    return _elems(outs[0].shape, batch) * per_out


def _flops_default(op, batch):
    return sum(_elems(v.shape, batch)
               for vs in op.outputs.values() for v in vs)


_FLOP_RULES = {
    'mul': _flops_matmul,
    'matmul': _flops_matmul,
    'conv2d': _flops_conv2d,
    'softmax': lambda op, b: 5 * _flops_default(op, b),
}


def _op_flops(op, batch):
    try:
        return int(_FLOP_RULES.get(op.type, _flops_default)(op, batch))
    except Exception:
        return 0


# -- the report ------------------------------------------------------------

class CostReport(object):
    """Typed result of the static cost model (see module docstring).
    All byte figures are PER DEVICE unless suffixed _total."""

    __slots__ = ('mesh', 'n_devices', 'batch',
                 'residency_per_device', 'residency_total',
                 'persistables', 'tables',
                 'activation_bytes', 'peak_temp_bytes',
                 'collectives', 'comm_bytes_per_step',
                 'flops_per_step', 'flops_per_device', 'flops_by_kind')

    def __init__(self):
        self.mesh = None
        self.n_devices = 1
        self.batch = 1
        self.residency_per_device = 0
        self.residency_total = 0
        self.persistables = {}
        self.tables = {}
        self.activation_bytes = 0
        self.peak_temp_bytes = 0
        self.collectives = []
        self.comm_bytes_per_step = 0
        self.flops_per_step = 0
        self.flops_per_device = 0
        self.flops_by_kind = {}

    def to_dict(self):
        return {
            'mesh': dict(self.mesh) if self.mesh else None,
            'n_devices': self.n_devices, 'batch': self.batch,
            'residency_per_device': self.residency_per_device,
            'residency_total': self.residency_total,
            'persistables': self.persistables,
            'tables': self.tables,
            'activation_bytes': self.activation_bytes,
            'peak_temp_bytes': self.peak_temp_bytes,
            'collectives': self.collectives,
            'comm_bytes_per_step': self.comm_bytes_per_step,
            'flops_per_step': self.flops_per_step,
            'flops_per_device': self.flops_per_device,
            'flops_by_kind': self.flops_by_kind,
        }

    def summary(self):
        """The program_lint --cost text block."""
        mesh = ('x'.join('%s=%d' % kv for kv in self.mesh.items())
                if self.mesh else 'none')
        lines = [
            'cost model: mesh=%s devices=%d batch=%d' % (
                mesh, self.n_devices, self.batch),
            '  residency/device: %s (%d persistable(s); total %s)' % (
                _fmt_bytes(self.residency_per_device),
                len(self.persistables),
                _fmt_bytes(self.residency_total)),
        ]
        for name, t in sorted(self.tables.items()):
            lines.append(
                '    table %s: %dx%d %s, %s/device%s' % (
                    name, t['rows'], t['dim'], t['dtype'],
                    _fmt_bytes(t['bytes_per_device']),
                    ', all_to_all over %r' % t['dist_axis']
                    if t['dist_axis'] else ''))
        lines.append(
            '  activations: %s declared, peak-liveness temp %s' % (
                _fmt_bytes(self.activation_bytes),
                _fmt_bytes(self.peak_temp_bytes)))
        lines.append(
            '  collectives: %d/step, %s/device/step on the wire' % (
                len(self.collectives),
                _fmt_bytes(self.comm_bytes_per_step)))
        for c in self.collectives:
            lines.append('    %s over %r by %s: %s' % (
                c['kind'], c['axis'], c['op_type'],
                _fmt_bytes(c['bytes_per_device'])))
        lines.append('  flops/step: %.3g (%.3g/device)' % (
            self.flops_per_step, self.flops_per_device))
        return '\n'.join(lines)


def _fmt_bytes(n):
    for unit in ('B', 'KiB', 'MiB', 'GiB'):
        if abs(n) < 1024 or unit == 'GiB':
            return ('%d%s' % (n, unit) if unit == 'B'
                    else '%.1f%s' % (n, unit))
        n /= 1024.0
    return '%dB' % n


def cost_report(program, mesh_axes=None, batch=1, feeds=None,
                fetches=None):
    """Compute the CostReport for `program` (pure — the program is
    never mutated). mesh_axes overrides the program's set_mesh spec
    (program_lint --mesh); batch resolves dynamic (-1) dims; fetches
    extends activation liveness to the block end. Records the
    `analysis.cost` obs span."""
    with obs.span('analysis.cost') as sp:
        rep = _cost_report(program, mesh_axes=mesh_axes, batch=batch,
                           feeds=feeds, fetches=fetches)
        sp.fields['residency_per_device'] = rep.residency_per_device
        sp.fields['comm_bytes_per_step'] = rep.comm_bytes_per_step
        sp.fields['collectives'] = len(rep.collectives)
    return rep


def _cost_report(program, mesh_axes=None, batch=1, feeds=None,
                 fetches=None):
    axes = _collectives.resolve_axes(program, mesh_axes)
    rep = CostReport()
    rep.mesh = axes
    rep.batch = int(batch)
    n_dev = 1
    for s in (axes or {}).values():
        n_dev *= int(s)
    rep.n_devices = n_dev

    # -- residency: every persistable at its per-device width ------------
    quant = _quant_widths(program)
    tables = _tables(program)
    seen = set()
    for v in program.list_vars():
        if not getattr(v, 'persistable', False) or v.name in seen:
            continue
        seen.add(v.name)
        if v.name in quant:
            q_item, scale_b = quant[v.name]
            elems = _elems(v.shape, batch)
            spec = getattr(v, 'sharding', None)
            full = _elems(v.shape, batch) * _itemsize(v.dtype)
            shard = var_bytes(v, axes, batch)
            # shard the int8 elems the way the f32 var is annotated
            b = (elems * q_item * shard // full if full else 0) + scale_b
            qmark = True
        else:
            b = var_bytes(v, axes, batch)
            qmark = False
        rep.residency_per_device += b
        rep.persistables[v.name] = {
            'shape': list(v.shape) if v.shape is not None else None,
            'dtype': v.dtype, 'bytes_per_device': b,
            'sharding': _jsonable_spec(getattr(v, 'sharding', None)),
            'quant': qmark,
        }
        if v.name in tables and v.shape is not None and len(v.shape) >= 2:
            rep.tables[v.name] = {
                'rows': int(v.shape[0]), 'dim': int(v.shape[1]),
                'dtype': v.dtype, 'bytes_per_device': b,
                'sharding': _jsonable_spec(getattr(v, 'sharding', None)),
                'dist_axis': next((ax for _, ax in tables[v.name] if ax),
                                  None),
            }
    rep.residency_total = rep.residency_per_device * n_dev

    # -- activations: def/last-use intervals over the global block -------
    blk = program.global_block()
    fetch_names = set(fetches or ())
    try:
        live = live_mask(program, blk, fetch_names) if fetch_names \
            else [True] * len(blk.ops)
    except Exception:
        live = [True] * len(blk.ops)
    intervals = {}   # name -> [def_idx, last_use_idx, bytes]
    for i, op in enumerate(blk.ops):
        if not live[i]:
            continue
        try:
            reads = op_reads(program, op)
        except Exception:
            reads = set(op.input_arg_names)
        for n in reads:
            if n in intervals:
                intervals[n][1] = i
        for slot_vs in op.outputs.values():
            for v in slot_vs:
                if getattr(v, 'persistable', False) or \
                        getattr(v, 'is_data', False):
                    continue
                b = var_bytes(v, axes, batch)
                if v.name not in intervals:
                    intervals[v.name] = [i, i, b]
                else:
                    intervals[v.name][1] = i
    end = len(blk.ops) - 1
    for n in fetch_names:
        if n in intervals:
            intervals[n][1] = end
    rep.activation_bytes = sum(b for _, _, b in intervals.values())
    peak = 0
    for i in range(len(blk.ops)):
        here = sum(b for d, u, b in intervals.values() if d <= i <= u)
        peak = max(peak, here)
    rep.peak_temp_bytes = peak

    # -- flops -----------------------------------------------------------
    for i, op in enumerate(blk.ops):
        if not live[i]:
            continue
        f = _op_flops(op, batch)
        if f:
            rep.flops_per_step += f
            rep.flops_by_kind[op.type] = \
                rep.flops_by_kind.get(op.type, 0) + f
    rep.flops_per_device = (rep.flops_per_step // n_dev if n_dev > 1
                            else rep.flops_per_step)

    # -- collectives -------------------------------------------------------
    if axes:
        rep.collectives = _price_collectives(program, axes, batch, n_dev)
        rep.comm_bytes_per_step = sum(
            c['bytes_per_device'] for c in rep.collectives)
    return rep


def _jsonable_spec(spec):
    if spec is None:
        return None
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _price_collectives(program, axes, batch, n_dev):
    """Byte-priced entries for the statically-derived collective
    sequence (analysis.collectives shares the derivation)."""
    out = []
    seq = _collectives.collective_sequence(program, mesh_axes=axes)
    # lookup pairs price as (ids out, rows back) via wire_stats
    lookup_leg = {}
    for blk_idx, op_idx, op, kind, ax in seq:
        bytes_dev = 0
        t = op.type
        if t in ('lookup_table', 'quant_lookup_table'):
            leg = lookup_leg.get((blk_idx, op_idx), 0)
            lookup_leg[(blk_idx, op_idx)] = leg + 1
            bytes_dev = _lookup_wire_bytes(op, axes, ax, batch, leg)
        elif t == 'autodiff':
            bytes_dev = _grad_bytes(program, op, axes, batch)
        else:
            # activation exchange: the op's input payload, per device
            bytes_dev = sum(
                var_bytes(v, axes, batch)
                for vs in op.inputs.values() for v in vs)
        out.append({'block': blk_idx, 'op_index': op_idx,
                    'op_type': t, 'kind': kind, 'axis': ax,
                    'bytes_per_device': int(bytes_dev)})
    return out


def _lookup_wire_bytes(op, axes, ax, batch, leg):
    """One leg of the all_to_all lookup exchange, via the same
    wire_stats accounting the runtime obs event records
    (embedding/lookup.py)."""
    try:
        from ...embedding.lookup import wire_stats
    except Exception:
        return 0
    ws = op.inputs.get('W', [])
    ids = op.inputs.get('Ids', [])
    if not ws or not ids or ws[0].shape is None or ids[0].shape is None:
        return 0
    n_ids = _elems(ids[0].shape, batch)
    vocab, dim = int(ws[0].shape[0]), int(ws[0].shape[1])
    stats = wire_stats(n_ids, vocab, dim, int(axes.get(ax, 1)),
                       itemsize=_itemsize(ws[0].dtype))
    return stats['id_bytes_per_device'] if leg == 0 \
        else stats['row_bytes_per_device']


def _grad_bytes(program, op, axes, batch):
    """The dp all-reduce payload: every gradient's per-device bytes."""
    total = 0
    for v in op.outputs.get('Grads', []):
        total += var_bytes(v, axes, batch)
    if not total:
        blk = op.block
        for n in op.attrs.get('grad_names', ()) or ():
            v = blk.vars.get(n)
            if v is not None:
                total += var_bytes(v, axes, batch)
    return total


# -- the analyze() pass ----------------------------------------------------

def run_pass(program, mesh_axes=None, hbm_budget=None, batch=1,
             feeds=None, fetches=None):
    """ImplicitReshard findings (always — metadata only) plus
    HbmOverBudget when `hbm_budget` (bytes) is declared. Never raises:
    an un-priceable program reports what it can and stays quiet about
    the rest (the analyze() contract)."""
    findings = []
    axes = _collectives.resolve_axes(program, mesh_axes)

    # ImplicitReshard: the same-shaped value re-placed across one op —
    # GSPMD satisfies the transition with a hidden all-gather/all-to-all
    # at that edge (the resharding hotspot class)
    if axes:
        for blk in program.blocks:
            for op in blk.ops:
                ins = [v for vs in op.inputs.values() for v in vs
                       if getattr(v, 'sharding', None)]
                if not ins:
                    continue
                for vs in op.outputs.values():
                    for ov in vs:
                        osp = getattr(ov, 'sharding', None)
                        if not osp or ov.shape is None:
                            continue
                        for iv in ins:
                            if iv.shape != ov.shape or \
                                    tuple(iv.sharding) == tuple(osp):
                                continue
                            findings.append(Finding.for_op(
                                IMPLICIT_RESHARD, SEV_WARNING,
                                '%r is placed %r but flows into %r '
                                'placed %r: the transition lowers to a '
                                'hidden all-gather/all-to-all at this '
                                'edge (~%s on the wire) — annotate both '
                                'ends identically, or make the reshard '
                                'explicit where the cost is intended'
                                % (iv.name, tuple(iv.sharding), ov.name,
                                   tuple(osp),
                                   _fmt_bytes(var_bytes(
                                       iv, axes, batch))), op,
                                var_names=(iv.name, ov.name)))

    if hbm_budget is not None:
        try:
            rep = _cost_report(program, mesh_axes=mesh_axes, batch=batch,
                               feeds=feeds, fetches=fetches)
        except Exception:
            rep = None   # un-priceable artifact: no budget verdict
        if rep is not None and \
                rep.residency_per_device > int(hbm_budget):
            findings.append(Finding(
                HBM_OVER_BUDGET, SEV_ERROR,
                'per-device persistable residency %s exceeds the '
                'declared HBM budget %s by %s (mesh %s, %d device(s)) '
                '— shard more dims, quantize weights '
                '(passes.quant_pass), or spill cold rows to the host '
                'tier (embedding.TieredVocabTable)'
                % (_fmt_bytes(rep.residency_per_device),
                   _fmt_bytes(int(hbm_budget)),
                   _fmt_bytes(rep.residency_per_device
                              - int(hbm_budget)),
                   'x'.join('%s=%d' % kv for kv in (axes or {}).items())
                   or 'none', rep.n_devices),
                var_names=tuple(sorted(
                    rep.persistables,
                    key=lambda n: -rep.persistables[n]
                    ['bytes_per_device'])[:5])))
    return findings
