"""Pass 4 — scope-race detection for concurrent execution.

A Program that WRITES persistables is only safe to run from one thread at
a time against one scope: two concurrent steps would race on the shared
parameter buffers (and, since a mutating step donates them, one step's
write invalidates the buffer the other step is still reading — worse than
a stale read). The serving engine and multi-threaded `Predictor`s run
read-only programs by construction; this pass is the build-time guard
that keeps it that way.

The pass only fires when the caller declares the program WILL run
concurrently over a shared scope (`analyze(..., concurrent=True)` — the
serving/Predictor wiring passes it; a single-threaded trainer does not),
so ordinary training programs report zero findings.
"""
from .donation import persistable_write_set, executor_write_set
from .findings import Finding, SEV_ERROR, SCOPE_RACE

__all__ = ['run_pass']


def run_pass(program, concurrent=False):
    if not concurrent:
        return []
    writes = persistable_write_set(program, recursive=True)
    if not writes:
        return []
    donating = bool(executor_write_set(program))
    return [Finding(
        SCOPE_RACE, SEV_ERROR,
        'program writes persistable(s) %r but is declared to run '
        'CONCURRENTLY over a shared scope — steps would race on the '
        'parameter buffers%s; serve a clone(for_test=True)-pruned '
        'inference program, or give each runner a private scope'
        % (sorted(writes),
           ' (and the mutating step donates them, so a concurrent reader '
           'sees invalidated memory)' if donating else ''),
        var_names=sorted(writes))]
