"""Pass 7 — collective-safety lint (docs/analysis.md#pass-7).

Which ops lower to collectives is statically knowable from the lowering
rules plus the program's annotations: a `lookup_table` with
`is_distributed` and a `dist_axis` the mesh declares takes the
all_to_all wire (ops_impl/embedding_ops.dist_lookup_applies), `moe_mlp`
rides two all_to_alls when the dp axis divides num_experts
(ops_impl/moe_ops), `flash_attention` ppermutes K/V around the ring
when an 'sp' axis exists (ops_impl/nn_ops), and `autodiff` under a mesh
with a data axis implies the GSPMD gradient all-reduce. This pass
derives each block's collective sequence from exactly those conditions
— no jax import, no device — and flags the two hazard classes the
runtime today only survives, not prevents:

  * CollectiveDivergence — a collective under divergent control flow.
    A cond/switch body issuing a collective on one branch only is the
    rendezvous-hang class: devices that take different branches never
    meet at the rendezvous (error). A collective inside a While body is
    the same hazard one remove away — safe only while every device
    runs the same trip count (warning).
  * ConcurrentCollectives — a program declared `concurrent=True`
    (the serving posture: ShardedPredictor verifies with it) that
    issues collectives. Two co-hosted modules interleaving collectives
    on shared devices would pair rendezvous participants across
    modules and deadlock; today that is survived only by the silent
    process-wide `_MESH_DISPATCH_LOCK` in serving/pod.py — the finding
    names the hazard and points at the lock (warning: the lock DOES
    serialize, so the program runs; the lint makes the dependence on
    it visible).

`collective_sequence(program, mesh_axes=)` is the shared derivation the
cost model (analysis/costmodel.py) prices for wire bytes.
"""
from .dataflow import sub_block_indices
from .findings import (COLLECTIVE_DIVERGENCE, CONCURRENT_COLLECTIVES,
                       Finding, SEV_ERROR, SEV_WARNING)

__all__ = ['run_pass', 'collective_sequence', 'op_collectives']


def resolve_axes(program, mesh_axes=None):
    """The mesh spec the pass judges against: the override (program_lint
    --mesh) or the program's own set_mesh() spec, as a plain dict or
    None."""
    if mesh_axes is not None:
        return dict(mesh_axes)
    items = getattr(program, '_mesh_axes', None)
    return dict(items) if items else None


def _data_axis(program, axes):
    """The axis feed batches (and therefore dp gradients) shard over:
    the program's declared data_axis when it is in `axes`, else the
    'dp'/'data' default set_mesh would derive."""
    da = getattr(program, '_mesh_data_axis', None)
    if da and da in axes:
        return da
    for cand in ('dp', 'data'):
        if cand in axes:
            return cand
    return None


def op_collectives(op, program, axes):
    """[(kind, axis)] collectives this op's lowering issues under mesh
    `axes` — the static mirror of the per-op mesh conditions in
    ops_impl/. Empty for ops that lower collective-free."""
    if not axes:
        return []
    t = op.type
    if t in ('lookup_table', 'quant_lookup_table'):
        ax = op.attrs.get('dist_axis')
        if op.attrs.get('is_distributed') and ax in axes:
            # the two-direction exchange: ids out, rows back
            return [('all_to_all', ax), ('all_to_all', ax)]
        return []
    if t == 'moe_mlp':
        try:
            n_exp = int(op.attrs.get('num_experts', 0))
        except (TypeError, ValueError):
            return []
        if 'dp' in axes and n_exp and n_exp % axes['dp'] == 0:
            # dispatch + combine
            return [('all_to_all', 'dp'), ('all_to_all', 'dp')]
        return []
    if t == 'flash_attention':
        if 'sp' in axes:
            return [('ppermute', 'sp')]
        return []
    if t == 'autodiff':
        ax = _data_axis(program, axes)
        if ax is not None:
            return [('all_reduce', ax)]
        return []
    return []


def collective_sequence(program, mesh_axes=None, block=None, _seen=None):
    """The statically-derived collective sequence of `block` (default:
    the global block), sub-blocks included, in program order:
    [(block_idx, op_index, op, kind, axis)]."""
    axes = resolve_axes(program, mesh_axes)
    if not axes:
        return []
    if block is None:
        block = program.global_block()
    if _seen is None:
        _seen = set()
    if block.idx in _seen:
        return []
    _seen = _seen | {block.idx}
    seq = []
    for i, op in enumerate(block.ops):
        for kind, ax in op_collectives(op, program, axes):
            seq.append((block.idx, i, op, kind, ax))
        for bi in sub_block_indices(op, program):
            if bi not in _seen:
                seq += collective_sequence(program, mesh_axes,
                                           program.block(bi), _seen)
    return seq


def _block_collectives(program, block, axes, _seen=None):
    """[(op, kind, axis)] issued anywhere under `block` (recursive)."""
    if _seen is None:
        _seen = set()
    if block.idx in _seen:
        return []
    _seen = _seen | {block.idx}
    out = []
    for op in block.ops:
        for kind, ax in op_collectives(op, program, axes):
            out.append((op, kind, ax))
        for bi in sub_block_indices(op, program):
            out += _block_collectives(program, program.block(bi), axes,
                                      _seen)
    return out


def _describe(colls):
    return ', '.join(sorted({'%s(%s) by %s' % (kind, ax, op.type)
                             for op, kind, ax in colls}))


def run_pass(program, concurrent=False, mesh_axes=None):
    """See analysis.analyze for concurrent/mesh_axes. Returns
    [Finding]; empty when the program declares no mesh — without one
    every op lowers collective-free."""
    axes = resolve_axes(program, mesh_axes)
    if not axes:
        return []
    findings = []

    # divergence: collectives inside control-flow bodies
    for blk in program.blocks:
        for op in blk.ops:
            sub_idxs = sub_block_indices(op, program)
            if not sub_idxs:
                continue
            per_branch = [_block_collectives(program, program.block(bi),
                                             axes) for bi in sub_idxs]
            if not any(per_branch):
                continue
            if op.type == 'while':
                colls = [c for branch in per_branch for c in branch]
                findings.append(Finding.for_op(
                    COLLECTIVE_DIVERGENCE, SEV_WARNING,
                    'While body issues collective(s) [%s]: safe only '
                    'while every device runs the SAME trip count — a '
                    'divergent condition strands part of the mesh at '
                    'the rendezvous (hang, not error)'
                    % _describe(colls), op,
                    var_names=sorted({o.input_arg_names[0]
                                      for o, _, _ in colls
                                      if o.input_arg_names})))
            else:
                # ifelse/switch: a branch-only collective is the
                # rendezvous-hang class even with every branch listed —
                # branches are mutually exclusive per device, and an
                # implicit else (fewer collectives on one path) is the
                # same divergence
                if not all(per_branch) or len(per_branch) < 2 or \
                        len({tuple((k, a) for _, k, a in b)
                             for b in per_branch}) > 1:
                    colls = [c for branch in per_branch for c in branch]
                    findings.append(Finding.for_op(
                        COLLECTIVE_DIVERGENCE, SEV_ERROR,
                        '%s issues collective(s) [%s] on one branch '
                        'only: devices taking the other branch never '
                        'reach the rendezvous and the mesh hangs — '
                        'hoist the collective out of the conditional '
                        'or issue a matching collective on every '
                        'branch' % (op.type, _describe(colls)), op))

    # concurrency: a concurrent-declared program issuing collectives at
    # all leans on serving/pod.py's process-wide _MESH_DISPATCH_LOCK
    if concurrent:
        top = [(op, kind, ax)
               for _, _, op, kind, ax in collective_sequence(
                   program, mesh_axes)]
        if top:
            findings.append(Finding(
                CONCURRENT_COLLECTIVES, SEV_WARNING,
                'program is declared to run CONCURRENTLY and issues '
                'collective(s) [%s]: two modules interleaving '
                'collectives on shared devices pair rendezvous '
                'participants across modules and deadlock — today this '
                'is survived only by the process-wide '
                '_MESH_DISPATCH_LOCK in paddle_tpu/serving/pod.py '
                '(co-hosted sharded replicas serialize their '
                'dispatches); keep dispatches behind that lock, or '
                'give each program its own devices' % _describe(top),
                var_names=sorted({op.inputs.get('W', [None])[0].name
                                  for op, _, _ in top
                                  if op.inputs.get('W')})))
    return findings
