"""Pass 1 — dataflow / def-use over Program blocks.

Checks (docs/analysis.md):
  * DanglingInput    — an op reads a name nothing defines at its position
                       (not a feed, not an initialized persistable, not an
                       earlier op's output);
  * WriteToFeed      — an op output overwrites a feed variable;
  * UnreachableFetch — a fetch target nothing in the program defines;
  * DeadOp           — (only when the fetch set is known) an op whose
                       outputs reach no fetch and no persistable write —
                       XLA DCEs it, so warning severity;
  * UseBeforeWrite   — a persistable read before any write that the given
                       startup program never initializes (under
                       run_bundle's scan carry this is the
                       "persistable output has no value in the scope yet"
                       rejection, surfaced at build time).

The env model mirrors the executor exactly: at step entry env holds the
feed dict plus every scope-initialized persistable; ops then bind outputs
in order (lowering.run_op raises KeyError on a missing input — this pass
is that error, ahead of time and with provenance).

Sub-blocks (while/ifelse/switch/static_rnn/dynamic_rnn bodies) are walked
with an ORDER-INSENSITIVE definition set: a loop body may legally read a
carry written later in the body (the value arrives from the previous
iteration), so inside a sub-block only names written nowhere at all count
as dangling.
"""
from ..framework import Parameter
from .findings import (Finding, SEV_ERROR, SEV_WARNING, DANGLING_INPUT,
                       WRITE_TO_FEED, DEAD_OP, UNREACHABLE_FETCH,
                       USE_BEFORE_WRITE)

__all__ = ['run_pass', 'sub_block_indices', 'op_reads', 'op_writes',
           'live_mask']


def sub_block_indices(op, program=None):
    """Block indices an op executes as its body/bodies (while, ifelse,
    switch, static_rnn, dynamic_rnn — anything carrying the standard
    sub_block/sub_blocks attrs). With `program` given, out-of-range
    indices are dropped — a corrupted artifact (program_lint feeds
    untrusted __model__.json) must produce findings, not IndexErrors."""
    idxs = []
    sb = op.attrs.get('sub_block')
    if isinstance(sb, int):
        idxs.append(sb)
    sbs = op.attrs.get('sub_blocks')
    if isinstance(sbs, (list, tuple)):
        # non-int entries (corrupted artifact) are dropped, not cast:
        # analyze() must survive adversarial attrs, never TypeError
        idxs.extend(b for b in sbs if isinstance(b, int))
    if program is not None:
        idxs = [b for b in idxs if 0 < b < program.num_blocks]
    return idxs


def _block_writes(program, block, seen=None, cache=None):
    """Every name written anywhere in `block` or its nested sub-blocks.
    `cache` (block idx -> frozen result, one dict per analyze() run —
    blocks are immutable during an analysis) is consulted/populated only
    for top-level entries: a mid-cycle partial result must not stick."""
    top = seen is None
    if top:
        if cache is not None and block.idx in cache:
            return cache[block.idx]
        seen = set()
    if block.idx in seen:
        return set()
    seen.add(block.idx)
    writes = set()
    for op in block.ops:
        writes.update(op.output_arg_names)
        for bi in sub_block_indices(op, program):
            writes |= _block_writes(program, program.block(bi), seen)
    if top and cache is not None:
        cache[block.idx] = writes
    return writes


def op_reads(program, op, _seen=None, cache=None):
    """Names an op consumes, including names its sub-blocks read that the
    sub-blocks themselves never define (i.e. reads of OUTER values). The
    `_seen` block-index set guards against cyclic sub_block attrs in
    hand-built or corrupted programs; `cache` memoizes _block_writes
    across the many per-op calls one analysis makes."""
    if _seen is None:
        _seen = {op.block.idx}
    reads = set(op.input_arg_names)
    if op.type == 'while':
        # loop carries must hold a value BEFORE the loop (the While rule
        # raises otherwise); they are outputs, but also reads
        reads.update(op.output_arg_names)
    for bi in sub_block_indices(op, program):
        if bi in _seen:
            continue
        _seen.add(bi)
        sub = program.block(bi)
        local = _block_writes(program, sub, cache=cache)
        for sop in sub.ops:
            reads.update(n for n in op_reads(program, sop, _seen, cache)
                         if n not in local)
    return reads


def op_writes(op):
    return set(op.output_arg_names)


def _walk_block(program, block, defined, feed_names, findings,
                order_insensitive=False, seen_blocks=None, cache=None):
    """Walk a block's ops against the running `defined` set (mutated in
    place), recursing into sub-blocks. Returns nothing; findings append.
    `seen_blocks` guards the recursion against cyclic sub_block attrs."""
    if seen_blocks is None:
        seen_blocks = set()
    seen_blocks = seen_blocks | {block.idx}
    local_pool = (_block_writes(program, block, cache=cache)
                  if order_insensitive else None)
    for i, op in enumerate(block.ops):
        if op.type == 'autodiff':
            # defines every @GRAD var from the traced forward; its only
            # true read is the loss
            loss = op.attrs.get('loss_name')
            if loss and loss not in defined:
                findings.append(Finding.for_op(
                    DANGLING_INPUT, SEV_ERROR,
                    'autodiff differentiates loss %r which nothing '
                    'defines' % loss, op, var_names=(loss,)))
            defined.update(op.output_arg_names)
            defined.update(op.attrs.get('grad_names', ()))
            continue
        for slot, vs in op.inputs.items():
            for v in vs:
                n = v.name
                if n in defined:
                    continue
                if order_insensitive and n in local_pool:
                    continue
                findings.append(Finding.for_op(
                    DANGLING_INPUT, SEV_ERROR,
                    'input %r (slot %r) is read but never defined: not a '
                    'feed, not an initialized persistable, and no earlier '
                    'op writes it' % (n, slot), op, var_names=(n,)))
                defined.add(n)   # report each dangling name once
        if op.type == 'while':
            missing = [n for n in op.output_arg_names if n not in defined]
            for n in missing:
                findings.append(Finding.for_op(
                    DANGLING_INPUT, SEV_ERROR,
                    'While carry %r has no value before the loop — write '
                    'it (fill_constant / array_write) first so its shape '
                    'is known' % n, op, var_names=(n,)))
                defined.add(n)
        for bi in sub_block_indices(op, program):
            if bi in seen_blocks:
                continue
            sub = program.block(bi)
            sub_defined = set(defined)
            _walk_block(program, sub, sub_defined, feed_names, findings,
                        order_insensitive=True, seen_blocks=seen_blocks,
                        cache=cache)
        for n in op_writes(op):
            # feed_names is the caller's EXACT feed set when given (an
            # unfed data var is an ordinary intermediate), else every
            # declared data var (standalone mode)
            if n in feed_names:
                findings.append(Finding.for_op(
                    WRITE_TO_FEED, SEV_ERROR,
                    'op overwrites feed variable %r — feeds are step '
                    'inputs, not scratch space' % n, op, var_names=(n,)))
            defined.add(n)


def live_mask(program, block, fetch_names, cache=None, keep=None):
    """Backward liveness over `block`: live[i] is True when op i's outputs
    transitively reach a fetch or a persistable write — including
    persistable writes that happen only inside the op's sub-blocks (a
    While body updating a counter is live even when its carries are not
    fetched). Shared by the DeadOp finding below and the dead-op
    ELIMINATION transform (fluid.passes.dce), so the verifier's warning
    and the optimizer's pruning can never disagree.

    keep — optional predicate forcing ops live regardless of dataflow
    (DCE passes its keep-effectful rule here, so a retained `print` op's
    PRODUCERS stay live too; the backward walk propagates its reads like
    any other live op's)."""
    if cache is None:
        cache = {}
    persistables = {v.name for v in program.list_vars() if v.persistable}
    needed = set(fetch_names)
    live = [False] * len(block.ops)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        writes = op_writes(op)
        writes_persist = any(
            getattr(v, 'persistable', False)
            for vs in op.outputs.values() for v in vs)
        if not writes_persist:
            writes_persist = any(
                _block_writes(program, program.block(bi), cache=cache)
                & persistables
                for bi in sub_block_indices(op, program))
        if op.type == 'autodiff':
            # live iff any of its grads feed a live consumer
            if writes & needed:
                live[i] = True
                needed.add(op.attrs.get('loss_name', ''))
                needed.update(op.input_arg_names)
            continue
        forced = keep is not None and keep(op)
        if forced or writes_persist or (writes & needed):
            live[i] = True
            needed.update(op_reads(program, op, cache=cache))
    return live


def _liveness(program, block, fetch_names, findings, cache=None):
    """DeadOp findings from live_mask: dead ops are warnings (XLA drops
    them; they still cost trace time)."""
    live = live_mask(program, block, fetch_names, cache=cache)
    for i, op in enumerate(block.ops):
        if not live[i]:
            findings.append(Finding.for_op(
                DEAD_OP, SEV_WARNING,
                'outputs %r reach no fetch and write no persistable — the '
                'op is dead for this fetch list'
                % sorted(op_writes(op)), op))


def run_pass(program, feeds=None, fetches=None, initialized=None,
             startup=None, bundle=False, dead_ops=True):
    """Run the dataflow pass. See analysis.analyze for the contract of
    feeds/fetches/initialized/startup/bundle. dead_ops=False skips the
    DeadOp liveness check (the executor wiring: one run's fetch subset is
    not evidence an op is dead — another call may fetch it)."""
    findings = []
    block = program.global_block()
    cache = {}   # per-analysis _block_writes memo (blocks are immutable)
    persistables = {v.name for v in program.list_vars() if v.persistable}

    if initialized is not None:
        defined = set(initialized)
    else:
        # standalone mode: assume every declared data var may be fed and
        # every persistable was initialized (startup ran)
        defined = {v.name for v in program.list_vars()
                   if getattr(v, 'is_data', False)}
        defined |= persistables
    feed_names = set(feeds) if feeds is not None else {
        v.name for v in program.list_vars() if getattr(v, 'is_data', False)}
    defined |= feed_names

    # UseBeforeWrite: a persistable read before any program write, that the
    # startup program never initializes. Needs the startup program to judge
    # — without it "uninitialized" is unknowable and the check stays quiet.
    if startup is not None:
        started = _block_writes(startup, startup.global_block())
        started |= {v.name for v in startup.list_vars()
                    if isinstance(v, Parameter)}
        written = set()
        flagged = set()
        for op in block.ops:
            if op.type == 'autodiff':
                written.update(op.attrs.get('grad_names', ()))
                continue
            for n in op_reads(program, op, cache=cache):
                if (n in persistables and n not in written
                        and n not in started and n not in feed_names
                        and n not in flagged):
                    flagged.add(n)
                    findings.append(Finding.for_op(
                        USE_BEFORE_WRITE, SEV_ERROR,
                        'persistable %r is read before any write and the '
                        'startup program never initializes it' % n, op,
                        var_names=(n,)))
            written.update(op_writes(op))

    # run_bundle's scan carry needs every written persistable to already
    # hold a scope value (executor.run_bundle raises otherwise); with scope
    # knowledge (initialized) this surfaces at verify time instead
    if bundle and initialized is not None:
        written_persist = {n for op in block.ops
                           for n in op_writes(op) if n in persistables}
        gap = sorted(written_persist - set(initialized))
        if gap:
            findings.append(Finding(
                USE_BEFORE_WRITE, SEV_ERROR,
                'persistable output(s) %r have no value in the scope, so '
                'they cannot thread through run_bundle\'s scan carry — run '
                'the startup program (or one unbundled step) first' % gap,
                var_names=gap))

    _walk_block(program, block, defined, feed_names, findings, cache=cache)

    if fetches is not None:
        produced = set(defined)
        for n in fetches:
            if n not in produced:
                findings.append(Finding(
                    UNREACHABLE_FETCH, SEV_ERROR,
                    'fetch target %r: no op produces it, it is not fed, '
                    'and no initialized persistable carries it' % n,
                    var_names=(n,)))
        if dead_ops:
            _liveness(program, block, set(fetches), findings, cache=cache)
    return findings
