"""Pass 3 — donation / aliasing safety.

The executor donates every persistable input buffer to the jitted step
when (and only when) the program's TOP-LEVEL ops write at least one
persistable (executor._CompiledStep): a mutating step updates params in
place in HBM and re-exposes every donated input as an output; a read-only
step donates nothing, because donation would invalidate the param buffers
under concurrent runs over a shared scope (the PR-3 serving bug).

This pass recomputes the persistable write-set INDEPENDENTLY — including
sub-block writes the executor's top-level scan cannot see — and verifies
it against the executor's donation decision:

  * DonationUnsafe (donates but write-set empty): a read-only step whose
    buffers would be invalidated — exactly the PR-3 class;
  * DonationUnsafe (writes but no donation/write-back): persistable
    updates the executor would silently drop;
  * DonationUnsafe (sub-block-only writes): a persistable written ONLY
    inside a sub-block — the executor's decision scan reads top-level
    outputs, so the step is treated read-only and the update is lost.
"""
from .dataflow import sub_block_indices
from .findings import Finding, SEV_ERROR, DONATION_UNSAFE

__all__ = ['run_pass', 'persistable_write_set', 'executor_write_set',
           'executor_donates']


def executor_write_set(program):
    """Persistable names the TOP-LEVEL block writes — byte-for-byte the
    scan executor._CompiledStep bases its donation decision on (defined
    here so the executor and the analyzer can never drift apart)."""
    persistable = {v.name for v in program.list_vars() if v.persistable}
    produced = set()
    for op in program.global_block().ops:
        for vs in op.outputs.values():
            for v in vs:
                if v.name in persistable:
                    produced.add(v.name)
    return produced


def executor_donates(program):
    """The executor's donation decision for this program (True = every
    persistable input buffer is donated to the jitted step)."""
    return bool(executor_write_set(program))


def _reachable_sub_blocks(program):
    """Sub-block indices actually executed by some (transitively
    reachable) block op. Orphaned blocks — prune()/clone(for_test) drop
    ops but keep every Block, so a pruned inference program can carry a
    dead While body — must not contribute writes: they never run."""
    reachable = set()
    frontier = [program.global_block().idx]
    seen = {program.global_block().idx}
    while frontier:
        bi = frontier.pop()
        for op in program.block(bi).ops:
            for nbi in sub_block_indices(op, program):
                if nbi not in seen:
                    seen.add(nbi)
                    reachable.add(nbi)
                    frontier.append(nbi)
    return reachable


def persistable_write_set(program, recursive=True):
    """Persistable names written anywhere in the REACHABLE program; with
    recursive=True this includes executed sub-block bodies (which the
    executor's top-level scan does NOT see — that gap is finding
    material), but never orphaned blocks left behind by prune(). The
    top-level scan is executor_write_set itself — one definition, no
    drift."""
    writes = set(executor_write_set(program))
    if recursive:
        for bi in sorted(_reachable_sub_blocks(program)):
            for op in program.block(bi).ops:
                for vs in op.outputs.values():
                    for v in vs:
                        if getattr(v, 'persistable', False):
                            writes.add(v.name)
    return writes


def _sub_block_only_writers(program):
    """(op, name) pairs for persistable writes that happen ONLY inside a
    sub-block, attributed to the sub-block op that performs them."""
    top = executor_write_set(program)
    hits = []
    for bi in sorted(_reachable_sub_blocks(program)):
        for op in program.block(bi).ops:
            for vs in op.outputs.values():
                for v in vs:
                    if getattr(v, 'persistable', False) and v.name not in top:
                        hits.append((op, v.name))
    return hits


def run_pass(program, donates=None):
    """donates: the executor's actual donation decision for the step about
    to run (compiled.mutates_persist). None = standalone analysis; the
    decision is re-derived from the executor's own rule, so only the
    sub-block gap can fire."""
    findings = []
    top_writes = executor_write_set(program)
    if donates is None:
        donates = bool(top_writes)

    if donates and not top_writes:
        findings.append(Finding(
            DONATION_UNSAFE, SEV_ERROR,
            'the step donates its persistable input buffers but no op '
            'writes any persistable — donation would invalidate parameter '
            'buffers under concurrent runs over a shared scope (read-only '
            'inference steps must not donate)', var_names=()))
    if not donates and top_writes:
        findings.append(Finding(
            DONATION_UNSAFE, SEV_ERROR,
            'ops write persistable(s) %r but the step neither donates nor '
            'writes back persistables — the updates would be dropped'
            % sorted(top_writes), var_names=sorted(top_writes)))

    for op, name in _sub_block_only_writers(program):
        findings.append(Finding.for_op(
            DONATION_UNSAFE, SEV_ERROR,
            'persistable %r is written only inside a sub-block; the '
            'executor\'s donation/write-back decision scans top-level '
            'outputs, so this update never reaches the scope — stage the '
            'write through a loop carry and assign it at the top level'
            % name, op, var_names=(name,)))
    return findings
