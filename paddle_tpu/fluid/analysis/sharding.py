"""Pass 5 — GSPMD sharding-annotation consistency (docs/parallel.md).

The annotation surface (`Program.set_mesh` + per-tensor
`ParamAttr(sharding=...)`/`Variable.sharding`) is declared at build time
but only CONSUMED at lowering, where a bad spec degrades into a runtime
warning-and-replicate (or an XLA error deep inside jit). This pass is the
ahead-of-lowering check, the same posture as donation safety: every
annotation is validated against the mesh spec statically and reported as
a structured Finding with the producer op's build-site provenance.

Checks:
  * ShardingInvalid  — an annotation names a mesh axis the spec does not
                       declare, uses one axis twice in a spec, or has
                       more entries than the tensor has dims; also (as a
                       warning) annotations on a Program with NO mesh
                       spec at all — they are inert until set_mesh().
  * ShardingUntileable — a statically-known dim is not divisible by the
                       product of the axis sizes assigned to it: the
                       mesh cannot tile the var, and the executor would
                       fall back to replicating it (forfeiting the
                       memory/compute scaling the annotation asked for).
                       Dynamic (-1) dims are skipped — the feed's batch
                       divisibility is a runtime check.
  * ShardingReshard  — resharding implied mid-pipeline: in a
                       pipeline-transpiled program, stage k's copy of a
                       stacked parameter carries a different spec than
                       stage 0's, so the per-stage weight stack would
                       transition layouts between stages — exactly the
                       involuntary-rematerialization class the executor's
                       consistent in/out shardings exist to prevent.

The pass only inspects metadata (no jax import) and never mutates the
program. `mesh_axes` overrides the program's own spec — that is how
`tools/program_lint.py --mesh dpx8,tpx2` lints a saved artifact against a
deployment mesh it was not annotated with.
"""
from .findings import (DIM_SHARDING, EMBEDDING_UNTILEABLE, Finding,
                       SEV_ERROR, SEV_WARNING, SHARDING_INVALID,
                       SHARDING_RESHARD, SHARDING_UNTILEABLE)

__all__ = ['run_pass']


def _embedding_tables(program):
    """Table name -> [lookup_table op] map: vars read through the 'W'
    slot of a lookup_table anywhere in the program. An untileable
    annotation on one of THESE is the EmbeddingShardUntileable class —
    the huge-vocab tensor the sharded-embedding subsystem exists for
    (docs/embedding.md), where the actionable fix is padding the vocab."""
    tables = {}
    for blk in program.blocks:
        for op in blk.ops:
            if op.type != 'lookup_table':
                continue
            for v in op.inputs.get('W', []):
                tables.setdefault(v.name, []).append(op)
    return tables


def _annotated_vars(program):
    seen = set()
    for blk in program.blocks:
        for v in blk.vars.values():
            spec = getattr(v, 'sharding', None)
            if spec and v.name not in seen:
                seen.add(v.name)
                yield v


def _var_finding(kind, sev, msg, v):
    """Finding anchored on an annotated Variable: provenance is the
    layer call that declared the annotation (captured at Variable build,
    since parameters have no producer op in the main program), falling
    back to the producer op's build site."""
    op = getattr(v, 'op', None)
    callsite = getattr(v, '_annot_callsite', None) \
        or getattr(op, 'callsite', None)
    return Finding(kind, sev, msg, var_names=(v.name,),
                   op_type=getattr(op, 'type', None),
                   callsite=callsite)


def _axes_of_entry(entry):
    return entry if isinstance(entry, tuple) else (entry,)


def run_pass(program, mesh_axes=None):
    """mesh_axes: {'dp': 8}-style override (program_lint --mesh); None
    uses the program's own set_mesh() spec. Returns [Finding]."""
    findings = []
    if mesh_axes is None:
        axes_items = getattr(program, '_mesh_axes', None)
        axes = dict(axes_items) if axes_items else None
    else:
        axes = dict(mesh_axes)

    annotated = list(_annotated_vars(program))
    emb_tables = _embedding_tables(program)
    if axes is None:
        for v in annotated:
            findings.append(_var_finding(
                SHARDING_INVALID, SEV_WARNING,
                'sharding annotation %r on %r but the program declares no '
                'mesh (Program.set_mesh) — the annotation is inert and '
                'the var will not be sharded' % (v.sharding, v.name), v))
        return findings

    for v in annotated:
        spec = v.sharding
        # a TIER-BACKED table (Variable.tiered — embedding/tiers.py
        # stamps it, and the mark survives the artifact round-trip)
        # whose spec shards any dim past the vocab dim: spills gather
        # WHOLE rows, so a dim sharding would tear rows across hosts.
        # The static twin of tiers.validate_program's runtime
        # DimShardingUnsupported raise (which stays as the backstop).
        if getattr(v, 'tiered', False) and \
                any(ax is not None for ax in tuple(spec)[1:]):
            findings.append(_var_finding(
                DIM_SHARDING, SEV_ERROR,
                'tiered table %r shards its EMBEDDING dim (sharding=%r) '
                '— the host-RAM tier store spills/restores WHOLE rows, '
                'so a dim sharding would tear rows across hosts. Column '
                'sharding for D > HBM is ROADMAP item 3; row-shard the '
                'table (e.g. sharding=(%r, None)) instead'
                % (v.name, tuple(spec),
                   tuple(spec)[1] if len(spec) > 1 else 'model'), v))
        ndim = len(v.shape) if v.shape is not None else None
        if ndim is not None and len(spec) > ndim:
            findings.append(_var_finding(
                SHARDING_INVALID, SEV_ERROR,
                'sharding annotation %r on %r has %d entries but the var '
                'is %d-dimensional' % (spec, v.name, len(spec), ndim), v))
            continue
        used = set()
        bad = False
        for entry in spec:
            if entry is None:
                continue
            for ax in _axes_of_entry(entry):
                if ax not in axes:
                    findings.append(_var_finding(
                        SHARDING_INVALID, SEV_ERROR,
                        'sharding annotation %r on %r names mesh axis %r '
                        'but the mesh declares only %r'
                        % (spec, v.name, ax, sorted(axes)), v))
                    bad = True
                elif ax in used:
                    findings.append(_var_finding(
                        SHARDING_INVALID, SEV_ERROR,
                        'sharding annotation %r on %r uses mesh axis %r '
                        'on more than one dim' % (spec, v.name, ax), v))
                    bad = True
                used.add(ax)
        if bad or v.shape is None:
            continue
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            dim = v.shape[d]
            if dim < 0:
                continue   # dynamic batch dim: runtime divisibility check
            tile = 1
            for ax in _axes_of_entry(entry):
                tile *= axes[ax]
            if dim % tile:
                if d == 0 and v.name in emb_tables:
                    # untileable VOCAB dim of a lookup table: the
                    # embedding-specific class, same provenance plumbing
                    # (the annotating layer call via _annot_callsite),
                    # plus the lookup op(s) that make it a table and the
                    # concrete fix
                    ops = emb_tables[v.name]
                    dist = any(o.attrs.get('is_distributed')
                               for o in ops)
                    findings.append(_var_finding(
                        EMBEDDING_UNTILEABLE, SEV_ERROR,
                        'embedding table %r (read by %d lookup_table '
                        'op%s%s) is row-sharded %r but its vocab dim %d '
                        'is not divisible by the assigned mesh extent '
                        '%d (%s) — the executor would replicate the one '
                        'tensor the annotation exists to shard; pad the '
                        'vocab to a multiple (paddle_tpu.embedding.'
                        'pad_vocab) or resize the axis'
                        % (v.name, len(ops), 's' if len(ops) > 1 else '',
                           ', is_distributed=True' if dist else '',
                           spec, dim, tile,
                           'x'.join('%s=%d' % (ax, axes[ax])
                                    for ax in _axes_of_entry(entry))),
                        v))
                    continue
                findings.append(_var_finding(
                    SHARDING_UNTILEABLE, SEV_ERROR,
                    'sharding annotation %r on %r: dim %d of size %d is '
                    'not divisible by the assigned mesh extent %d (%s) — '
                    'the mesh cannot tile it and the executor would '
                    'replicate instead'
                    % (spec, v.name, d, dim, tile,
                       'x'.join('%s=%d' % (ax, axes[ax])
                                for ax in _axes_of_entry(entry))), v))

    # mid-pipeline consistency: a pipeline-transpiled program stacks the
    # per-stage copies of each parameter into ONE tensor — stage copies
    # whose annotations disagree would force a layout transition between
    # stages (the MULTICHIP_r05 involuntary-remat class)
    pipe = getattr(program, '_pipeline_config', None)
    if pipe and pipe.get('param_names'):
        blk = program.global_block()
        stage0 = pipe['param_names'][0]
        for j, n0 in enumerate(stage0):
            v0 = blk.vars.get(n0)
            spec0 = getattr(v0, 'sharding', None)
            for k, names in enumerate(pipe['param_names'][1:], start=1):
                vk = blk.vars.get(names[j])
                speck = getattr(vk, 'sharding', None)
                if speck != spec0:
                    findings.append(_var_finding(
                        SHARDING_RESHARD, SEV_WARNING,
                        'pipeline stage %d parameter %r is annotated %r '
                        'but its stage-0 peer %r is annotated %r — the '
                        'per-stage weight stack would reshard mid-'
                        'pipeline (involuntary rematerialization); '
                        'annotate every stage copy identically'
                        % (k, names[j], speck, n0, spec0),
                        vk if vk is not None else v0))
    return findings
