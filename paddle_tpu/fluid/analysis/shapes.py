"""Pass 2 — whole-program shape/dtype inference.

A per-op-type INFER RULE registry mirroring lowering.py's rule registry:
an explicit rule can be registered with @register_infer('op'), and every
op that has a lowering rule but no explicit infer rule gets the default —
jax.eval_shape over its lowering rule (lowering.abstract_eval), so one
definition of op semantics serves execution, build-time inference, AND
static analysis. The pass PROPAGATES ShapeDtypeStructs through the block
(sub-block bodies included): each op is abstract-evaluated on the specs
its producers actually inferred, not on declared metadata, so a corrupted
declaration is caught at the op that exposes it, with the op's build-time
callsite.

Findings: ShapeMismatch / DtypeMismatch when an op's inferred output
contradicts the variable's declared metadata (per-dim: -1 on either side
is compatible — the dynamic batch dim; rank conflicts and concrete-dim
conflicts flag). Ops whose rules cannot abstract-eval (value-dependent
control flow, LoDTensorArray plumbing with undeclared element shapes) are
skipped, never guessed: the pass reports what it can prove.
"""
from .. import core
from .. import lowering
from .findings import (Finding, SEV_ERROR, SHAPE_MISMATCH, DTYPE_MISMATCH)

__all__ = ['run_pass', 'register_infer', 'has_infer_rule', 'infer_rule']

_INFER_RULES = {}


def register_infer(op_type):
    """Register an explicit analysis infer rule:
    fn(op, in_specs) -> {slot: [spec | SeqValue | None]} (specs are
    jax.ShapeDtypeStructs). Ops without one fall back to abstract-eval of
    their lowering rule, so the registry covers every op with a lowering
    rule by construction."""
    def deco(fn):
        _INFER_RULES[op_type] = fn
        return fn
    return deco


def has_infer_rule(op_type):
    return op_type in _INFER_RULES or lowering.has_rule(op_type)


def infer_rule(op_type):
    if op_type in _INFER_RULES:
        return _INFER_RULES[op_type]
    if lowering.has_rule(op_type):
        return lowering.abstract_eval   # (op, in_specs) -> outs
    raise lowering.NoRuleError('no infer rule for op %r' % op_type)


@register_infer('autodiff')
def _infer_autodiff(op, in_specs):
    """Gradients mirror their parameters: @GRAD specs come from the
    declared grad vars (backward.append_backward sized them)."""
    return {'Grads': [lowering.spec_of(v)
                      for v in op.outputs.get('Grads', [])]}


def _declared_shape(var):
    return tuple(var.shape) if var.shape is not None else None


def _compatible_shape(declared, inferred):
    """Per-dim comparison; -1 (dynamic) on either side matches anything.
    A rank difference or a concrete-dim conflict is a mismatch."""
    if len(declared) != len(inferred):
        return False
    for d, i in zip(declared, inferred):
        if d == -1 or i == -1:
            continue
        if int(d) != int(i):
            return False
    return True


# Declared 64-bit vars execute as their 32-bit counterparts on device
# (jax x64 disabled — the TPU default; pytest.ini documents the same policy
# for the per-cast truncation warning), so a declared/inferred difference
# that is EXACTLY that truncation is not a finding.
_X64_NARROWING = {'int64': 'int32', 'uint64': 'uint32', 'float64': 'float32'}


def _canon_dtype(dt):
    try:
        import jax
        if jax.config.jax_enable_x64:
            return dt
    except Exception:
        pass
    return _X64_NARROWING.get(dt, dt)


def _check_output(op, var, spec, findings):
    """Compare one inferred output spec against the var's declaration."""
    data = spec.data if isinstance(spec, lowering.SeqValue) else spec
    inferred_shape = lowering.shape_from_spec(data)
    declared = _declared_shape(var)
    if declared is not None and not _compatible_shape(declared,
                                                      inferred_shape):
        findings.append(Finding.for_op(
            SHAPE_MISMATCH, SEV_ERROR,
            'output %r declares shape %s but the op infers %s'
            % (var.name, list(declared), list(inferred_shape)), op,
            var_names=(var.name,)))
    inferred_dtype = core.convert_dtype(data.dtype)
    if var.dtype is not None and \
            _canon_dtype(inferred_dtype) != _canon_dtype(var.dtype):
        findings.append(Finding.for_op(
            DTYPE_MISMATCH, SEV_ERROR,
            'output %r declares dtype %s but the op infers %s'
            % (var.name, var.dtype, inferred_dtype), op,
            var_names=(var.name,)))


def _in_specs(op, env):
    """Per-slot input specs for an op: the propagated spec when a producer
    ran, else the declared spec. Returns None (skip the op) when any input
    has no usable spec."""
    specs = {}
    for slot, vs in op.inputs.items():
        row = []
        for v in vs:
            s = env.get(v.name)
            if s is None:
                s = lowering.spec_of(v)
            if s is None:
                return None
            row.append(s)
        specs[slot] = row
    return specs


def _bind_declared(op, env):
    for vs in op.outputs.values():
        for v in vs:
            s = lowering.spec_of(v)
            if s is not None and v.name not in env:
                env[v.name] = s


def _walk(program, block, env, findings, stats, seen_blocks=None):
    from .dataflow import sub_block_indices
    if seen_blocks is None:
        seen_blocks = set()
    seen_blocks = seen_blocks | {block.idx}
    for op in block.ops:
        idxs = sub_block_indices(op, program)
        if idxs or op.type in lowering._BLOCK_RULES:
            # structured control flow: propagate through each body with a
            # private env copy (branches/iterations do not leak), then
            # trust the block op's declared outputs
            for bi in idxs:
                if bi in seen_blocks:
                    continue
                sub_env = dict(env)
                _walk(program, program.block(bi), sub_env, findings, stats,
                      seen_blocks=seen_blocks)
            _bind_declared(op, env)
            continue
        try:
            rule = infer_rule(op.type)
        except lowering.NoRuleError:
            stats['no_rule'] += 1
            _bind_declared(op, env)
            continue
        in_specs = _in_specs(op, env)
        if in_specs is None:
            stats['skipped'] += 1
            _bind_declared(op, env)
            continue
        try:
            outs = rule(op, in_specs)
        except Exception:
            # value-dependent rule (concrete-index reads, host branching):
            # nothing provable here — skip, never guess
            stats['failed'] += 1
            _bind_declared(op, env)
            continue
        stats['inferred'] += 1
        for slot, vs in op.outputs.items():
            vals = outs.get(slot) if hasattr(outs, 'get') else None
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for var, val in zip(vs, vals):
                if val is None:
                    continue
                _check_output(op, var, val, findings)
                env[var.name] = val


def run_pass(program, feeds=None, stats=None):
    """Propagate specs through every block from the feed/persistable
    frontier; returns findings. `stats` (optional dict) receives
    inferred/skipped/failed/no_rule op counts."""
    findings = []
    if stats is None:
        stats = {}
    for k in ('inferred', 'skipped', 'failed', 'no_rule'):
        stats.setdefault(k, 0)
    feed_names = set(feeds) if feeds is not None else None
    env = {}
    for v in program.list_vars():
        fed = (v.name in feed_names if feed_names is not None
               else getattr(v, 'is_data', False))
        if fed or v.persistable:
            s = lowering.spec_of(v)
            if s is not None:
                env[v.name] = s
    _walk(program, program.global_block(), env, findings, stats)
    return findings
