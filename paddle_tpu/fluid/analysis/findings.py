"""Structured analyzer findings (docs/analysis.md).

Every pass reports through the same Finding shape so the executor hook,
`Program.verify`, and tools/program_lint.py can rank, print, and count them
uniformly. A Finding names the op (block + index + type), the variables
involved, and — when op provenance is on (framework.ENV_PROVENANCE) — the
user-code callsite that built the op, so a build-time rejection reads
"the fc you built at train.py:42", not an XLA trace dump.
"""

__all__ = [
    'Finding', 'ProgramVerifyError',
    'SEV_ERROR', 'SEV_WARNING',
    'DANGLING_INPUT', 'WRITE_TO_FEED', 'DEAD_OP', 'UNREACHABLE_FETCH',
    'USE_BEFORE_WRITE', 'SHAPE_MISMATCH', 'DTYPE_MISMATCH',
    'DONATION_UNSAFE', 'SCOPE_RACE', 'SHARDING_INVALID',
    'SHARDING_UNTILEABLE', 'SHARDING_RESHARD', 'EMBEDDING_UNTILEABLE',
    'HBM_OVER_BUDGET', 'IMPLICIT_RESHARD', 'COLLECTIVE_DIVERGENCE',
    'CONCURRENT_COLLECTIVES', 'DIM_SHARDING',
]

SEV_ERROR = 'error'       # the program cannot run correctly as lowered
SEV_WARNING = 'warning'   # suspicious but executable (XLA DCEs dead ops)

# finding kinds (one per checkable contract; the catalog lives in
# docs/analysis.md)
DANGLING_INPUT = 'DanglingInput'        # op input never defined at its use
WRITE_TO_FEED = 'WriteToFeed'           # op output overwrites a feed var
DEAD_OP = 'DeadOp'                      # op's outputs reach no fetch/persist
UNREACHABLE_FETCH = 'UnreachableFetch'  # fetch name nothing defines
USE_BEFORE_WRITE = 'UseBeforeWrite'     # persistable read before any write
SHAPE_MISMATCH = 'ShapeMismatch'        # declared vs inferred shape conflict
DTYPE_MISMATCH = 'DtypeMismatch'        # declared vs inferred dtype conflict
DONATION_UNSAFE = 'DonationUnsafe'      # write-set vs donation decision
SCOPE_RACE = 'ScopeRace'                # persistable writes + shared scope
SHARDING_INVALID = 'ShardingInvalid'        # annotation vs mesh spec
SHARDING_UNTILEABLE = 'ShardingUntileable'  # mesh cannot tile the dim
SHARDING_RESHARD = 'ShardingReshard'        # resharding implied mid-pipeline
# a row-sharded EMBEDDING TABLE whose vocab dim the mesh axis cannot tile:
# the untileable class specialized for lookup_table weights, where the fix
# is concrete (pad the vocab — embedding.pad_vocab) and the runtime cost
# of the fallback is a silent replicate of the one tensor the annotation
# existed to shard (docs/embedding.md)
EMBEDDING_UNTILEABLE = 'EmbeddingShardUntileable'
# cost-model pass (analysis/costmodel.py — docs/analysis.md#pass-6):
# per-device persistable residency exceeds a declared --hbm-budget, or a
# var is re-placed mid-program (a sharding transition GSPMD satisfies
# with a hidden all-gather/all-to-all at the edge)
HBM_OVER_BUDGET = 'HbmOverBudget'
IMPLICIT_RESHARD = 'ImplicitReshard'
# collective-safety pass (analysis/collectives.py — docs/analysis.md
# #pass-7): a collective issued under divergent control flow (the
# rendezvous-hang class), or a concurrent-declared program issuing
# collectives at all (today survived only by serving/pod.py's
# process-wide _MESH_DISPATCH_LOCK)
COLLECTIVE_DIVERGENCE = 'CollectiveDivergence'
CONCURRENT_COLLECTIVES = 'ConcurrentCollectives'
# a dim-sharded TIERED table: spills gather whole rows, so the tier
# store statically refuses the embedding-dim sharding the runtime guard
# (embedding/tiers.py validate_program) would reject at train start
DIM_SHARDING = 'DimSharding'

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1}


class Finding(object):
    """One analyzer verdict: what is wrong, where in the program, and where
    in the user's code the offending op was built."""

    __slots__ = ('kind', 'severity', 'message', 'block', 'op_index',
                 'op_type', 'var_names', 'callsite')

    def __init__(self, kind, severity, message, block=0, op_index=None,
                 op_type=None, var_names=(), callsite=None):
        self.kind = kind
        self.severity = severity
        self.message = message
        self.block = block
        self.op_index = op_index
        self.op_type = op_type
        self.var_names = tuple(var_names)
        self.callsite = callsite

    @classmethod
    def for_op(cls, kind, severity, message, op, var_names=()):
        """Finding anchored on an Operator: block/index/type/provenance are
        derived from the op itself."""
        blk = op.block
        try:
            idx = blk.ops.index(op)
        except ValueError:
            idx = None
        return cls(kind, severity, message, block=blk.idx, op_index=idx,
                   op_type=op.type, var_names=var_names,
                   callsite=getattr(op, 'callsite', None))

    def to_dict(self):
        return {'kind': self.kind, 'severity': self.severity,
                'message': self.message, 'block': self.block,
                'op_index': self.op_index, 'op_type': self.op_type,
                'var_names': list(self.var_names), 'callsite': self.callsite}

    def _where(self):
        parts = []
        if self.op_index is not None:
            parts.append('block %d op #%d (%s)'
                         % (self.block, self.op_index, self.op_type))
        elif self.op_type is not None:
            parts.append('op %s' % self.op_type)
        if self.callsite:
            parts.append('built at %s' % self.callsite)
        return ', '.join(parts)

    def __repr__(self):
        where = self._where()
        return '[%s] %s: %s%s' % (self.severity, self.kind, self.message,
                                  ' [%s]' % where if where else '')

    __str__ = __repr__


def sort_findings(findings):
    """Errors first, then by (block, op index) program order."""
    return sorted(findings, key=lambda f: (
        _SEV_ORDER.get(f.severity, 9), f.block,
        -1 if f.op_index is None else f.op_index))


class ProgramVerifyError(ValueError):
    """Raised by Program.verify(level='error') / PADDLE_TPU_VERIFY=error
    when the analyzer reports error-severity findings. `.findings` carries
    every finding (including warnings) for programmatic inspection."""

    def __init__(self, message, findings):
        super(ProgramVerifyError, self).__init__(message)
        self.findings = list(findings)
