"""Persistent-socket RPC wire for the serving pod (docs/serving.md#pod).

PR 14's pod wire was an atomic-file mailbox on a shared filesystem: one
npz per request, polled at `_POLL_S`. That wire is durable and trivially
debuggable, but it cannot stream — a response is visible only when its
file is complete — and every hop pays a poll interval. This module is
the socket twin: length-prefixed JSON frames over persistent TCP
connections, carrying numpy arrays as raw little-endian blobs after the
header. `serving/pod.py` keeps BOTH wires behind one seam
(`PodWorker(transport='file'|'rpc')`); everything here is transport
mechanics with no pod semantics.

Frame layout (everything after the magic is length-prefixed, so a
well-formed stream never requires lookahead)::

    b'pT' | u32 header_len | u32 body_len | header JSON | array blobs

The header is UTF-8 JSON. Arrays travel out-of-band: the encoder moves
them into a ``__arrays__`` manifest — ``[name, dtype.str, shape]`` per
array, in blob order — and concatenates their ``tobytes()`` into the
body. msgpack would shave a few header bytes but is not in the image;
JSON + raw blobs keeps the dependency surface at zero while the arrays
(the actual payload mass) stay binary.

Failure posture (the part the fault drills care about):

  * a frame with a bad magic, an oversized length, or an undecodable
    header raises a typed `TransportError` — the reader NEVER hangs on
    a garbled stream, and never silently resynchronizes (there is no
    reliable resync point in a length-prefixed stream, so the
    connection is condemned and rebuilt);
  * an EOF at a frame boundary is a clean `EOFError` (peer closed); a
    reset or an EOF mid-frame is `Disconnected` — the connection died
    but nothing received was malformed, so the client redials and
    replays instead of condemning its pending work (a SIGKILLed worker
    is a host loss, not a garbled stream);
  * the server writes through a per-connection queue drained by a
    writer thread, so producers (the decode loop emitting tokens) only
    ever append to a deque — connection-level backpressure lands on the
    socket, never inside the engine;
  * the server admits at the wire: when a connection already has
    `max_inflight` uncompleted requests, new ones are refused with a
    typed ServerOverloaded error frame before the handler runs;
  * the client `Channel` owns reconnection: a broken connection is
    redialed forever (until close) on `utils.retry.backoff_delays` with
    seeded jitter, and the owner decides what to replay via the
    `on_reconnect` hook — the transport does not guess at idempotency.
"""
import json
import socket
import struct
import threading
import time

import numpy as np

from .. import obs
from ..utils.retry import backoff_delays

__all__ = ['TransportError', 'Disconnected', 'Connection', 'RpcServer',
           'Channel', 'encode_frame']

MAGIC = b'pT'
_LENS = struct.Struct('>II')
# A header is routing metadata, never payload: 4 MiB of JSON means the
# stream is garbage, not a big request. Bodies carry arrays and get the
# same ceiling the npz wire effectively had (per-frame, not per-stream).
MAX_HEADER_BYTES = 4 << 20
MAX_BODY_BYTES = 1 << 31

_C_FRAMES_OUT = obs.counter('serving.transport.frames_out')
_C_FRAMES_IN = obs.counter('serving.transport.frames_in')
_C_BYTES_OUT = obs.counter('serving.transport.bytes_out')
_C_BYTES_IN = obs.counter('serving.transport.bytes_in')
_C_RECONNECTS = obs.counter('serving.transport.reconnects')
_C_ERRORS = obs.counter('serving.transport.errors')
_C_REJECTED = obs.counter('serving.transport.rejected')


class TransportError(ConnectionError):
    """The wire itself failed: garbled frame, torn frame, oversized
    length, or a send into a dead socket. Distinct from every
    application error (those cross INSIDE well-formed frames, by name)
    so callers can tell 'the remote said no' from 'the wire broke'."""


class Disconnected(TransportError):
    """The CONNECTION died (reset, or closed mid-frame) but every byte
    received so far was well-formed. Distinct from its parent because
    the two demand opposite reactions: a dead connection is redialed
    and its pending work replayed (a SIGKILLed worker must look like a
    host loss, not a poisoned stream), while a garbled stream condemns
    the pending work typed — corruption gives no honest claim about
    what the other side received."""


def encode_frame(header, arrays=None):
    """Serialize one frame. `header` is a JSON-able dict (not mutated);
    `arrays` maps names to ndarrays, shipped as contiguous raw blobs."""
    header = dict(header)
    manifest = []
    blobs = []
    for name in sorted(arrays or ()):
        a = np.ascontiguousarray(arrays[name])
        manifest.append([name, a.dtype.str, list(a.shape)])
        blobs.append(a.tobytes())
    header['__arrays__'] = manifest
    hdr = json.dumps(header, sort_keys=True).encode('utf-8')
    body = b''.join(blobs)
    if len(hdr) > MAX_HEADER_BYTES:
        raise TransportError('frame header of %d bytes exceeds the %d '
                             'byte cap' % (len(hdr), MAX_HEADER_BYTES))
    if len(body) > MAX_BODY_BYTES:
        raise TransportError('frame body of %d bytes exceeds the %d '
                             'byte cap' % (len(body), MAX_BODY_BYTES))
    return b''.join((MAGIC, _LENS.pack(len(hdr), len(body)), hdr, body))


def _recv_exact(sock, n, at_boundary=False):
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise Disconnected('recv failed: %s' % (e,))
        if not chunk:
            if at_boundary and not buf:
                raise EOFError('peer closed the connection')
            raise Disconnected(
                'connection closed mid-frame (%d of %d bytes)'
                % (len(buf), n))
        buf += chunk
    return bytes(buf)


class Connection(object):
    """One framed socket: locked sends (frames from concurrent senders
    interleave whole, never byte-wise) and single-reader recvs."""

    def __init__(self, sock):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (tests may hand in a socketpair)
        self._sock = sock
        self._wlock = threading.Lock()
        self.peer = None
        try:
            self.peer = sock.getpeername()
        except OSError:
            pass

    def send(self, header, arrays=None):
        frame = encode_frame(header, arrays)
        with self._wlock:
            self._sock.sendall(frame)
        _C_FRAMES_OUT.inc()
        _C_BYTES_OUT.inc(len(frame))

    def recv(self):
        """Read one frame; returns (header, arrays). Raises EOFError on
        a clean close at a frame boundary, Disconnected on a reset or
        mid-frame close, TransportError on anything garbled or
        oversized — never hangs on a bad stream."""
        head = _recv_exact(self._sock, len(MAGIC) + _LENS.size,
                           at_boundary=True)
        if head[:len(MAGIC)] != MAGIC:
            raise TransportError(
                'bad frame magic %r — garbled stream' % (head[:len(MAGIC)],))
        hlen, blen = _LENS.unpack(head[len(MAGIC):])
        if hlen > MAX_HEADER_BYTES or blen > MAX_BODY_BYTES:
            raise TransportError(
                'frame lengths (%d, %d) exceed caps — garbled stream'
                % (hlen, blen))
        try:
            header = json.loads(
                _recv_exact(self._sock, hlen).decode('utf-8'))
        except (ValueError, UnicodeDecodeError) as e:
            raise TransportError('undecodable frame header: %s' % (e,))
        if not isinstance(header, dict):
            raise TransportError('frame header is not an object: %r'
                                 % (header,))
        body = _recv_exact(self._sock, blen) if blen else b''
        arrays = {}
        off = 0
        for item in header.pop('__arrays__', []):
            try:
                name, dstr, shape = item
                dt = np.dtype(dstr)
                count = int(np.prod(shape, dtype=np.int64)) if shape else 1
                nbytes = count * dt.itemsize
            except (TypeError, ValueError) as e:
                raise TransportError('bad array manifest entry %r: %s'
                                     % (item, e))
            if off + nbytes > len(body):
                raise TransportError(
                    'frame body shorter than its array manifest')
            arrays[name] = np.frombuffer(
                body, dt, count=count, offset=off).reshape(shape)
            off += nbytes
        _C_FRAMES_IN.inc()
        _C_BYTES_IN.inc(len(head) + hlen + blen)
        return header, arrays

    def close(self):
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _ServerConn(object):
    """One accepted connection: a reader thread dispatching frames to
    the server's handler, and a writer thread draining a send queue so
    handler/engine callbacks enqueue without ever blocking on the
    socket (that IS the backpressure seam: a slow client backs up this
    queue and eventually its own TCP window, never the decode loop)."""

    def __init__(self, server, sock):
        self._server = server
        self.conn = Connection(sock)
        self.state = {}            # owner scratch (PodWorker's uid maps)
        self.inflight = set()      # admitted uids awaiting a final frame
        self._q = []
        self._cv = threading.Condition()
        self._alive = True
        self._reader = threading.Thread(target=self._read_loop,
                                        name='rpc-conn-reader', daemon=True)
        self._writer = threading.Thread(target=self._write_loop,
                                        name='rpc-conn-writer', daemon=True)
        self._reader.start()
        self._writer.start()

    @property
    def alive(self):
        return self._alive

    def send(self, header, arrays=None):
        """Queue one frame for the writer; returns False when the
        connection is already gone (the caller's signal to abort a
        stream whose consumer vanished)."""
        with self._cv:
            if header.get('final'):
                self.inflight.discard(header.get('uid'))
            if not self._alive:
                return False
            self._q.append((header, arrays))
            self._cv.notify()
        return True

    def _write_loop(self):
        while True:
            with self._cv:
                while self._alive and (not self._q or self._server.frozen):
                    self._cv.wait(0.05)
                if not self._alive:
                    return
                header, arrays = self._q.pop(0)
            try:
                self.conn.send(header, arrays)
            except (TransportError, OSError):
                self._die()
                return

    def _read_loop(self):
        try:
            while self._alive:
                if self._server.frozen:
                    time.sleep(0.02)
                    continue
                try:
                    header, arrays = self.conn.recv()
                except (EOFError, TransportError, OSError):
                    return
                if self._server.frozen:
                    continue   # a frozen (simulated-dead) host swallows it
                uid = header.get('uid')
                if uid is not None \
                        and header.get('op') in self._server.admitted_ops:
                    with self._cv:
                        full = len(self.inflight) >= self._server.max_inflight
                        if not full:
                            self.inflight.add(uid)
                    if full:
                        _C_REJECTED.inc()
                        obs.event('serving.transport.reject', uid=uid,
                                  inflight=self._server.max_inflight)
                        self.send({'uid': uid, 'final': True, 'error': {
                            'type': 'ServerOverloaded',
                            'message': 'connection already has %d '
                                       'request(s) in flight — admission '
                                       'refused at the wire'
                                       % self._server.max_inflight}})
                        continue
                try:
                    self._server.handler(self, header, arrays)
                except Exception as e:  # noqa: BLE001 — reader must live
                    if uid is not None:
                        self.send({'uid': uid, 'final': True, 'error': {
                            'type': type(e).__name__, 'message': str(e)}})
        finally:
            self._die()

    def _die(self):
        with self._cv:
            if not self._alive:
                return
            self._alive = False
            del self._q[:]
            self._cv.notify_all()
        self.conn.close()
        self._server._conn_closed(self)

    def close(self):
        self._die()


class RpcServer(object):
    """Accept loop + per-connection reader/writer pairs. `handler` is
    called as handler(conn, header, arrays) on the connection's reader
    thread; it replies (possibly later, from any thread) via
    `conn.send`. `freeze()` simulates a dead host for the fault drills:
    frames are neither read nor written, but every socket stays open —
    exactly what a wedged process looks like from the outside, so the
    heartbeat watcher (not the transport) must be the detector."""

    def __init__(self, handler, host='127.0.0.1', port=0, max_inflight=64,
                 on_close=None, admitted_ops=('submit',)):
        self.handler = handler
        self.max_inflight = int(max_inflight)
        self.admitted_ops = frozenset(admitted_ops)
        self.frozen = False
        self._on_close = on_close
        self._closed = False
        self._lock = threading.Lock()
        self._conns = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.addr = self._sock.getsockname()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name='rpc-accept', daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._closed:
            try:
                sock, peer = self._sock.accept()
            except OSError:
                return
            obs.event('serving.transport.accept', port=self.addr[1])
            sc = _ServerConn(self, sock)
            with self._lock:
                raced_shutdown = self._closed
                if not raced_shutdown:
                    self._conns.add(sc)
            if raced_shutdown:
                # outside the lock: close() -> _die() -> _conn_closed()
                # re-enters it, and the lock is not reentrant
                sc.close()

    def _conn_closed(self, sc):
        with self._lock:
            self._conns.discard(sc)
        if self._on_close is not None and not self._closed:
            try:
                self._on_close(sc)
            except Exception:  # noqa: BLE001 — owner bug, not wire state
                pass

    def freeze(self):
        self.frozen = True

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
        try:
            self._sock.close()
        except OSError:
            pass
        for sc in conns:
            sc.close()


class Channel(object):
    """Client side of the wire: ONE persistent connection to `addr`,
    rebuilt forever (until `close`) on `backoff_delays` with seeded
    jitter. Incoming frames land on `on_frame(header, arrays)` from the
    channel thread. The channel never decides what a reconnect means:
    `on_reconnect()` fires after every re-dial so the owner replays
    what it knows is idempotent, and `on_wire_error(exc)` fires when a
    frame was GARBLED (torn/bad-magic/undecodable) — the owner fails
    its pending work typed rather than trusting a poisoned stream."""

    def __init__(self, addr, on_frame, on_reconnect=None,
                 on_wire_error=None, seed=None, dial_timeout=2.0):
        self.addr = (str(addr[0]), int(addr[1]))
        self._on_frame = on_frame
        self._on_reconnect = on_reconnect
        self._on_wire_error = on_wire_error
        self._seed = seed
        self._dial_timeout = float(dial_timeout)
        self._conn = None
        self._closed = False
        self._ever_connected = False
        self.dial_attempts = 0
        self.reconnects = 0
        self._thread = threading.Thread(target=self._run,
                                        name='rpc-channel', daemon=True)
        self._thread.start()

    @property
    def connected(self):
        return self._conn is not None

    def send(self, header, arrays=None):
        """Best-effort send on the CURRENT connection; returns False
        when disconnected (the frame is NOT queued — the owner's
        pending map plus `on_reconnect` is the replay path, so the
        transport never re-sends something the owner already gave up
        on)."""
        conn = self._conn
        if conn is None:
            return False
        try:
            conn.send(header, arrays)
            return True
        except (TransportError, OSError):
            return False

    def _delays(self):
        # Small, capped, jittered: a worker restart is sub-second; a
        # genuinely dead host is the heartbeat watcher's problem, and
        # this loop just needs to not stampede while it decides.
        return backoff_delays(8, base_delay=0.05, factor=1.6,
                              max_delay=0.5, jitter=0.5, seed=self._seed)

    def _run(self):
        delays = None
        while not self._closed:
            try:
                sock = socket.create_connection(
                    self.addr, timeout=self._dial_timeout)
                sock.settimeout(None)
            except OSError:
                self.dial_attempts += 1
                if delays is None:
                    delays = self._delays()
                d = next(delays, None)
                if d is None:
                    delays = self._delays()
                    d = next(delays)
                deadline = time.monotonic() + d
                while not self._closed and time.monotonic() < deadline:
                    time.sleep(min(0.05, d))
                continue
            delays = None
            conn = Connection(sock)
            self._conn = conn
            if self._ever_connected:
                self.reconnects += 1
                _C_RECONNECTS.inc()
                obs.event('serving.transport.reconnect', peer=self.addr[1],
                          attempts=self.dial_attempts)
                if self._on_reconnect is not None:
                    try:
                        self._on_reconnect()
                    except Exception:  # noqa: BLE001
                        pass
            else:
                self._ever_connected = True
                obs.event('serving.transport.connect', peer=self.addr[1])
            wire_err = None
            while not self._closed:
                try:
                    header, arrays = conn.recv()
                except (EOFError, Disconnected):
                    break     # connection death: redial + replay
                except TransportError as e:
                    wire_err = e       # garbling: condemn pending work
                    break
                except OSError:
                    break
                try:
                    self._on_frame(header, arrays)
                except Exception:  # noqa: BLE001 — callback must not
                    pass           # kill the reader
            self._conn = None
            conn.close()
            if wire_err is not None:
                _C_ERRORS.inc()
                obs.event('serving.transport.error', peer=self.addr[1],
                          error=str(wire_err))
                if self._on_wire_error is not None and not self._closed:
                    try:
                        self._on_wire_error(wire_err)
                    except Exception:  # noqa: BLE001
                        pass

    def close(self):
        self._closed = True
        conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()
        self._thread.join(timeout=2.0)
