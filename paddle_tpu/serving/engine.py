"""In-process TPU serving engine: dynamic micro-batching behind futures.

`Predictor.run` is one synchronous model execution per request; under
concurrent traffic that wastes the accelerator twice — per-call dispatch
overhead dominates small batches, and every novel request batch size
risks an XLA recompile on the hot path. `ServingEngine` puts an async
request API in front of either a fluid `Predictor` or a `load_compiled`
StableHLO runner:

  * callers `submit(feed)` and get a `concurrent.futures.Future`; a
    single batcher thread coalesces waiting requests into micro-batches
    under a (max_batch_size, max_queue_delay_ms) policy — ORCA/Clipper-
    style dynamic batching;
  * each micro-batch is padded up to a configured shape BUCKET
    (serving/buckets.py), so the executor's jit cache sees a small
    closed signature set and `warmup()` can pre-compile every bucket
    before traffic arrives (steady state performs ZERO compiles);
  * admission control: the request queue is bounded; overflow either
    blocks the submitter or rejects with a typed `ServerOverloaded`;
    per-request deadlines shed already-expired work before it wastes a
    batch slot; `shutdown()` drains in-flight work (the Trainer's
    preemption pattern: signal handlers may only flip the flag via
    `request_shutdown()` — the batcher, not the signal frame, owns the
    drain);
  * everything is observable through paddle_tpu.obs: queue-depth gauge,
    batch-size / queue-wait / exec-latency histograms, shed and reject
    counters, per-batch spans in the run log — `tools/obs_report.py`
    renders a serving section from them (docs/serving.md has the event
    catalog).

The engine owns no devices and compiles nothing itself: batches execute
through the wrapped model's ordinary entry point on ONE thread, so the
compiled step is byte-identical to a hand-rolled fixed-batch loop and
the executor/jit caches behave exactly as documented in
docs/architecture.md.
"""
import collections
import concurrent.futures
import threading
import time

import numpy as np

from .. import obs
from . import buckets as _buckets

__all__ = ['ServingConfig', 'ServingEngine', 'ServerOverloaded',
           'ServerClosed', 'DeadlineExceeded', 'DeltaUnsupported']

# How long any internal condition-wait may sleep before re-checking the
# shutdown flag. request_shutdown() must be callable from a signal
# handler, which cannot take locks (the interrupted main thread may hold
# them) — so it only writes a flag, and every wait polls at this period.
_POLL_S = 0.02


class ServerOverloaded(RuntimeError):
    """The bounded request queue is full and the overflow policy is
    'reject' (or a blocking submit hit its admission timeout)."""


class ServerClosed(RuntimeError):
    """The engine is shutting down (or already shut down): the request
    was not admitted, or a queued request was cancelled by a
    non-draining shutdown."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it waited in the queue; it
    was shed before execution (its future receives this exception)."""


class DeltaUnsupported(TypeError):
    """push_rows targeted a model that cannot take row deltas: a
    `load_compiled` runner (parameters are baked into the StableHLO
    artifact as constants — publish a new artifact and Router.swap()
    instead), or a decode-pool persistable that is donated per-step
    state rather than a weight."""


class ServingConfig(object):
    """Batching / admission policy for a ServingEngine.

    max_batch_size:     rows per micro-batch cap (and the largest
                        default bucket).
    max_queue_delay_ms: how long the batcher waits after the FIRST
                        request of a batch for more work to coalesce —
                        the latency price paid for throughput.
    queue_capacity:     bounded queue length, in requests.
    overflow:           'block' (submit waits for space) or 'reject'
                        (raise ServerOverloaded immediately).
    buckets:            batch-dim bucket set; default powers of two up
                        to max_batch_size. A load_compiled artifact has
                        ONE exported batch size — pass buckets=[that].
    default_deadline_ms: deadline applied to submits that don't carry
                        their own; None = no deadline.
    max_retries:        per-batch execution retries (utils.retry, site
                        'serving.batch') before the batch's futures see
                        the error; 0 = fail fast.
    """

    def __init__(self, max_batch_size=32, max_queue_delay_ms=5.0,
                 queue_capacity=256, overflow='block', buckets=None,
                 default_deadline_ms=None, max_retries=0,
                 retry_base_delay_ms=10.0, retry_seed=0):
        if overflow not in ('block', 'reject'):
            raise ValueError("overflow must be 'block' or 'reject', got %r"
                             % (overflow,))
        if max_batch_size < 1:
            raise ValueError('max_batch_size must be >= 1')
        if queue_capacity < 1:
            raise ValueError('queue_capacity must be >= 1')
        self.max_batch_size = int(max_batch_size)
        self.max_queue_delay_ms = float(max_queue_delay_ms)
        self.queue_capacity = int(queue_capacity)
        self.overflow = overflow
        self.buckets = tuple(sorted(int(b) for b in buckets)) if buckets \
            else _buckets.default_buckets(max_batch_size)
        if self.buckets[-1] < self.max_batch_size:
            # a batch can never exceed the largest padded signature
            self.max_batch_size = self.buckets[-1]
        self.default_deadline_ms = default_deadline_ms
        self.max_retries = int(max_retries)
        self.retry_base_delay_ms = float(retry_base_delay_ms)
        self.retry_seed = retry_seed


def _validate_delta(name, w, ids, rows):
    """Shared delta validation for the push surfaces (ServingEngine and
    DecodeEngine): in-range int row ids, matching trailing dims, a
    safely-castable dtype. Returns (ids int32 [n], rows w.dtype [n,...])
    or raises ValueError naming the table."""
    ids = np.asarray(ids)
    rows = np.asarray(rows)
    if ids.ndim != 1:
        raise ValueError('push_rows: %r row ids must be 1-D, got shape %r'
                         % (name, tuple(ids.shape)))
    if not np.issubdtype(ids.dtype, np.integer):
        raise ValueError('push_rows: %r row ids must be integers, got %s'
                         % (name, ids.dtype))
    cap = int(w.shape[0])
    if ids.size and (int(ids.min()) < 0 or int(ids.max()) >= cap):
        raise ValueError(
            'push_rows: %r row ids out of range [0, %d) (got min %d '
            'max %d)' % (name, cap, int(ids.min()), int(ids.max())))
    want = (ids.shape[0],) + tuple(int(d) for d in w.shape[1:])
    if tuple(rows.shape) != want:
        raise ValueError(
            'push_rows: %r rows have shape %r, expected %r (one row per '
            'id, trailing dims of the table)'
            % (name, tuple(rows.shape), want))
    wdt = np.dtype(str(w.dtype))
    if rows.dtype != wdt:
        if np.can_cast(rows.dtype, wdt, 'same_kind'):
            rows = rows.astype(wdt)
        else:
            raise ValueError(
                'push_rows: %r rows dtype %s cannot cast to the table '
                'dtype %s' % (name, rows.dtype, wdt))
    return ids.astype(np.int32), rows


class _Request(object):
    __slots__ = ('feed', 'n', 'sig', 'future', 't_submit', 'deadline')

    def __init__(self, feed, n, sig, future, t_submit, deadline):
        self.feed = feed
        self.n = n
        self.sig = sig
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline


# Process-wide serving telemetry (docs/serving.md): unlabeled, like the
# executor's — per-engine views live in engine.stats.
_G_QDEPTH = obs.gauge('serving.queue.depth')
_H_BATCH_SIZE = obs.histogram('serving.batch.size',
                              buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256,
                                       512, 1024))
_H_QWAIT = obs.histogram('serving.queue.wait.seconds')
_C_REQUESTS = obs.counter('serving.requests')
_C_BATCHES = obs.counter('serving.batches')
_C_REJECTED = obs.counter('serving.rejected')
_C_SHED = obs.counter('serving.shed')
_C_BATCH_ERRORS = obs.counter('serving.batch.errors')
_C_PAD_ROWS = obs.counter('serving.padded_rows')


class ServingEngine(object):
    """Async micro-batching front end over one loaded model.

    `model` is either a `paddle_tpu.inference.Predictor`, a
    `load_compiled` runner, or any object exposing `feed_names` plus a
    `run(feed) -> [ndarray]` method (or being itself that callable) —
    the fault drills wrap flaky callables this way. The engine starts
    its batcher thread immediately and is a context manager
    (`with ServingEngine(p) as eng: ...` drains on exit).

    `per_row_outputs` declares which fetch-list positions are batched
    per-row (everything else replicates whole to each request in the
    batch). Without it the engine falls back to a HEURISTIC — an output
    is per-row iff its leading dim equals the padded bucket size —
    which silently mis-slices a batch-level aggregate whose leading dim
    coincidentally equals the bucket. Declare the set whenever any
    fetch output is not batched on axis 0 (docs/serving.md).
    """

    def __init__(self, model, config=None, per_row_outputs=None):
        self.config = config or ServingConfig()
        self._model = model
        self._model_fn = model.run if hasattr(model, 'run') else model
        self.feed_names = list(model.feed_names)
        self._input_spec = getattr(model, 'input_spec', None)
        self._per_row_outputs = None if per_row_outputs is None \
            else frozenset(int(i) for i in per_row_outputs)
        if self._per_row_outputs is not None:
            fetch_names = getattr(model, 'fetch_names', None)
            n_out = len(fetch_names) if fetch_names is not None else None
            bad = sorted(i for i in self._per_row_outputs
                         if i < 0 or (n_out is not None and i >= n_out))
            if bad:
                raise ValueError(
                    'per_row_outputs %r out of range: indices must be '
                    '>= 0%s' % (bad, '' if n_out is None else
                                ' and < %d fetch output(s)' % n_out))
        self.buckets = self.config.buckets
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._shutdown = False
        self._drain = True
        self._warm = False
        # per-engine counters (process-wide twins live in the registry)
        self._n_submitted = 0
        self._n_completed = 0
        self._n_rejected = 0
        self._n_shed = 0
        self._n_batches = 0
        self._n_batch_errors = 0
        self._n_padded_rows = 0
        self._n_inflight = 0           # rows in the currently-executing batch
        self._q_high_water = 0         # cumulative queue high-water mark
        # row-delta pushes (push_rows): serialized so two publishers'
        # read-modify-write scatters never lose rows to each other
        self._push_lock = threading.Lock()
        self._push_write_set = None    # memoized program write set
        self._n_delta_pushes = 0
        self._n_delta_rows = 0
        # the windowed counterparts stats_window() reads-and-resets — the
        # admission-pressure signal the router balances on
        self._win = {'submitted': 0, 'completed': 0, 'shed': 0,
                     'rejected': 0, 'queue_high_water': 0}
        self._thread = threading.Thread(target=self._batcher_loop,
                                        name='serving-batcher', daemon=True)
        self._thread.start()

    # -- request admission -------------------------------------------------

    def _normalize_feed(self, feed):
        """np-ify the feed, check names, and derive (rows, signature).
        The signature — feed names + trailing dims + dtypes — decides
        which requests may share a micro-batch."""
        if set(feed) != set(self.feed_names):
            raise ValueError(
                'feed names %r do not match the model inputs %r'
                % (sorted(feed), sorted(self.feed_names)))
        arrays, n = {}, None
        for name in self.feed_names:
            a = np.asarray(feed[name])
            if a.ndim == 0:
                raise ValueError(
                    'serving feeds are batched on axis 0; input %r is a '
                    'scalar' % name)
            if a.shape[0] == 0:
                raise ValueError(
                    'input %r has 0 rows — an empty request cannot be '
                    'padded to a bucket' % name)
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    'inconsistent leading (batch) dims in one request: '
                    'input %r has %d rows, expected %d'
                    % (name, a.shape[0], n))
            arrays[name] = a
        sig = tuple((name, arrays[name].shape[1:], str(arrays[name].dtype))
                    for name in self.feed_names)
        return arrays, int(n), sig

    def submit(self, feed, deadline_ms=None, timeout=None):
        """Enqueue one request; returns a `concurrent.futures.Future`
        resolving to the model's fetch list, each output sliced back to
        this request's rows. Raises ServerClosed after shutdown,
        ServerOverloaded when the queue is full under the 'reject'
        policy (or when a 'block' submit exceeds `timeout` seconds), and
        ValueError for malformed feeds. `deadline_ms` (default
        config.default_deadline_ms) sheds the request with
        DeadlineExceeded if it is still queued when the deadline
        passes."""
        arrays, n, sig = self._normalize_feed(feed)
        if n > self.config.max_batch_size:
            raise ValueError(
                'request of %d rows exceeds max_batch_size=%d — split it '
                'client-side' % (n, self.config.max_batch_size))
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = time.monotonic()
        deadline = now + deadline_ms / 1000.0 if deadline_ms is not None \
            else None
        fut = concurrent.futures.Future()
        req = _Request(arrays, n, sig, fut, now, deadline)
        t_give_up = now + timeout if timeout is not None else None
        with self._lock:
            while True:
                if self._shutdown:
                    raise ServerClosed('serving engine is shut down')
                if len(self._queue) < self.config.queue_capacity:
                    break
                if self.config.overflow == 'reject':
                    self._n_rejected += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('serving.reject',
                              queue_depth=len(self._queue),
                              capacity=self.config.queue_capacity)
                    raise ServerOverloaded(
                        'request queue is full (%d request(s), capacity %d) '
                        'and the overflow policy is reject'
                        % (len(self._queue), self.config.queue_capacity))
                remaining = _POLL_S if t_give_up is None else \
                    min(_POLL_S, t_give_up - time.monotonic())
                if t_give_up is not None and remaining <= 0:
                    self._n_rejected += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('serving.reject',
                              queue_depth=len(self._queue),
                              capacity=self.config.queue_capacity,
                              timeout_s=timeout)
                    raise ServerOverloaded(
                        'request queue stayed full for %.3fs (capacity %d)'
                        % (timeout, self.config.queue_capacity))
                self._not_full.wait(remaining)
            self._queue.append(req)
            self._n_submitted += 1
            self._win['submitted'] += 1
            depth = len(self._queue)
            self._q_high_water = max(self._q_high_water, depth)
            self._win['queue_high_water'] = max(
                self._win['queue_high_water'], depth)
            _C_REQUESTS.inc()
            _G_QDEPTH.set(depth)
            self._not_empty.notify()
        return fut

    def predict(self, feed, deadline_ms=None, timeout=None):
        """Synchronous convenience: submit + wait. `timeout` is ONE
        wall-clock budget covering both admission (a 'block' overflow
        wait on a full queue) and the result, and its expiry raises the
        typed DeadlineExceeded (never a raw
        concurrent.futures.TimeoutError). A still-queued request is
        cancelled — dropped at dequeue time without consuming a batch
        slot; one already mid-batch cannot be recalled: its batch
        completes and the result is discarded."""
        t0 = time.monotonic()
        fut = self.submit(feed, deadline_ms=deadline_ms, timeout=timeout)
        remaining = None if timeout is None else \
            max(0.0, timeout - (time.monotonic() - t0))
        try:
            return fut.result(remaining)
        except concurrent.futures.TimeoutError:
            if fut.done():
                # the future resolved in the race window after result()
                # expired — return the just-arrived result (or re-raise
                # the model's own exception, including a genuine model
                # TimeoutError) instead of discarding it
                return fut.result()
            if fut.cancel():
                raise DeadlineExceeded(
                    'no result within the %.3fs predict() timeout; the '
                    'queued request was cancelled and will not execute'
                    % timeout)
            raise DeadlineExceeded(
                'no result within the %.3fs predict() timeout; the '
                'request is already executing — its batch completes but '
                'the result is discarded' % timeout)

    def cancel(self, future):
        """Best-effort cancel of one submitted request by its future
        (the pod worker reaps a disconnected client's work through
        this). A still-QUEUED request is cancelled — dropped at dequeue
        time without consuming a batch slot; one already mid-batch
        completes and its result is discarded. Returns True if the
        future was cancelled while queued."""
        return future.cancel()

    # -- warmup ------------------------------------------------------------

    def warmup(self, example_feed=None):
        """Pre-compile every bucket signature before traffic arrives, so
        steady-state serving performs zero compiles (assert it via
        `exe.cache_stats` or the absence of executor.compile events in
        the run log). Builds a feed per bucket by tiling `example_feed`
        (any row count >= 1) — or, when the model publishes a fully
        static `input_spec`, a zeros feed. Returns the bucket list.

        With PADDLE_TPU_COMPILE_CACHE set (docs/perf.md), a RESTARTED
        server's warmup deserializes every bucket's executable from the
        persistent cache instead of re-compiling: each serving.warmup
        span then carries cache='persistent_hit' and the run log shows
        zero executor.compile spans — warm in seconds, not minutes."""
        template = {}
        if example_feed is not None:
            arrays, _, _ = self._normalize_feed(example_feed)
            template = {n: a[:1] for n, a in arrays.items()}
        else:
            spec = self._input_spec or {}
            for name in self.feed_names:
                sp = spec.get(name)
                if sp is None or any(int(d) < 0 for d in sp[0][1:]):
                    raise ValueError(
                        'warmup() needs example_feed: input %r has no '
                        'static shape in the model metadata' % name)
                shape, dtype = sp
                template[name] = np.zeros((1,) + tuple(
                    int(d) for d in shape[1:]), dtype=np.dtype(dtype))
        exe = getattr(self._model, '_exe', None)
        # Donation/memory plan (fluid.passes.memplan): the engine runs
        # batches concurrently with callers holding the same scope, so a
        # model whose plan DONATES (writes persistables) is a serving
        # hazard — the Predictor's load-time verify already rejects it as
        # a ScopeRace under PADDLE_TPU_VERIFY; the plan is recorded here
        # either way so warmup spans document the decision.
        plan = None
        prog = getattr(self._model, '_program', None)
        if prog is not None:
            try:
                from ..fluid.passes import memory_plan
                plan = memory_plan(prog)
            except Exception:
                plan = None
        if plan is not None:
            obs.event('serving.memory_plan', donates=plan.donates,
                      writes=len(plan.write_set))
            if plan.donates:
                import warnings
                warnings.warn(
                    'serving warmup: the model writes persistable(s) %r — '
                    'its step would donate parameter buffers, which is '
                    'unsafe under concurrent serving; load a '
                    'clone(for_test=True)/pruned inference artifact '
                    '(PADDLE_TPU_VERIFY=error rejects this at load)'
                    % sorted(plan.write_set), RuntimeWarning)
        for b in self.buckets:
            feed = {n: _buckets.pad_rows(a, b) for n, a in template.items()}
            with obs.span('serving.warmup', bucket=b) as sp:
                if plan is not None:
                    sp.fields['donates'] = plan.donates
                self._model_fn(feed)
                if exe is not None:
                    look = getattr(exe, '_last_cache_lookup', None) or {}
                    sp.fields['cache'] = look.get('outcome')
        self._warm = True
        return list(self.buckets)

    # -- row-delta push (docs/serving.md#delta-push) -----------------------

    def push_rows(self, deltas):
        """Scatter trained row deltas into this replica's LIVE weights —
        the streaming train->serve freshness path (docs/embedding.md
        "streaming ids"): `deltas` maps a persistable name to
        `(row_ids, rows)` where `rows[i]` is the new value of
        `table[row_ids[i]]`. The replacement is per-TABLE atomic: the
        new array is built fully off to the side, then swapped into the
        model scope by reference — a batch executing concurrently reads
        the old table or the new one, never a torn row. Only
        Predictor-backed models take deltas (a `load_compiled` runner
        bakes parameters into the artifact as constants: typed
        DeltaUnsupported — publish an artifact and Router.swap()
        instead), and only into variables the program does not WRITE
        (a written persistable is donated state; scattering into it
        would race the batcher's in-place update). Returns rows
        applied."""
        scope = getattr(self._model, '_scope', None)
        prog = getattr(self._model, '_program', None)
        if scope is None or prog is None:
            raise DeltaUnsupported(
                'this replica serves a compiled artifact (or a bare '
                'callable) with no live parameter scope — row deltas '
                'need a Predictor-backed engine; swap() a new artifact '
                'instead')
        if self._shutdown:
            raise ServerClosed('serving engine is shut down')
        # the program never changes for the life of the engine: walk
        # its write set once, not once per publisher cadence
        write_set = self._push_write_set
        if write_set is None:
            from ..fluid.passes import memory_plan
            write_set = self._push_write_set = memory_plan(prog).write_set
        import jax.numpy as jnp
        applied = 0
        with self._push_lock:
            for name in sorted(deltas):
                ids, rows = deltas[name]
                w = scope._chain_get(name)
                if w is None:
                    raise KeyError(
                        'push_rows: no persistable %r in the model scope'
                        % (name,))
                if name in write_set:
                    raise DeltaUnsupported(
                        'push_rows: %r is WRITTEN by the serving program '
                        '(donated state) — pushing rows into it would '
                        'race the in-place update' % (name,))
                ids, rows = _validate_delta(name, w, ids, rows)
                new = jnp.asarray(w).at[ids].set(rows)
                # reference swap = the atomic commit: concurrent batches
                # hold either the old array or the new one
                scope._chain_set(name, new)
                applied += int(ids.shape[0])
        self._n_delta_rows += applied
        self._n_delta_pushes += 1
        return applied

    # -- shutdown ----------------------------------------------------------

    def request_shutdown(self):
        """Signal-safe shutdown request (the Trainer preemption pattern:
        flag only, NO locks — safe from a SIGTERM handler). Admission
        closes immediately; the batcher drains queued and in-flight
        requests, then exits."""
        self._shutdown = True

    def shutdown(self, drain=True, timeout=None):
        """Stop admission and wait for the batcher to finish. With
        drain=True (default) every queued request still executes; with
        drain=False queued futures fail with ServerClosed. Either way no
        future is ever lost. Returns True when the batcher exited within
        `timeout`."""
        with self._lock:
            self._drain = drain
            self._shutdown = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
        self._thread.join(timeout)
        done = not self._thread.is_alive()
        obs.event('serving.shutdown', drained=drain, clean=done,
                  completed=self._n_completed, shed=self._n_shed,
                  batches=self._n_batches)
        return done

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    @property
    def stats(self):
        """This engine's CUMULATIVE serving statistics (process-wide
        aggregates of the same series live in the obs registry,
        docs/serving.md). The windowed admission-pressure signal a
        router balances on is `stats_window()`."""
        with self._lock:
            depth = len(self._queue)
        return {'submitted': self._n_submitted,
                'completed': self._n_completed,
                'rejected': self._n_rejected,
                'shed': self._n_shed,
                'batches': self._n_batches,
                'batch_errors': self._n_batch_errors,
                'padded_rows': self._n_padded_rows,
                'queue_depth': depth,
                'queue_high_water': self._q_high_water,
                'inflight': self._n_inflight,
                'delta_pushes': self._n_delta_pushes,
                'delta_rows': self._n_delta_rows,
                'warm': self._warm}

    def stats_window(self):
        """Admission-pressure counters SINCE THE LAST CALL — the queue
        high-water mark plus shed/reject/submit/complete counts of the
        window, with the instantaneous depth and in-flight rows
        appended. Instantaneous depth alone is a useless balancing
        signal (a bursty replica reads 0 between bursts; one that shed
        work a moment ago looks idle); the router (serving/router.py)
        is the intended single consumer — reading resets the window."""
        with self._lock:
            win = dict(self._win)
            for k in self._win:
                self._win[k] = 0
            depth = len(self._queue)
        win['queue_depth'] = depth
        win['inflight'] = self._n_inflight
        win['capacity'] = self.config.queue_capacity
        return win

    # -- batcher -----------------------------------------------------------

    def _pop_live_locked(self, now, shed):
        """Pop the next request that is still wanted, collecting expired
        ones into `shed`. Caller holds the lock — the shed futures are
        FAILED BY THE CALLER after releasing it (set_exception runs
        done-callbacks synchronously; a callback that re-enters the
        engine, e.g. a client-side retry submit, would deadlock on the
        non-reentrant lock)."""
        while self._queue:
            req = self._queue.popleft()
            _G_QDEPTH.set(len(self._queue))
            self._not_full.notify()
            if req.deadline is not None and now > req.deadline:
                shed.append(req)
                continue
            return req
        return None

    def _fail_shed(self, shed):
        """Resolve shed requests' futures (lock NOT held)."""
        now = time.monotonic()
        for req in shed:
            # a request can be cancelled while queued (predict()'s
            # timeout path) and ALSO pass its deadline before the
            # batcher reaches it: set_exception on a cancelled future
            # raises InvalidStateError, which would kill the batcher
            # thread. This transition claims the future atomically —
            # False means cancelled, and nobody is waiting for it.
            if not req.future.set_running_or_notify_cancel():
                continue
            self._n_shed += 1
            with self._lock:   # _win races stats_window's copy+reset
                self._win['shed'] += 1
            _C_SHED.inc()
            waited = now - req.t_submit
            obs.event('serving.shed', waited_s=waited, rows=req.n)
            req.future.set_exception(DeadlineExceeded(
                'request shed after waiting %.3fs: its deadline passed '
                'before a batch slot opened' % waited))

    def _collect(self):
        """Block for the next micro-batch: the first live request opens
        a max_queue_delay_ms window; compatible requests (same feed
        signature) join until the window closes or max_batch_size rows
        are reached. Returns [] transiently, None when shut down and
        fully drained. Future resolution (shed, cancel) always happens
        OUTSIDE the lock — see _pop_live_locked."""
        while True:
            shed = []
            with self._lock:
                while not self._queue:
                    if self._shutdown:
                        return None
                    self._not_empty.wait(_POLL_S)
                first = self._pop_live_locked(time.monotonic(), shed)
            self._fail_shed(shed)
            if first is None:
                return []
            if first.future.set_running_or_notify_cancel():
                break  # cancelled-while-queued requests are dropped
        batch, rows = [first], first.n
        horizon = time.monotonic() + self.config.max_queue_delay_ms / 1000.0
        while rows < self.config.max_batch_size:
            shed, req, closed, sealed = [], None, False, False
            with self._lock:
                if self._queue:
                    req = self._pop_live_locked(time.monotonic(), shed)
                    if req is not None and (
                            req.sig != first.sig or
                            rows + req.n > self.config.max_batch_size):
                        # expired heads are shed INSIDE the pop, so the
                        # request it returns need not be the head that
                        # was visible beforehand — compatibility must be
                        # checked after popping, never before. A request
                        # with a different signature (np.concatenate
                        # would fail or promote dtypes) or one that
                        # overflows the row budget (pick_bucket would
                        # raise) goes back to the front and opens the
                        # NEXT batch instead.
                        self._queue.appendleft(req)
                        _G_QDEPTH.set(len(self._queue))
                        req, sealed = None, True
                elif self._shutdown:
                    closed = True  # draining: don't wait for more traffic
            self._fail_shed(shed)
            if sealed or closed:
                break
            if req is not None:
                if req.future.set_running_or_notify_cancel():
                    batch.append(req)
                    rows += req.n
                continue
            remaining = horizon - time.monotonic()
            if remaining <= 0:
                break
            with self._lock:
                if not self._queue and not self._shutdown:
                    self._not_empty.wait(min(_POLL_S, remaining))
        return batch

    def _batcher_loop(self):
        while True:
            batch = self._collect()
            if batch is None:
                break
            if not batch:
                continue
            if self._shutdown and not self._drain:
                for req in batch:
                    req.future.set_exception(ServerClosed(
                        'serving engine shut down without draining'))
                continue
            try:
                self._execute(batch)
            except BaseException as e:  # noqa: BLE001 — thread last resort
                # _execute routes model/assembly errors to the batch's
                # futures itself; anything escaping it is an engine bug.
                # Fail the batch rather than letting the exception kill
                # the batcher thread silently — a dead batcher strands
                # every queued future and blocks all later submits.
                self._n_batch_errors += 1
                self._n_inflight = 0   # _execute died before its reset
                _C_BATCH_ERRORS.inc()
                obs.event('serving.batch.error', requests=len(batch),
                          error='batcher guard: %s: %s'
                                % (type(e).__name__, e))
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    def _run_with_retry(self, feed):
        if self.config.max_retries <= 0:
            return self._model_fn(feed)
        from ..utils import retry as retry_mod
        return retry_mod.retry_call(
            self._model_fn, args=(feed,),
            retries=self.config.max_retries,
            base_delay=self.config.retry_base_delay_ms / 1000.0,
            retry_on=(Exception,), seed=self.config.retry_seed,
            site='serving.batch')

    def _execute(self, batch):
        now = time.monotonic()
        rows = sum(r.n for r in batch)
        self._n_inflight = rows
        waits = [now - r.t_submit for r in batch]
        # batch ASSEMBLY failures (bucket lookup, concat, padding) must
        # resolve the futures exactly like model failures do — an
        # exception escaping here would kill the batcher thread
        try:
            bucket = _buckets.pick_bucket(rows, self.buckets)
            for w in waits:
                _H_QWAIT.observe(w)
            _H_BATCH_SIZE.observe(rows)
            self._n_batches += 1
            self._n_padded_rows += bucket - rows
            _C_BATCHES.inc()
            _C_PAD_ROWS.inc(bucket - rows)
            feed = {}
            for name in self.feed_names:
                merged = np.concatenate(
                    [r.feed[name] for r in batch], axis=0) \
                    if len(batch) > 1 else batch[0].feed[name]
                feed[name] = _buckets.pad_rows(merged, bucket)
            with obs.span('serving.batch', requests=len(batch),
                          batch_size=rows, bucket=bucket,
                          padded=bucket - rows,
                          wait_max_s=max(waits)) as sp:
                outs = self._run_with_retry(feed)
                sp.fields['warm'] = self._warm
            outs = [np.asarray(o) for o in outs]
            if self._per_row_outputs is not None:
                bad = sorted(i for i in self._per_row_outputs
                             if i >= len(outs))
                if bad:
                    raise ValueError(
                        'per_row_outputs %r out of range: the model '
                        'returned %d output(s)' % (bad, len(outs)))
        except Exception as e:  # noqa: BLE001 — the batch's futures own it
            self._n_batch_errors += 1
            _C_BATCH_ERRORS.inc()
            obs.event('serving.batch.error', requests=len(batch),
                      batch_size=rows,
                      error='%s: %s' % (type(e).__name__, e))
            for req in batch:
                req.future.set_exception(e)
            self._n_inflight = 0
            return
        per_row = self._per_row_outputs
        off = 0
        for req in batch:
            # declared per-row outputs scatter back to their request's
            # rows; undeclared engines fall back to the leading-dim
            # heuristic (see the class docstring for its failure mode);
            # everything else (batch-level aggregates) replicates whole
            req.future.set_result([
                o[off:off + req.n]
                if (i in per_row if per_row is not None
                    else (o.ndim and o.shape[0] == bucket))
                else o for i, o in enumerate(outs)])
            off += req.n
            self._n_completed += 1
            with self._lock:
                self._win['completed'] += 1
        self._n_inflight = 0
