"""paddle_tpu.serving — dynamic micro-batching serving engine.

The inference counterpart of the fault-tolerant training runtime: wraps
a loaded model (`paddle_tpu.inference.Predictor` or a `load_compiled`
StableHLO runner) behind an async request API with continuous
micro-batching, shape buckets (a closed set of compiled signatures +
startup warmup = zero steady-state compiles), admission control with
typed overload errors, per-request deadlines, and a draining shutdown.
See docs/serving.md; run the serving test tier with `pytest -m serving`.

    from paddle_tpu import inference, serving

    pred = inference.Predictor(model_dir)
    eng = serving.ServingEngine(pred, serving.ServingConfig(
        max_batch_size=32, max_queue_delay_ms=5))
    eng.warmup()                       # pre-compile every bucket
    fut = eng.submit({'x': batch})     # concurrent.futures.Future
    probs, = fut.result()
    eng.shutdown()                     # drains in-flight requests
"""
from . import buckets  # noqa: F401
from . import pages  # noqa: F401
from . import transport  # noqa: F401
from .buckets import default_buckets, pad_rows, pick_bucket  # noqa: F401
from .pages import PagePool, PrefixCache  # noqa: F401
from .decode import (DecodeConfig, DecodeEngine,  # noqa: F401
                     DecodeSlotPoisoned, LockstepDecoder, StreamCancelled,
                     mt_weights, program_prefill)
from .engine import (DeadlineExceeded, ServerClosed,  # noqa: F401
                     ServerOverloaded, ServingConfig, ServingEngine)
from .router import (ModelOverloaded, Router,  # noqa: F401
                     TokenStream, UnknownModel, estimate_state_bytes)
from .transport import Channel, RpcServer, TransportError  # noqa: F401
from .pod import (AutoscalePolicy, Autoscaler, PodRouter,  # noqa: F401
                  PodWorker, RemoteReplica, RpcReplica, ShardedPredictor,
                  save_serving_program, sharded_replica)

__all__ = ['ServingEngine', 'ServingConfig', 'ServerOverloaded',
           'ServerClosed', 'DeadlineExceeded', 'buckets',
           'default_buckets', 'pick_bucket', 'pad_rows',
           'DecodeConfig', 'DecodeEngine', 'DecodeSlotPoisoned',
           'LockstepDecoder', 'StreamCancelled', 'mt_weights',
           'program_prefill',
           'Router', 'ModelOverloaded', 'TokenStream', 'UnknownModel',
           'estimate_state_bytes',
           'pages', 'PagePool', 'PrefixCache',
           'transport', 'Channel', 'RpcServer', 'TransportError',
           'PodRouter', 'PodWorker', 'RemoteReplica', 'RpcReplica',
           'ShardedPredictor', 'sharded_replica', 'save_serving_program',
           'AutoscalePolicy', 'Autoscaler']
