"""Pod-scale serving: sharded replicas, cross-host routing, self-healing.

The serving counterpart of the elastic training runtime
(docs/robustness.md#elastic): one `set_mesh`-annotated Program served
as a single Router replica across the devices of a host, replicas
registered across MANY hosts behind one front door, and capacity that
heals itself when a host dies. Three layers (docs/serving.md#pod):

  * SHARDED REPLICAS — :class:`ShardedPredictor` loads an inference
    Program (program only, no dense params) onto a device mesh and
    restores its weights straight from a SHARDED checkpoint
    (`utils.checkpoint.load_latest_verified(mesh=...)` →
    `Executor.load_state_dict`): a row-sharded embedding table or a
    tensor-parallel decoder comes up WITHOUT ever materializing dense
    on any host, and the GSPMD executor serves it through the same
    all_to_all lookup wire training proved (docs/embedding.md). Feeds
    replicate (`set_mesh(..., data_axis=False)`), so every serving
    bucket works regardless of the mesh shape.
  * POD-AWARE ROUTING — :class:`PodWorker` registers a host's replicas
    into a shared-filesystem registry (the heartbeat/checkpoint
    posture: dependency-free, atomic-replace files) and serves their
    request spools; :class:`PodRouter` watches the registry, wraps each
    remote replica in an engine-protocol :class:`RemoteReplica` proxy,
    and runs the EXISTING Router semantics — least-loaded dispatch,
    quotas, swap, push_deltas — across process boundaries through the
    one replica abstraction (`Router.add_replica(..., host=, key=)`).
  * SELF-HEALING — each host heartbeats (`parallel.Heartbeat`); a stale
    host surfaces as the typed `HostLost`, its replicas are detached,
    every future still pending against them is RE-ROUTED to survivors
    (zero dropped futures — the router holds each request's feed until
    its response lands), and a heal command asks a surviving host to
    re-shard the replica onto its own topology via the same
    `load_latest_verified(mesh=...)` restore path. Queue-depth-driven
    :class:`Autoscaler` rides the same add/drain machinery for
    scale-up/down with zero-downtime cutover.

Events: serving.replica.{register,drain,lost,reshard}, the
router.pod_size gauge, and an obs_report `-- pod serving --` section
(docs/observability.md). Drilled by tests/test_pod_serving.py
(`pod` marker) and measured by `serve_bench --workload pod-sharded`.
"""
import collections
import concurrent.futures
import json
import os
import threading
import time
import uuid

import numpy as np

from .. import obs
from ..obs import trace
from .engine import (DeadlineExceeded, DeltaUnsupported, ServerClosed,
                     ServerOverloaded, ServingConfig, ServingEngine)
from .router import Router
from .transport import Channel, RpcServer, TransportError

__all__ = ['ShardedPredictor', 'save_serving_program', 'sharded_replica',
           'PodWorker', 'PodRouter', 'RemoteReplica', 'RpcReplica',
           'AutoscalePolicy', 'Autoscaler']

_C_REROUTED = obs.counter('serving.pod.rerouted_futures')
_C_HEALS = obs.counter('serving.pod.heals')
# stream failover accounting: failovers = live streams whose serving
# host died; resumes = the subset brought back token-exact from a
# decode-state checkpoint (failovers - resumes = typed HostLost streams)
_C_STREAM_FAILOVERS = obs.counter('serving.stream.failovers')
_C_STREAM_RESUMES = obs.counter('serving.stream.resumes')

# wire poll cadence: the spool transport is filesystem mailboxes, read
# at this period (same order as the engine's _POLL_S)
_POLL_S = 0.02

# One host, many sharded replicas: two compiled modules ISSUING
# COLLECTIVES (the all_to_all lookup wire) must never interleave on the
# same devices — XLA's rendezvous would pair participants across the
# two modules and deadlock. Replicas co-hosted on one process share the
# physical chips anyway, so serializing their dispatches costs nothing
# but removes the hazard (docs/serving.md#pod).
_MESH_DISPATCH_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# sharded replicas: program-only load + sharded-checkpoint restore
# ---------------------------------------------------------------------------

def save_serving_program(dirname, feeded_var_names, target_vars,
                         main_program=None, model_filename=None):
    """Save ONLY the pruned inference Program (no parameters) — the
    pod-serving artifact: a 100GB-table model's weights live in the
    SHARDED checkpoint (`utils.checkpoint.save_sharded`), never in a
    dense params file, so neither the save nor the load ever gathers a
    table whole (`fluid.io.save_inference_model` would —
    docs/serving.md#pod). The program keeps its mesh spec and sharding
    annotations through serialization; :class:`ShardedPredictor` is the
    loader. Returns the program file path."""
    from ..fluid import framework, io
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    if main_program is None:
        main_program = framework.default_main_program()
    infer = main_program.clone(for_test=True).prune(list(target_vars))
    os.makedirs(dirname, exist_ok=True)
    meta = {
        'program': infer._to_dict(),
        'feed_names': list(feeded_var_names),
        'fetch_names': [v.name if isinstance(v, framework.Variable)
                        else str(v) for v in target_vars],
    }
    path = os.path.join(dirname, model_filename or io._PROGRAM_FILE)
    _atomic_json(path, meta)
    return path


class ShardedPredictor(object):
    """Predictor over a `set_mesh`-annotated Program with weights
    restored from a SHARDED checkpoint — the sharded-replica loader
    (docs/serving.md#pod).

    Loads the saved inference Program WITHOUT its dense params file,
    asserts/overrides the mesh (`mesh_axes`), and restores every
    persistable via `utils.checkpoint.load_latest_verified(ckpt_dir,
    mesh=...)` → `Executor.load_state_dict`: each array is assembled
    shard-by-shard onto this host's devices per its annotation — a
    vocab-sharded table arrives as per-device row shards and is NEVER
    materialized dense anywhere (reshard-on-restore covers a checkpoint
    written on a different topology). Inference then runs through the
    plain GSPMD executor — a row-sharded `lookup_table` takes the same
    all_to_all wire as training (docs/embedding.md), now on the serving
    path. Feeds REPLICATE by default (`data_axis=False`), so any
    serving bucket size works on any mesh; pass `data_axis='dp'` to
    shard request batches instead (buckets must then divide the axis).

    Drop-in for `inference.Predictor` wherever the serving engine
    expects one (run/feed_names/fetch_names/input_spec, private
    program/scope/executor seams — `push_rows` row-delta freshness
    works against the sharded table too)."""

    def __init__(self, model_dir, mesh_axes=None, ckpt_dir=None,
                 place=None, model_filename=None, data_axis=False):
        from .. import parallel
        from ..fluid import analysis, core, io
        from ..fluid.executor import Executor, Scope
        from ..fluid.framework import Program

        with open(os.path.join(model_dir,
                               model_filename or io._PROGRAM_FILE)) as f:
            meta = json.load(f)
        prog = Program._from_dict(meta['program'])
        axes = mesh_axes if mesh_axes is not None else prog.mesh_axes
        if not axes:
            raise ValueError(
                'ShardedPredictor needs a mesh: the saved program at %r '
                'carries no set_mesh spec and no mesh_axes= was given '
                '(an un-annotated model belongs in inference.Predictor)'
                % (model_dir,))
        prog.set_mesh(dict(axes), data_axis=data_axis)
        self._scope = Scope()
        self._place = place or (core.TPUPlace(0)
                                if core.is_compiled_with_tpu()
                                else core.CPUPlace())
        self._exe = Executor(self._place)
        self._program = prog
        self.feed_names = list(meta['feed_names'])
        self._fetch_vars = [prog.global_block()._var_recursive(n)
                            for n in meta['fetch_names']]
        analysis.maybe_verify(
            prog, where='predictor', feeds=list(self.feed_names),
            fetches=[v.name for v in self._fetch_vars], concurrent=True)
        self.mesh = parallel.make_mesh(dict(prog.mesh_axes))
        self.state_step = None
        if ckpt_dir is not None:
            self._restore_sharded(ckpt_dir)
        else:
            # dense fallback: a small model saved the classic way still
            # serves sharded (load_persistables reads the params file,
            # load-time placement shards per the annotations)
            io.load_persistables(self._exe, model_dir, prog,
                                 scope=self._scope)

    @staticmethod
    def _referenced_names(program):
        """Every var name an op of `program` reads/writes, including
        names referenced through string attrs (control-flow rules
        resolve env by attr name — the decode idiom)."""
        out = set()

        def from_attr(a):
            if isinstance(a, str):
                out.add(a)
            elif isinstance(a, (list, tuple)):
                for x in a:
                    from_attr(x)
            elif isinstance(a, dict):
                for x in a.values():
                    from_attr(x)

        for blk in program.blocks:
            for op in blk.ops:
                for vs in list(op.inputs.values()) \
                        + list(op.outputs.values()):
                    for v in (vs if isinstance(vs, (list, tuple))
                              else [vs]):
                        out.add(getattr(v, 'name', v) if not
                                isinstance(v, str) else v)
                for a in op.attrs.values():
                    from_attr(a)
        return out

    def _restore_sharded(self, ckpt_dir):
        from ..utils import checkpoint as ck
        # prune() keeps dead optimizer vars LISTED; only persistables an
        # op actually references must come out of the checkpoint
        used = self._referenced_names(self._program)
        pvars = {v.name for v in self._program.list_vars()
                 if v.persistable and v.name in used}
        with obs.span('serving.sharded_restore',
                      dir=os.path.basename(str(ckpt_dir))) as sp:
            arrays, meta = ck.load_latest_verified(ckpt_dir,
                                                   mesh=self.mesh)
            # train-only state (optimizer moments) is legitimately
            # absent from an inference program: filter BEFORE
            # load_state_dict so the restore is quiet, then check the
            # program side is fully covered
            state = {n: a for n, a in arrays.items() if n in pvars}
            self._exe.load_state_dict(state, self._program,
                                      scope=self._scope)
            missing = sorted(pvars - set(state))
            if missing:
                raise RuntimeError(
                    'sharded checkpoint %r restores %d of %d program '
                    'persistables; missing: %s — the serving program '
                    'and the training checkpoint disagree'
                    % (ckpt_dir, len(state), len(pvars), missing[:8]))
            self.state_step = meta.get('step')
            sp.fields['restored'] = len(state)
            sp.fields['step'] = self.state_step

    @property
    def fetch_names(self):
        return [v.name for v in self._fetch_vars]

    @property
    def input_spec(self):
        blk = self._program.global_block()
        spec = {}
        for n in self.feed_names:
            v = blk.vars.get(n)
            if v is not None:
                spec[n] = (tuple(int(d) for d in v.shape), str(v.dtype))
        return spec

    def shard_shapes(self):
        """{name: per-device shard shape} for every multi-device
        persistable — the never-dense assertion surface (a VOCAB-row
        table on an 8-way mesh must report VOCAB/8 rows per device)."""
        out = {}
        for n, v in self._scope.vars.items():
            shards = getattr(v, 'addressable_shards', None)
            if shards and len(getattr(v.sharding, 'device_set', ())) > 1:
                out[n] = tuple(shards[0].data.shape)
        return out

    def run(self, feed):
        # the process-wide mesh-dispatch lock: a co-hosted replica's
        # collectives must not interleave with ours (see _MESH_DISPATCH_LOCK)
        with _MESH_DISPATCH_LOCK:
            return self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars,
                                 scope=self._scope)


def sharded_replica(model_dir, mesh_axes=None, ckpt_dir=None, config=None,
                    warm=True, example_feed=None, **predictor_kwargs):
    """One call from artifacts to a warmed sharded replica: build a
    :class:`ShardedPredictor` and wrap it in a `ServingEngine` (every
    bucket pre-compiled when `warm`). This is the builder shape the
    pod's heal path wants: `lambda reason: sharded_replica(...)`."""
    pred = ShardedPredictor(model_dir, mesh_axes=mesh_axes,
                            ckpt_dir=ckpt_dir, **predictor_kwargs)
    eng = ServingEngine(pred, config or ServingConfig())
    if warm:
        eng.warmup(example_feed)
    return eng


# ---------------------------------------------------------------------------
# wire: filesystem mailboxes (the heartbeat/checkpoint posture)
# ---------------------------------------------------------------------------

def _registry_dir(pod_dir):
    return os.path.join(pod_dir, 'registry')


def _beats_dir(pod_dir):
    return os.path.join(pod_dir, 'beats')


def _spool_dir(pod_dir, key):
    return os.path.join(pod_dir, 'spool', str(key))


def _ctl_dir(pod_dir, host):
    return os.path.join(pod_dir, 'ctl', 'h%d' % int(host))


def _streams_dir(pod_dir):
    # per-stream decode-state checkpoints (ckpt.<sid>.npz): written by
    # the SERVING worker at the stream's ckpt_every cadence, read by the
    # router's failover path to resume on a survivor token-exact
    return os.path.join(pod_dir, 'streams')


def _traces_dir(pod_dir):
    # per-process trace-span spill files (spans.p<pid>.json): every
    # participant (router + each worker) dumps its bounded span buffer
    # here on its stats cadence; obs.trace.TraceCollector stitches the
    # per-host files into end-to-end timelines, flagging spans a dead
    # host never closed as orphans (docs/observability.md#distributed-tracing)
    return os.path.join(pod_dir, trace.TRACE_DIR)


def _atomic_json(path, obj):
    tmp = '%s.tmp%d' % (path, os.getpid())
    with open(tmp, 'w') as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _atomic_npz(path, **arrays):
    # the tmp name must NOT keep the .npz suffix: spool/ctl scanners
    # match on it, and a scanner consuming a half-written tmp file both
    # corrupts the read AND makes the final os.replace fail
    tmp = '%s.tmp%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


# typed errors cross the wire by name — the caller gets the SAME typed
# signal it would from an in-process engine (docs/serving.md#pod)
_TYPED_ERRORS = {
    'ServerOverloaded': ServerOverloaded,
    'ServerClosed': ServerClosed,
    'DeadlineExceeded': DeadlineExceeded,
    'DeltaUnsupported': DeltaUnsupported,
    'TransportError': TransportError,
    'ValueError': ValueError,
    'TypeError': TypeError,
    'KeyError': KeyError,
}


def _register_typed_errors():
    """Late-bound typed errors (their modules import lazily elsewhere in
    this file for the same reason): HostLost from the elastic runtime,
    StreamCancelled from the decode engine."""
    if 'HostLost' in _TYPED_ERRORS:
        return
    from ..parallel import HostLost
    from .decode import StreamCancelled
    _TYPED_ERRORS['HostLost'] = HostLost
    _TYPED_ERRORS['StreamCancelled'] = StreamCancelled


def _encode_error(exc):
    return json.dumps({'type': type(exc).__name__, 'message': str(exc)})


def _error_from_dict(d):
    _register_typed_errors()
    cls = _TYPED_ERRORS.get(d.get('type'), RuntimeError)
    return cls(d.get('message', 'remote replica error'))


def _decode_error(payload):
    try:
        d = json.loads(payload)
    except ValueError:
        return RuntimeError(str(payload))
    return _error_from_dict(d)


def _complete(fut, result=None, exc=None):
    """Resolve a future that may have been cancelled (predict() timeout)
    or already completed by a racing re-route — never raise into the
    poller/worker thread."""
    try:
        if not fut.set_running_or_notify_cancel():
            return False
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except Exception:        # InvalidStateError: already resolved
        return False


def _chain(src, dst):
    """Copy src's outcome into dst when src resolves (the re-route
    splice: the caller keeps ITS future; a survivor's future feeds it)."""
    def cb(f):
        if f.cancelled():
            dst.cancel()
            return
        e = f.exception()
        if e is not None:
            _complete(dst, exc=e)
        else:
            _complete(dst, result=f.result())
    src.add_done_callback(cb)


# ---------------------------------------------------------------------------
# PodWorker: a host's replicas, served from the shared registry
# ---------------------------------------------------------------------------

class PodWorker(object):
    """One serving HOST of the pod: registers replicas into the shared
    registry, answers their request spools, heartbeats, and heals —
    builds replacement replicas on a `heal` control command through the
    builders it was constructed with (docs/serving.md#pod).

    pod_dir: the shared directory (every host + the router must see it;
        the checkpoint filesystem is the natural choice).
    host: this host's integer id (beat files are per-host).
    builders: {model_id: callable(reason) -> warmed engine} — the heal
        path; a host with no builder for a model simply never receives
        its heal commands. `sharded_replica` closures are the intended
        shape: the replacement re-shards the checkpoint onto THIS
        host's topology (`load_latest_verified(mesh=...)`).
    transport: 'file' (atomic-npz spool mailboxes, PR 14's wire) or
        'rpc' (persistent TCP, serving/transport.py). The rpc wire is
        ADDITIVE: registry, beats, heal control, and stats publishing
        stay on the shared filesystem either way — only the request/
        response/stream hop moves to the socket, so the two wires stay
        drop-in interchangeable behind one seam (docs/serving.md#pod).
    rpc_max_inflight: per-connection wire admission cap (rpc only);
        a connection over it gets typed ServerOverloaded frames
        before the handler runs.
    """

    def __init__(self, pod_dir, host, builders=None, beat_interval=0.25,
                 stats_interval_s=0.2, poll_s=_POLL_S, transport='file',
                 rpc_max_inflight=64):
        from ..parallel import Heartbeat
        if transport not in ('file', 'rpc'):
            raise ValueError("transport must be 'file' or 'rpc', not %r"
                             % (transport,))
        self.pod_dir = str(pod_dir)
        self.host = int(host)
        self.transport = str(transport)
        self._builders = dict(builders or {})
        self._poll_s = float(poll_s)
        self._stats_every = float(stats_interval_s)
        for d in (_registry_dir(self.pod_dir), _beats_dir(self.pod_dir),
                  _ctl_dir(self.pod_dir, self.host),
                  _streams_dir(self.pod_dir)):
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._replicas = {}          # key -> dict(engine, thread, stop)
        self._last_telemetry_t = 0.0
        self._serial = 0
        self._stop = threading.Event()
        self._frozen = False         # simulate_death(): loops stall
        self._rpc = None
        if self.transport == 'rpc':
            self._rpc = RpcServer(self._rpc_handle,
                                  max_inflight=rpc_max_inflight,
                                  on_close=self._rpc_conn_closed)
        self.heartbeat = Heartbeat(_beats_dir(self.pod_dir),
                                   process_id=self.host, num_processes=0,
                                   interval=beat_interval)
        self.heartbeat.start()
        advert = {'host': self.host, 'pid': os.getpid(),
                  'transport': self.transport,
                  'builders': sorted(str(m) for m in self._builders)}
        if self._rpc is not None:
            advert['addr'] = list(self._rpc.addr)
        _atomic_json(os.path.join(_registry_dir(self.pod_dir),
                                  'host.%d.json' % self.host), advert)
        self._ctl_thread = threading.Thread(
            target=self._ctl_loop, name='pod-worker-ctl-h%d' % self.host,
            daemon=True)
        self._ctl_thread.start()

    # -- replica lifecycle -------------------------------------------------

    def serve(self, model_id, engine, name=None, heal_token=None,
              mesh=None):
        """Register `engine` as a replica of `model_id` and start
        answering its spool. Returns the registry key. The engine should
        already be WARM (every bucket pre-compiled) — registration makes
        it routable immediately."""
        with self._lock:
            self._serial += 1
            key = '%d.%s' % (self.host,
                             name if name is not None else
                             '%s-%d' % (model_id, self._serial))
            if key in self._replicas:
                raise ValueError('replica key %r already served' % key)
        spool = _spool_dir(self.pod_dir, key)
        os.makedirs(spool, exist_ok=True)
        if mesh is None:
            prog = getattr(getattr(engine, '_model', None), '_program',
                           None)
            axes = getattr(prog, 'mesh_axes', None)
            mesh = sorted(axes.items()) if axes else None
        stop = threading.Event()
        rec = {'engine': engine, 'stop': stop, 'spool': spool,
               'model_id': str(model_id),
               'stats_lock': threading.Lock()}
        t = threading.Thread(target=self._replica_loop, args=(key, rec),
                             name='pod-worker-%s' % key, daemon=True)
        rec['thread'] = t
        with self._lock:
            self._replicas[key] = rec
        self._publish_stats(key, rec)       # stats exist before routing
        reg = {'model_id': str(model_id), 'host': self.host, 'key': key,
               'pid': os.getpid(), 'mesh': mesh,
               'transport': self.transport,
               'feed_names': list(getattr(engine, 'feed_names', []) or []),
               'buckets': [int(b) for b in
                           getattr(engine, 'buckets', ()) or ()]}
        if self._rpc is not None:
            reg['addr'] = list(self._rpc.addr)
        if heal_token is not None:
            reg['heal_token'] = str(heal_token)
        t.start()
        _atomic_json(os.path.join(_registry_dir(self.pod_dir),
                                  'replica.%s.json' % key), reg)
        obs.event('serving.replica.register', model=str(model_id),
                  host=self.host, key=key,
                  healed=heal_token is not None)
        return key

    def retire(self, key, drain=True, timeout=None):
        """Deregister one replica (registry entry removed first, so the
        router stops routing to it) and drain its engine."""
        with self._lock:
            rec = self._replicas.pop(key, None)
        if rec is None:
            return False
        try:
            os.remove(os.path.join(_registry_dir(self.pod_dir),
                                   'replica.%s.json' % key))
        except OSError:
            pass
        rec['stop'].set()
        rec['thread'].join(timeout or 10.0)
        ok = rec['engine'].shutdown(drain=drain, timeout=timeout)
        obs.event('serving.replica.drain', model=rec['model_id'],
                  host=self.host, key=key, drain=bool(drain),
                  reason='retired')
        return ok

    def served(self):
        with self._lock:
            return sorted(self._replicas)

    def shutdown(self, drain=True, timeout=None):
        """Retire every replica, stop the heartbeat (peers will judge
        this host stale, correct for a stopping host), remove the host
        registration."""
        self._stop.set()
        ok = True
        for key in self.served():
            ok = self.retire(key, drain=drain, timeout=timeout) and ok
        self._host_telemetry(force=True)   # final spill: no span lost
        self.heartbeat.stop()
        if self._rpc is not None:
            self._rpc.close()
        try:
            os.remove(os.path.join(_registry_dir(self.pod_dir),
                                   'host.%d.json' % self.host))
        except OSError:
            pass
        return ok

    def simulate_death(self):
        """Test harness: stop beating and freeze every loop WITHOUT
        cleanup — indistinguishable from a SIGKILLed host to the
        router (beats stale, registration files orphaned, spooled
        requests never answered; rpc sockets stay OPEN but go silent,
        the wedged-process picture the heartbeat must see through)."""
        self._frozen = True
        if self._rpc is not None:
            self._rpc.freeze()
        self.heartbeat.stop()

    # -- spool service -----------------------------------------------------

    def _replica_loop(self, key, rec):
        engine, spool, stop = rec['engine'], rec['spool'], rec['stop']
        # requests taken but not yet answered: a request file stays on
        # disk until its response is written (crash-visible), so the
        # scan must skip what it already submitted
        rec['inflight'] = set()
        last_stats = 0.0
        while not stop.is_set() and not self._stop.is_set():
            if self._frozen:
                time.sleep(self._poll_s)
                continue
            try:
                names = sorted(os.listdir(spool))
            except OSError:
                names = []
            worked = False
            for fname in names:
                if stop.is_set() or self._frozen:
                    break
                path = os.path.join(spool, fname)
                if fname.startswith('rq.') and fname.endswith('.npz'):
                    if fname[3:-4] in rec['inflight']:
                        continue
                    worked = True
                    self._serve_request(engine, spool, path, fname,
                                        rec['inflight'])
                elif fname.startswith('push.') and fname.endswith('.npz'):
                    worked = True
                    self._serve_push(engine, spool, path, fname)
                elif fname == 'retire.json':
                    os.remove(path)
                    # deregister THEN drain, like retire()
                    threading.Thread(target=self.retire, args=(key,),
                                     daemon=True).start()
                    return
            now = time.monotonic()
            if now - last_stats >= self._stats_every:
                self._publish_stats(key, rec)
                last_stats = now
            if not worked:
                time.sleep(self._poll_s)

    def _serve_request(self, engine, spool, path, fname, inflight):
        uid = fname[3:-4]
        rs = os.path.join(spool, 'rs.%s.npz' % uid)
        inflight.add(uid)

        def respond(outs=None, exc=None):
            try:
                if exc is not None:
                    _atomic_npz(rs, __error__=np.frombuffer(
                        _encode_error(exc).encode(), np.uint8))
                else:
                    _atomic_npz(rs, **{'o:%d' % i: np.asarray(o)
                                       for i, o in enumerate(outs)})
            except Exception:
                pass
            try:
                os.remove(path)
            except OSError:
                pass
            inflight.discard(uid)

        try:
            with np.load(path, allow_pickle=False) as z:
                kwargs = json.loads(bytes(z['__meta__']).decode())
                feed = {k[2:]: z[k] for k in z.files if k.startswith('f:')}
        except Exception:
            # torn/unreadable request: leave it one cycle (the writer
            # replaces atomically, so this is a transient FS hiccup)
            inflight.discard(uid)
            return
        # the request JSON carries the caller's trace context; re-enter
        # it so this host's spans/events stitch into the same timeline
        tr = trace.from_headers(kwargs.pop('trace', None))
        h = trace.begin('serving.pod.serve', ctx=tr,
                        node='h%d' % self.host, uid=uid, wire='file')
        try:
            if h is not None:
                h.mark('trace.dispatch')
            with trace.activate(h.ctx if h is not None else None,
                                node='h%d' % self.host):
                fut = engine.submit(feed, **kwargs)
        except Exception as e:  # noqa: BLE001 — typed back to the caller
            if h is not None:
                h.end(error=type(e).__name__)
            respond(exc=e)
            return

        def done(f, _h=h):
            if self._frozen:
                # SIGKILL fidelity: a dead host answers nothing, and its
                # serve span stays OPEN — the spilled open span is the
                # orphan the trace collector flags
                return
            try:
                e = f.exception()
            except concurrent.futures.CancelledError as ce:
                e = ce
            respond(outs=None if e is not None else f.result(), exc=e)
            if _h is not None:
                _h.end(error=type(e).__name__ if e is not None else None)
        fut.add_done_callback(done)

    def _serve_push(self, engine, spool, path, fname):
        uid = fname[5:-4]
        ack = os.path.join(spool, 'pushok.%s.json' % uid)
        try:
            with np.load(path, allow_pickle=False) as z:
                meta = {}
                if '__meta__' in z.files:
                    try:
                        meta = json.loads(bytes(z['__meta__']).decode())
                    except ValueError:
                        meta = {}
                deltas = {}
                for k in z.files:
                    if k.startswith('i:'):
                        name = k[2:]
                        deltas[name] = (z[k], z['r:%s' % name])
            with trace.activate(trace.from_headers(meta.get('trace')),
                                node='h%d' % self.host):
                rows = engine.push_rows(deltas)
            _atomic_json(ack, {'ok': True, 'rows': int(rows)})
        except Exception as e:  # noqa: BLE001 — typed back to the caller
            _atomic_json(ack, {'ok': False,
                               'error': _encode_error(e)})
        try:
            os.remove(path)
        except OSError:
            pass

    def _publish_stats(self, key, rec):
        """Fold the engine's window into cumulative counters and write
        stats.json; returns the payload (the rpc 'stats' op replies
        with it directly). Serialized per replica: the file loop and
        rpc reader threads both publish, and the read-and-reset window
        must fold into `cum` exactly once."""
        engine = rec['engine']
        with rec.setdefault('stats_lock', threading.Lock()):
            cum = rec.setdefault('cum', collections.Counter())
            try:
                win = engine.stats_window()
            except Exception:
                return None
            live = {}
            for k in ('queue_depth', 'inflight', 'capacity', 'slots',
                      'pages_free', 'pages_total'):
                if k in win:
                    live[k] = win.pop(k)
            hw = win.pop('queue_high_water', 0)
            for k, v in win.items():
                if isinstance(v, (int, float)):
                    cum[k] += v
            exe = getattr(getattr(engine, '_model', None), '_exe', None)
            cache = {}
            if exe is not None:
                cs = exe.cache_stats
                cache = {'online_compiles': cs.get('online_compiles'),
                         'misses': cs.get('misses')}
            rec['stats_seq'] = rec.get('stats_seq', 0) + 1
            payload = {'seq': rec['stats_seq'], 'cum': dict(cum),
                       'live': live, 'queue_high_water': hw,
                       'cache': cache}
            _atomic_json(os.path.join(rec['spool'], 'stats.json'),
                         payload)
        self._host_telemetry()
        return payload

    def _host_telemetry(self, force=False):
        """Host-wide observability dumps riding the stats cadence: the
        trace-span spill (traces/spans.p<pid>.json, the collector's
        input) and the Prometheus exposition file (metrics.h<host>.prom)
        — scrape surfaces needing no live server. A frozen (simulated-
        dead) host stops dumping, so its LAST spill still holds the
        open spans the collector flags as orphans."""
        if self._frozen:
            return
        now = time.monotonic()
        if not force and now - self._last_telemetry_t < self._stats_every:
            return
        self._last_telemetry_t = now
        try:
            trace.spill(_traces_dir(self.pod_dir))
        except Exception:  # noqa: BLE001 — telemetry must not kill serving
            pass
        try:
            path = os.path.join(self.pod_dir,
                                'metrics.h%d.prom' % self.host)
            tmp = '%s.tmp%d' % (path, os.getpid())
            with open(tmp, 'w') as f:
                f.write(obs.metrics.render_prom())
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — same
            pass

    # -- rpc service (transport='rpc'; serving/transport.py) ---------------

    def _rec(self, key):
        with self._lock:
            rec = self._replicas.get(key)
        if rec is None or self._frozen:
            raise ServerClosed('no replica %r on host %d'
                               % (key, self.host))
        return rec

    def _rpc_handle(self, conn, header, arrays):
        """Dispatch one frame (runs on the connection's reader thread —
        a blocking engine.submit() here IS the wire backpressure: this
        connection stops reading and the client's TCP window fills).
        Exceptions cross back as typed error frames (transport layer)."""
        op = header.get('op')
        if op == 'submit':
            self._rpc_submit(conn, header, arrays)
        elif op == 'push':
            self._rpc_push(conn, header, arrays)
        elif op == 'stats':
            payload = self._publish_stats(header.get('key'),
                                          self._rec(header.get('key')))
            conn.send({'uid': header.get('uid'), 'final': True,
                       'stats': payload or {}})
        elif op == 'metrics':
            # Prometheus text exposition over the wire: one frame in,
            # one final frame out carrying the whole registry — the
            # scrape path for deployments that never mount pod_dir
            conn.send({'uid': header.get('uid'), 'final': True,
                       'prom': obs.metrics.render_prom()})
        elif op == 'retire':
            ok = self.retire(header.get('key'),
                             drain=bool(header.get('drain', True)),
                             timeout=header.get('timeout'))
            conn.send({'uid': header.get('uid'), 'final': True,
                       'ok': bool(ok)})
        elif op == 'cancel':
            # fire-and-forget: the cancelled submit's own final frame
            # (typed StreamCancelled) is the acknowledgement
            entry = (conn.state.get('futs') or {}).get(
                header.get('cancel_uid'))
            if entry is not None:
                fut, engine = entry
                cancel = getattr(engine, 'cancel', None)
                if cancel is not None:
                    cancel(fut)
                else:
                    fut.cancel()
        else:
            raise ValueError('unknown rpc op %r' % (op,))

    def _rpc_submit(self, conn, header, arrays):
        uid = header['uid']
        rec = self._rec(header.get('key'))
        engine = rec['engine']
        kwargs = dict(header.get('meta') or {})
        feed = {n[2:]: arrays[n] for n in arrays if n.startswith('f:')}
        resume = {n[2:]: np.asarray(arrays[n])
                  for n in arrays if n.startswith('z:')}
        if resume:
            kwargs['resume'] = resume
        # frame header carries the caller's trace context; re-enter it
        # so this host's serve span stitches into the same timeline
        tr = trace.from_headers(header.get('trace'))
        h = trace.begin('serving.pod.serve', ctx=tr,
                        node='h%d' % self.host, uid=uid, wire='rpc')
        sid = header.get('sid')
        ckpt_path = None
        # dispatch stamp: set right before engine.submit; the first
        # token's server-side TTFT (dispatch -> token 1, no wire) is
        # measured against it and shipped in that token's frame header
        t_dispatch = [time.monotonic()]
        if header.get('stream'):
            # per-token emitter: enqueue on the connection's writer (the
            # decode loop never blocks); a dead consumer turns the False
            # return into a typed abort — the engine frees slot + pages.
            # The _frozen check keeps simulate_death() faithful to
            # SIGKILL: a dead host's in-process engine must stop having
            # observable effects the moment it "dies"
            sent_first = [False]

            def on_token(t, ids, _c=conn, _u=uid, _h=h):
                hdr = {'uid': _u, 'final': False, 'tok': int(t)}
                if not sent_first[0]:
                    sent_first[0] = True
                    sttft = round(time.monotonic() - t_dispatch[0], 6)
                    hdr['sttft'] = sttft
                    if _h is not None:
                        _h.mark('trace.first_token',
                                server_ttft_s=sttft)
                if self._frozen or not _c.send(
                        hdr, {'ids': np.asarray(ids)}):
                    raise TransportError(
                        'stream consumer disconnected')
            kwargs['on_token'] = on_token
        ckpt_every = int(header.get('ckpt_every') or 0)
        if sid and ckpt_every:
            ckpt_path = os.path.join(_streams_dir(self.pod_dir),
                                     'ckpt.%s.npz' % sid)

            def checkpoint(state, _p=ckpt_path):
                if self._frozen:     # a dead host writes nothing
                    return
                _atomic_npz(_p, **{k: np.asarray(v)
                                   for k, v in state.items()})
            kwargs['checkpoint'] = checkpoint
            kwargs['ckpt_every'] = ckpt_every
        if h is not None:
            h.mark('trace.dispatch')
        t_dispatch[0] = time.monotonic()
        try:
            with trace.activate(h.ctx if h is not None else None,
                                node='h%d' % self.host):
                fut = engine.submit(feed, **kwargs)
        except Exception as e:
            if h is not None:
                h.end(error=type(e).__name__)
            raise
        conn.state.setdefault('futs', {})[uid] = (fut, engine)

        def done(f, _c=conn, _u=uid, _p=ckpt_path, _h=h):
            (_c.state.get('futs') or {}).pop(_u, None)
            if self._frozen:
                # SIGKILL fidelity: a dead host answers nothing, never
                # closes its serve span (the spilled open span IS the
                # orphan the collector flags), and must not janitor the
                # shared stream checkpoint the failover path resumes
                # from
                return
            try:
                e = f.exception()
            except concurrent.futures.CancelledError as ce:
                e = ce
            if _h is not None:
                _h.end(error=type(e).__name__ if e is not None else None)
            if e is not None:
                _c.send({'uid': _u, 'final': True,
                         'error': {'type': type(e).__name__,
                                   'message': str(e)}})
            else:
                _c.send({'uid': _u, 'final': True},
                        {'o:%d' % i: np.asarray(o)
                         for i, o in enumerate(f.result())})
                if _p is not None:
                    try:   # finished stream: its checkpoint is garbage
                        os.remove(_p)
                    except OSError:
                        pass
        fut.add_done_callback(done)

    def _rpc_push(self, conn, header, arrays):
        rec = self._rec(header.get('key'))
        deltas = {}
        for n in arrays:
            if n.startswith('i:'):
                name = n[2:]
                deltas[name] = (np.asarray(arrays[n]),
                                np.asarray(arrays['r:%s' % name]))
        with trace.activate(trace.from_headers(header.get('trace')),
                            node='h%d' % self.host):
            rows = rec['engine'].push_rows(deltas)
        conn.send({'uid': header.get('uid'), 'final': True, 'ok': True,
                   'rows': int(rows)})

    def _rpc_conn_closed(self, conn):
        """A client connection died: reap its work. Queued requests are
        dropped at dequeue; a decoding stream's slot and pages free at
        the next loop tick (typed StreamCancelled — nobody is listening
        for the result anyway). A reconnecting client re-sends what it
        still wants (RpcReplica._on_reconnect)."""
        futs = conn.state.get('futs') or {}
        for uid, (fut, engine) in sorted(futs.items()):
            try:
                cancel = getattr(engine, 'cancel', None)
                if cancel is not None:
                    cancel(fut)
                else:
                    fut.cancel()
            except Exception:  # noqa: BLE001 — reaping is best-effort
                pass

    # -- control: heal commands --------------------------------------------

    def _ctl_loop(self):
        ctl = _ctl_dir(self.pod_dir, self.host)
        while not self._stop.is_set():
            if self._frozen:
                time.sleep(self._poll_s)
                continue
            try:
                names = sorted(os.listdir(ctl))
            except OSError:
                names = []
            for fname in names:
                if not (fname.startswith('cmd.')
                        and fname.endswith('.json')):
                    continue
                path = os.path.join(ctl, fname)
                cmd = _read_json(path)
                if cmd is None:
                    continue
                try:
                    os.remove(path)
                except OSError:
                    continue   # another thread/incarnation took it
                if cmd.get('cmd') == 'heal':
                    self._heal(cmd)
            time.sleep(self._poll_s)

    def _heal(self, cmd):
        model_id = cmd.get('model')
        token = cmd.get('token')
        builder = self._builders.get(model_id)
        if builder is None:
            self._heal_failed(token, 'host %d has no builder for %r'
                              % (self.host, model_id))
            return
        # the heal order carries the router's trace context: the whole
        # recovery (build -> re-shard -> register) lands on the same
        # timeline as the host loss that triggered it
        with trace.activate(trace.from_headers(cmd.get('trace')),
                            node='h%d' % self.host):
            try:
                with obs.span('serving.replica.build',
                              model=str(model_id), host=self.host,
                              reason=cmd.get('reason')):
                    engine = builder(cmd.get('reason', 'heal'))
                key = self.serve(model_id, engine, heal_token=token)
            except Exception as e:  # noqa: BLE001 — report, don't die
                self._heal_failed(token, '%s: %s' % (type(e).__name__, e))
                return
            obs.event('serving.replica.reshard', model=str(model_id),
                      host=self.host, key=key, token=str(token),
                      reason=cmd.get('reason'),
                      lost_host=cmd.get('lost_host'))

    def _heal_failed(self, token, why):
        obs.event('serving.pod.heal_failed', host=self.host,
                  token=str(token), error=str(why)[:200])
        if token:
            _atomic_json(os.path.join(_registry_dir(self.pod_dir),
                                      'healfail.%s.json' % token),
                         {'token': token, 'host': self.host,
                          'error': str(why)[:500]})


# ---------------------------------------------------------------------------
# RemoteReplica: the engine-protocol proxy the router balances on
# ---------------------------------------------------------------------------

class RemoteReplica(object):
    """Engine-protocol proxy for one registered replica on another
    host: submit/predict/stats_window/push_rows/shutdown look exactly
    like a local engine's, so `Router` (and everything riding it —
    quotas, push_deltas, drain) works unchanged across process
    boundaries. Requests travel as atomic files through the replica's
    spool; the proxy keeps every in-flight request's feed until its
    response lands, which is what makes host-loss re-routing LOSSLESS
    (`take_pending`)."""

    def __init__(self, pod_dir, reg, poll_s=_POLL_S):
        self.pod_dir = str(pod_dir)
        self.reg = dict(reg)
        self.key = reg['key']
        self.host = int(reg['host'])
        self.model_id = reg.get('model_id')
        self.feed_names = list(reg.get('feed_names') or [])
        self.buckets = tuple(reg.get('buckets') or ())
        self._spool = _spool_dir(self.pod_dir, self.key)
        self._poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._pending = {}           # uid -> (future, feed, kwargs)
        self._seq = 0
        self._closed = False
        self._detached = False
        self._last_cum = collections.Counter()
        self._last_stats = {}
        self._thread = threading.Thread(
            target=self._poll_loop, name='pod-proxy-%s' % self.key,
            daemon=True)
        self._thread.start()

    # -- engine protocol ---------------------------------------------------

    def submit(self, feed, **kwargs):
        if self._closed:
            raise ServerClosed('remote replica %s is closed' % self.key)
        for k, v in kwargs.items():
            if callable(v):
                # typed, not a json.dumps crash: the mailbox wire has no
                # frame to carry a token back on
                raise ValueError(
                    'per-token streaming (%s=) needs the rpc transport; '
                    'the file wire only carries whole responses — start '
                    "the PodWorker with transport='rpc'" % k)
        # capture the caller's trace context (Router.submit dispatches
        # inside its activation) so a host-loss re-route keeps the
        # ORIGINAL trace_id; '_trace' stays client-side, the wire meta
        # carries it under 'trace' (the worker pops it back out)
        if kwargs.get('_trace') is None:
            hdrs = trace.headers()
            if hdrs is not None:
                kwargs['_trace'] = hdrs
        arrays = {str(n): np.asarray(a) for n, a in feed.items()}
        with self._lock:
            self._seq += 1
            uid = '%06d-%s' % (self._seq, uuid.uuid4().hex[:8])
            fut = concurrent.futures.Future()
            self._pending[uid] = (fut, arrays, dict(kwargs))
        meta = {k: v for k, v in kwargs.items() if k != '_trace'}
        if kwargs.get('_trace') is not None:
            meta['trace'] = kwargs['_trace']
        payload = {'f:%s' % n: a for n, a in arrays.items()}
        payload['__meta__'] = np.frombuffer(
            json.dumps(meta).encode(), np.uint8)
        try:
            _atomic_npz(os.path.join(self._spool, 'rq.%s.npz' % uid),
                        **payload)
        except OSError as e:
            with self._lock:
                self._pending.pop(uid, None)
            raise ServerClosed('replica %s spool unreachable: %s'
                               % (self.key, e))
        return fut

    def predict(self, feed, timeout=None, **kwargs):
        fut = self.submit(feed, timeout=timeout, **kwargs)
        return fut.result(timeout)

    def warmup(self, example_feed=None):
        # the worker warmed the engine before registering it; the
        # router-side contract (every bucket pre-compiled) already holds
        return list(self.buckets)

    def stats_window(self):
        """Window semantics preserved remotely: the worker publishes
        CUMULATIVE counters; the proxy diffs against its last read —
        read-and-reset, single consumer, exactly like the local
        engines. Live depth is the max of the published depth and this
        proxy's own in-flight count (the truest signal between
        publishes)."""
        st = _read_json(os.path.join(self._spool, 'stats.json')) or {}
        cum = collections.Counter(
            {k: v for k, v in (st.get('cum') or {}).items()
             if isinstance(v, (int, float))})
        win = dict(cum - self._last_cum)
        self._last_cum = cum
        self._last_stats = st
        live = st.get('live') or {}
        with self._lock:
            outstanding = len(self._pending)
        win['queue_depth'] = max(int(live.get('queue_depth', 0)),
                                 outstanding)
        win['inflight'] = int(live.get('inflight', 0))
        win['queue_high_water'] = max(int(st.get('queue_high_water', 0)),
                                      outstanding)
        win['capacity'] = live.get('capacity', 0)
        for k in ('slots', 'pages_free', 'pages_total'):
            if k in live:
                win[k] = live[k]
        return win

    def cache_stats(self):
        """The remote replica's published compile counters (the
        steady-state-compiles assertion surface) — read fresh from the
        worker's latest stats publish."""
        st = _read_json(os.path.join(self._spool, 'stats.json')) \
            or self._last_stats or {}
        return dict(st.get('cache') or {})

    def push_rows(self, deltas, timeout=30.0):
        if self._closed:
            raise ServerClosed('remote replica %s is closed' % self.key)
        uid = uuid.uuid4().hex[:12]
        payload = {}
        for name in sorted(deltas):
            ids, rows = deltas[name]
            payload['i:%s' % name] = np.asarray(ids)
            payload['r:%s' % name] = np.asarray(rows)
        hdrs = trace.headers()
        if hdrs is not None:
            payload['__meta__'] = np.frombuffer(
                json.dumps({'trace': hdrs}).encode(), np.uint8)
        _atomic_npz(os.path.join(self._spool, 'push.%s.npz' % uid),
                    **payload)
        ack_path = os.path.join(self._spool, 'pushok.%s.json' % uid)
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            ack = _read_json(ack_path)
            if ack is not None:
                try:
                    os.remove(ack_path)
                except OSError:
                    pass
                if ack.get('ok'):
                    return int(ack.get('rows', 0))
                raise _decode_error(ack.get('error', '{}'))
            if self._closed:
                break
            time.sleep(self._poll_s)
        raise ServerClosed(
            'remote replica %s did not acknowledge a %d-table delta '
            'push within %.1fs (host gone?)'
            % (self.key, len(deltas), timeout))

    def shutdown(self, drain=True, timeout=None):
        """Retire the remote replica: the worker deregisters it first
        (no new routing) then drains its engine; this proxy waits for
        its own in-flight responses."""
        if self._detached:
            self._closed = True
            return True
        self._closed = True     # no NEW submits through this proxy
        try:
            _atomic_json(os.path.join(self._spool, 'retire.json'),
                         {'drain': bool(drain)})
        except OSError:
            pass
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while drain:
            with self._lock:
                n = len(self._pending)
            if n == 0:
                break
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(self._poll_s)
        return True

    # -- host-loss seam ----------------------------------------------------

    def take_pending(self):
        """Atomically detach every unanswered request — (future, feed,
        kwargs) triples the router re-routes to survivors. The proxy
        stops accepting new submits; a LATE response (the host was slow,
        not dead) still resolves any future the re-route has not beaten
        (first outcome wins, the other is dropped)."""
        self._closed = True
        self._detached = True
        self._detach_t = time.monotonic()
        with self._lock:
            pending = list(self._pending.values())
            # keep the map: a late rs file may still win the race
        return pending

    def outstanding(self):
        with self._lock:
            return len(self._pending)

    def _poll_loop(self):
        while True:
            try:
                names = os.listdir(self._spool)
            except OSError:
                names = []
            got = False
            for fname in names:
                if not (fname.startswith('rs.')
                        and fname.endswith('.npz')):
                    continue
                uid = fname[3:-4]
                with self._lock:
                    entry = self._pending.pop(uid, None)
                path = os.path.join(self._spool, fname)
                if entry is None:
                    try:
                        os.remove(path)   # cancelled/duplicate response
                    except OSError:
                        pass
                    continue
                got = True
                fut = entry[0]
                try:
                    with np.load(path, allow_pickle=False) as z:
                        if '__error__' in z.files:
                            _complete(fut, exc=_decode_error(
                                bytes(z['__error__']).decode()))
                        else:
                            outs = [z['o:%d' % i]
                                    for i in range(len(z.files))]
                            _complete(fut, result=outs)
                except Exception:
                    # torn read: put it back for the next cycle
                    with self._lock:
                        self._pending.setdefault(uid, entry)
                    continue
                try:
                    os.remove(path)
                except OSError:
                    pass
            if self._closed and not got:
                if not self._pending:
                    return
                # detached (host lost): late responses get a bounded
                # grace window, then the re-routed futures own the
                # outcome and this poller retires
                t0 = getattr(self, '_detach_t', None)
                if t0 is not None and time.monotonic() - t0 > 5.0:
                    return
            if not got:
                time.sleep(self._poll_s)


class RpcReplica(object):
    """RemoteReplica's socket twin: the same engine-protocol proxy
    (submit/predict/stats_window/push_rows/shutdown/take_pending), over
    ONE persistent `transport.Channel` to the replica's host instead of
    spool files. What the socket buys (docs/serving.md#pod):

      * no poll interval on the request/response hop — a response is a
        frame, not a file another poller must notice;
      * per-token STREAMING: submit kwargs carrying `on_token` mark the
        request `stream`; the worker emits one non-final frame per
        generated token, and the callback fires here on the channel's
        reader thread (end-to-end TTFT);
      * reconnect-with-replay: the channel re-dials forever on seeded
        backoff; after each reconnect every still-pending request is
        re-sent (first outcome wins — a duplicate final frame finds its
        uid already popped and is dropped; duplicate token frames are
        absorbed by the consumer's ordering contract);
      * a GARBLED frame (torn, bad magic) fails every pending future
        with the typed `TransportError` immediately — a poisoned stream
        is condemned, never trusted or hung on.

    Host-loss semantics are unchanged: the proxy keeps every pending
    request's feed AND kwargs, so `take_pending` hands the router the
    same lossless re-route triples the file proxy does — including the
    stream bookkeeping (`sid`, `ckpt_every`, `_last_t`) the decode-
    stream failover path resumes from."""

    def __init__(self, pod_dir, reg, poll_s=_POLL_S):
        self.pod_dir = str(pod_dir)
        self.reg = dict(reg)
        self.key = reg['key']
        self.host = int(reg['host'])
        self.model_id = reg.get('model_id')
        self.feed_names = list(reg.get('feed_names') or [])
        self.buckets = tuple(reg.get('buckets') or ())
        self._poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._pending = {}           # uid -> (future, feed, kwargs)
        self._ctl = {}               # uid -> (future, header, arrays)
        self._seq = 0
        self._closed = False
        self._detached = False
        self._last_cum = collections.Counter()
        self._last_stats = {}
        addr = reg.get('addr') or ()
        if len(addr) != 2:
            raise ValueError('replica %r advertises no rpc addr'
                             % (self.key,))
        self._chan = Channel((str(addr[0]), int(addr[1])),
                             on_frame=self._on_frame,
                             on_reconnect=self._on_reconnect,
                             on_wire_error=self._on_wire_error,
                             seed=self.host)

    # -- engine protocol ---------------------------------------------------

    def submit(self, feed, **kwargs):
        if self._closed:
            raise ServerClosed('remote replica %s is closed' % self.key)
        # capture the caller's trace context (Router.submit dispatches
        # inside its activation): the pending entry keeps it so a
        # host-loss re-route resumes under the ORIGINAL trace_id
        if kwargs.get('_trace') is None:
            hdrs = trace.headers()
            if hdrs is not None:
                kwargs['_trace'] = hdrs
        arrays = {str(n): np.asarray(a) for n, a in feed.items()}
        with self._lock:
            self._seq += 1
            uid = '%06d-%s' % (self._seq, uuid.uuid4().hex[:8])
            fut = concurrent.futures.Future()
            self._pending[uid] = (fut, arrays, dict(kwargs))
        # best-effort: disconnected now -> the reconnect replay re-sends
        self._send_submit(uid, arrays, kwargs)
        return fut

    def _send_submit(self, uid, arrays, kwargs):
        # callables and resumed decode state never cross as JSON meta:
        # streaming intent travels as header flags, resume state as
        # typed array blobs, and the callbacks stay client-side; the
        # trace context rides the frame header, not the meta
        meta = {k: v for k, v in kwargs.items()
                if k not in ('on_token', 'checkpoint', 'resume', 'sid',
                             'ckpt_every', '_last_t', '_trace')}
        header = {'op': 'submit', 'uid': uid, 'key': self.key,
                  'meta': meta}
        if kwargs.get('_trace') is not None:
            header['trace'] = kwargs['_trace']
        wire = {'f:%s' % n: a for n, a in arrays.items()}
        if kwargs.get('on_token') is not None:
            header['stream'] = True
        if kwargs.get('sid'):
            header['sid'] = str(kwargs['sid'])
            header['ckpt_every'] = int(kwargs.get('ckpt_every') or 0)
        resume = kwargs.get('resume')
        if resume is not None:
            for n in sorted(resume):
                wire['z:%s' % n] = np.asarray(resume[n])
        return self._chan.send(header, wire)

    def predict(self, feed, timeout=None, **kwargs):
        fut = self.submit(feed, timeout=timeout, **kwargs)
        return fut.result(timeout)

    def warmup(self, example_feed=None):
        return list(self.buckets)

    # -- channel callbacks (reader thread) ---------------------------------

    def _on_frame(self, header, arrays):
        uid = header.get('uid')
        if not header.get('final'):
            # one streamed token; ordering/dedup is the consumer's
            # contract (router.TokenStream), _last_t feeds the failover
            # path's replayed-work accounting
            with self._lock:
                entry = self._pending.get(uid)
            if entry is None:
                return
            kwargs = entry[2]
            t = int(header.get('tok', 0))
            kwargs['_last_t'] = max(t, int(kwargs.get('_last_t') or 0))
            cb = kwargs.get('on_token')
            if cb is not None:
                sttft = header.get('sttft')
                if sttft is not None:
                    # first token's frame carries the worker's server-
                    # side TTFT (dispatch -> token 1, no wire): hand it
                    # to consumers that take it (TokenStream), fall back
                    # for plain 2-arg callbacks (failover replay path)
                    try:
                        cb(t, arrays.get('ids'), float(sttft))
                    except TypeError:
                        cb(t, arrays.get('ids'))
                else:
                    cb(t, arrays.get('ids'))
            return
        with self._lock:
            entry = self._pending.pop(uid, None)
            ctl = self._ctl.pop(uid, None) if entry is None else None
        fut = entry[0] if entry is not None else \
            (ctl[0] if ctl is not None else None)
        if fut is None:
            return          # duplicate final frame lost the race: drop
        if 'error' in header:
            _complete(fut, exc=_error_from_dict(header['error'] or {}))
        elif entry is not None:
            _complete(fut, result=[arrays['o:%d' % i]
                                   for i in range(len(arrays))])
        else:
            _complete(fut, result=header)

    def _on_reconnect(self):
        """The worker restarted or the network blinked: re-send every
        request still wanted. The worker cancelled the old incarnations
        when the connection died, so this never double-decodes; if a
        final frame DID land just before the cut, first-outcome-wins
        drops the duplicate."""
        with self._lock:
            pend = sorted(self._pending.items())
            ctl = sorted(self._ctl.items())
        for uid, (fut, arrays, kwargs) in pend:
            if not fut.done():
                self._send_submit(uid, arrays, kwargs)
        for uid, (fut, header, arrays) in ctl:
            if not fut.done():
                self._chan.send(header, arrays)

    def _on_wire_error(self, exc):
        """A garbled frame condemned the connection: every pending
        future fails TYPED now. No replay — a corrupted stream gives no
        honest claim about what the other side received; the caller
        (or the router's re-route machinery) owns the retry decision."""
        with self._lock:
            pend = list(self._pending.values())
            ctl = list(self._ctl.values())
            self._pending.clear()
            self._ctl.clear()
        err = exc if isinstance(exc, TransportError) \
            else TransportError(str(exc))
        for fut, _arrays, _kwargs in pend:
            _complete(fut, exc=err)
        for fut, _header, _arrays in ctl:
            _complete(fut, exc=err)

    # -- control rpcs ------------------------------------------------------

    def _ctl_rpc(self, header, arrays=None):
        with self._lock:
            self._seq += 1
            uid = 'c%05d-%s' % (self._seq, uuid.uuid4().hex[:6])
            fut = concurrent.futures.Future()
            header = dict(header, uid=uid)
            self._ctl[uid] = (fut, header, dict(arrays or {}))
        self._chan.send(header, arrays or {})
        return fut

    def stats_window(self):
        """Same window semantics as the file proxy (cumulative counters
        diffed against the last read), fed by a stats rpc instead of
        stats.json. The rpc is fired fresh each call but only waited on
        briefly — a slow or dead host costs the dispatch path
        milliseconds, and the reply (when it lands) freshens the NEXT
        sample; the heartbeat, not this path, decides the host is gone."""
        with self._lock:
            # abandon older unanswered stats probes (a dead host must
            # not accumulate one per sample window until reconnect)
            for uid in [u for u, (f, h, _a) in self._ctl.items()
                        if h.get('op') == 'stats' and not f.done()]:
                self._ctl.pop(uid)
        fut = self._ctl_rpc({'op': 'stats', 'key': self.key})

        def land(f, _self=self):
            try:
                if f.exception() is None:
                    _self._last_stats = f.result().get('stats') or {}
            except Exception:  # noqa: BLE001 — cancelled probe
                pass
        fut.add_done_callback(land)
        try:
            fut.result(max(0.05, 2 * self._poll_s))
        except Exception:  # noqa: BLE001 — fall back to the last landed
            pass
        st = self._last_stats or {}
        cum = collections.Counter(
            {k: v for k, v in (st.get('cum') or {}).items()
             if isinstance(v, (int, float))})
        win = dict(cum - self._last_cum)
        self._last_cum = cum
        live = st.get('live') or {}
        with self._lock:
            outstanding = len(self._pending)
        win['queue_depth'] = max(int(live.get('queue_depth', 0)),
                                 outstanding)
        win['inflight'] = int(live.get('inflight', 0))
        win['queue_high_water'] = max(int(st.get('queue_high_water', 0)),
                                      outstanding)
        win['capacity'] = live.get('capacity', 0)
        for k in ('slots', 'pages_free', 'pages_total'):
            if k in live:
                win[k] = live[k]
        return win

    def cache_stats(self):
        fut = self._ctl_rpc({'op': 'stats', 'key': self.key})
        try:
            st = fut.result(2.0).get('stats') or {}
            self._last_stats = st
        except Exception:  # noqa: BLE001 — dead host: last known
            st = self._last_stats or {}
        return dict(st.get('cache') or {})

    def metrics_text(self, timeout=5.0):
        """The worker host's full metrics registry in Prometheus text
        exposition format (the rpc `metrics` op) — the scrape path for
        deployments that never mount pod_dir."""
        fut = self._ctl_rpc({'op': 'metrics', 'key': self.key})
        reply = fut.result(float(timeout))
        return str(reply.get('prom') or '')

    def push_rows(self, deltas, timeout=30.0):
        if self._closed:
            raise ServerClosed('remote replica %s is closed' % self.key)
        payload = {}
        for name in sorted(deltas):
            ids, rows = deltas[name]
            payload['i:%s' % name] = np.asarray(ids)
            payload['r:%s' % name] = np.asarray(rows)
        header = {'op': 'push', 'key': self.key}
        hdrs = trace.headers()
        if hdrs is not None:
            header['trace'] = hdrs
        fut = self._ctl_rpc(header, payload)
        try:
            reply = fut.result(float(timeout))
        except concurrent.futures.TimeoutError:
            raise ServerClosed(
                'remote replica %s did not acknowledge a %d-table delta '
                'push within %.1fs (host gone?)'
                % (self.key, len(deltas), timeout))
        return int(reply.get('rows', 0))

    def cancel(self, future):
        """Ask the worker to cancel/abort the submit owning `future`
        (queued -> dropped; a decoding stream's slot and pages free at
        the next loop tick). Returns True when a cancel was sent."""
        with self._lock:
            uid = next((u for u, e in self._pending.items()
                        if e[0] is future), None)
        if uid is None:
            return False
        return self._chan.send({'op': 'cancel', 'cancel_uid': uid,
                                'key': self.key})

    def shutdown(self, drain=True, timeout=None):
        if self._detached:
            self._closed = True
            self._chan.close()
            return True
        self._closed = True      # no NEW submits through this proxy
        ok = True
        try:
            fut = self._ctl_rpc({'op': 'retire', 'key': self.key,
                                 'drain': bool(drain),
                                 'timeout': timeout})
            fut.result(30.0 if timeout is None else float(timeout))
        except Exception:  # noqa: BLE001 — already retired / host gone
            ok = False
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while drain:
            with self._lock:
                n = len(self._pending)
            if n == 0:
                break
            if deadline is not None and time.monotonic() > deadline:
                ok = False
                break
            time.sleep(self._poll_s)
        self._chan.close()
        return ok

    # -- host-loss seam ----------------------------------------------------

    def take_pending(self):
        """Detach every unanswered request for re-routing — the same
        lossless triples as the file proxy's. The channel stays up for
        a bounded grace window (a late final frame from a slow-not-dead
        host still wins any future the re-route has not beaten), then
        closes so it stops re-dialing a dead address forever."""
        self._closed = True
        self._detached = True
        with self._lock:
            pending = list(self._pending.values())
            # keep the map: a late final frame may still win the race
        t = threading.Timer(5.0, self._chan.close)
        t.daemon = True
        t.start()
        return pending

    def outstanding(self):
        with self._lock:
            return len(self._pending)


# ---------------------------------------------------------------------------
# autoscaling: queue-depth-driven capacity, riding the swap machinery
# ---------------------------------------------------------------------------

class AutoscalePolicy(object):
    """When to grow/shrink a model's replica set (docs/serving.md#pod).

    scale_up_at / scale_down_at: thresholds on the PER-REPLICA windowed
        admission pressure (queue high-water + depth + in-flight, the
        same signal least-loaded dispatch balances on). Above the first
        for a full window -> one replica is added; below the second ->
        one is drained.
    cooldown_s: minimum seconds between scaling actions (a heal takes
        time to land; don't storm).
    """

    def __init__(self, min_replicas=1, max_replicas=4, scale_up_at=4.0,
                 scale_down_at=0.5, cooldown_s=5.0):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError('need 1 <= min_replicas <= max_replicas')
        if scale_down_at >= scale_up_at:
            raise ValueError('scale_down_at must be < scale_up_at')
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_at = float(scale_up_at)
        self.scale_down_at = float(scale_down_at)
        self.cooldown_s = float(cooldown_s)


class Autoscaler(object):
    """Queue-depth-driven replica scale-up/down for one model, riding
    the router's zero-downtime machinery: scale-UP builds + warms the
    incoming replica OFF TO THE SIDE (the swap() discipline — traffic
    never sees a cold compile) then `add_replica`s it atomically;
    scale-DOWN `remove_replica`s the least-loaded one and drains it in
    the background (no future lost). `builder(reason) -> warmed engine`
    adds in-process; a PodRouter wires `scale_up=` to a heal command so
    the new replica lands on the least-loaded HOST instead."""

    def __init__(self, router, model_id, policy, builder=None,
                 scale_up=None):
        if builder is None and scale_up is None:
            raise ValueError('Autoscaler needs builder= or scale_up=')
        self.router = router
        self.model_id = model_id
        self.policy = policy
        self._builder = builder
        self._scale_up = scale_up
        self._last_action_t = None
        self._building = False     # an async scale-up build in flight
        self.actions = []          # ('up'|'down', pressure) history

    def pressure(self):
        """Mean per-replica windowed admission pressure."""
        samples = self.router.sample_windows(self.model_id)
        if not samples:
            return None
        per = []
        for s in samples:
            w = s['window']
            per.append(w.get('queue_depth', 0) + w.get('inflight', 0)
                       + w.get('queue_high_water', 0)
                       + s.get('routed_since', 0))
        return float(sum(per)) / len(per)

    def tick(self):
        """One policy evaluation; returns 'up', 'down', or None. The
        pod/poll loop calls this each cycle; tests call it directly."""
        pol = self.policy
        now = time.monotonic()
        if self._last_action_t is not None \
                and now - self._last_action_t < pol.cooldown_s:
            return None
        p = self.pressure()
        if p is None:
            return None
        n = len(self.router.replicas(self.model_id))
        if p >= pol.scale_up_at and n < pol.max_replicas:
            if self._building:
                return None        # last scale-up is still building
            self._last_action_t = now
            obs.event('serving.autoscale', model=str(self.model_id),
                      direction='up', replicas=n, pressure=round(p, 3))
            if self._scale_up is not None:
                self._scale_up('scale_up')
            else:
                # build + warm OFF the caller's thread (tick runs
                # inside PodRouter.poll — a minutes-long sharded
                # restore must not stall host-loss detection), then
                # add atomically: the swap() discipline
                self._building = True

                def build():
                    try:
                        engine = self._builder('scale_up')
                        self.router.add_replica(self.model_id, engine)
                    except Exception as e:  # noqa: BLE001 — report
                        obs.event('serving.autoscale.error',
                                  model=str(self.model_id),
                                  error='%s: %s' % (type(e).__name__, e))
                    finally:
                        self._building = False

                threading.Thread(target=build, name='autoscale-build',
                                 daemon=True).start()
            self.actions.append(('up', p))
            return 'up'
        if p <= pol.scale_down_at and n > pol.min_replicas:
            self._last_action_t = now
            victim = min(self.router.sample_windows(self.model_id),
                         key=lambda s: (
                             s['window'].get('queue_depth', 0)
                             + s['window'].get('inflight', 0)
                             + s.get('routed_since', 0)))
            obs.event('serving.autoscale', model=str(self.model_id),
                      direction='down', replicas=n,
                      pressure=round(p, 3), rid=victim['rid'])
            self.router.remove_replica(self.model_id, victim['rid'],
                                       drain=True, reason='scale_down')
            self.actions.append(('down', p))
            return 'down'
        return None


# ---------------------------------------------------------------------------
# PodRouter: registry-driven routing + host-loss self-healing
# ---------------------------------------------------------------------------

class PodRouter(Router):
    """A Router whose replicas live on OTHER hosts, discovered through
    the shared-filesystem registry PodWorkers publish into
    (docs/serving.md#pod). Everything the single-process Router does —
    least-loaded dispatch, quotas, typed overload, swap, push_deltas —
    runs unchanged over RemoteReplica proxies; on top of it:

      * registry sync: new replica registrations become routable
        replicas (serving.replica.register), voluntary retirements are
        removed cleanly;
      * host-loss: a host whose heartbeat goes stale raises the typed
        `HostLost` inside the poll loop; its replicas are detached, the
        futures pending against them RE-ROUTED to survivors (zero
        dropped futures), and — with heal=True — a heal command asks
        the least-loaded surviving host with a builder to re-shard the
        replica onto its topology (serving.replica.{lost,reshard});
      * autoscaling: `enable_autoscale` ticks an Autoscaler per poll,
        scaling through heal commands (up) / draining removals (down).

    Call `poll()` for one deterministic pass (tests), or rely on the
    background thread (`poll_s` cadence)."""

    def __init__(self, pod_dir, window_s=0.25, poll_s=0.1,
                 heartbeat_timeout=2.0, heal=True, reroute_timeout=30.0,
                 start=True):
        from ..parallel import Heartbeat
        Router.__init__(self, window_s=window_s)
        self.pod_dir = str(pod_dir)
        for d in (_registry_dir(self.pod_dir), _beats_dir(self.pod_dir),
                  _streams_dir(self.pod_dir)):
            os.makedirs(d, exist_ok=True)
        self.heal = bool(heal)
        self._poll_s = float(poll_s)
        self._reroute_timeout = float(reroute_timeout)
        # pure watcher: beats nothing, watches hosts as they register
        self.heartbeat = Heartbeat(_beats_dir(self.pod_dir),
                                   process_id=-1, num_processes=0,
                                   timeout=heartbeat_timeout)
        self._pod_lock = threading.RLock()
        self._known = {}        # key -> dict(rid, proxy, model_id, host)
        self._hosts = {}        # host -> registration dict
        self._heals = {}        # token -> dict(model, lost_host, host, t)
        self._parked = []       # [(model_id, fut, feed, kwargs, t_expire)]
        self._autoscalers = {}
        self.lost_hosts = []    # [{'host', 'stale', 'error', ...}]
        self._last_spill_t = 0.0
        self._stop = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._pod_loop, name='pod-router', daemon=True)
            self._thread.start()

    # -- registry sync -----------------------------------------------------

    def poll(self):
        """One synchronous registry/heartbeat/parked/autoscale pass."""
        with self._pod_lock:
            self._sync_hosts()
            self._sync_registry()
            self._check_hosts()
            self._retry_parked()
            self._check_heal_failures()
            for a in list(self._autoscalers.values()):
                try:
                    a.tick()
                except Exception as e:  # noqa: BLE001 — keep polling
                    obs.event('serving.autoscale.error',
                              error='%s: %s' % (type(e).__name__, e))
        self.spill_traces()

    def spill_traces(self, force=False):
        """Dump this process's trace-span buffer into the shared
        traces/ dir (where each PodWorker spills too) so the collector
        can stitch the router's request spans against the workers'
        serve spans. Cadenced off the poll loop; `force` for a final
        flush (shutdown) or deterministic tests."""
        now = time.monotonic()
        if not force and now - self._last_spill_t < 1.0:
            return
        self._last_spill_t = now
        try:
            trace.spill(_traces_dir(self.pod_dir))
        except Exception:  # noqa: BLE001 — telemetry must not kill poll
            pass

    def _pod_loop(self):
        while not self._stop.wait(self._poll_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — the loop must live
                obs.event('router.pod.error',
                          error='%s: %s' % (type(e).__name__, e))

    def _sync_hosts(self):
        reg = _registry_dir(self.pod_dir)
        try:
            names = os.listdir(reg)
        except OSError:
            names = []
        hosts = {}
        for fname in names:
            if fname.startswith('host.') and fname.endswith('.json'):
                d = _read_json(os.path.join(reg, fname))
                if d is not None and 'host' in d:
                    hosts[int(d['host'])] = d
        # watch EVERY advertised host — a builder-only host (no
        # replicas yet) must still be disqualified as a heal candidate
        # the moment its beats go stale; a host whose file vanished
        # (clean shutdown, or the host-loss janitor) stops being
        # watched so it cannot read as a fresh loss forever
        for h in hosts:
            if h not in self._hosts:
                self.heartbeat.watch(h)
        for h in self._hosts:
            if h not in hosts \
                    and not any(i['host'] == h
                                for i in self._known.values()):
                self.heartbeat.unwatch(h)
        self._hosts = hosts

    def _sync_registry(self):
        reg = _registry_dir(self.pod_dir)
        try:
            names = os.listdir(reg)
        except OSError:
            names = []
        seen = set()
        for fname in names:
            if not (fname.startswith('replica.')
                    and fname.endswith('.json')):
                continue
            d = _read_json(os.path.join(reg, fname))
            if d is None or 'key' not in d:
                continue
            key = d['key']
            seen.add(key)
            if key in self._known:
                continue
            # the ONE transport seam: everything downstream (routing,
            # quotas, host loss, heal, push) sees the same proxy protocol
            cls = RpcReplica if (d.get('transport') == 'rpc'
                                 and d.get('addr')) else RemoteReplica
            proxy = cls(self.pod_dir, d, poll_s=self._poll_s)
            model_id = d.get('model_id')
            if model_id not in self._models:
                self.add_model(model_id, [proxy])
                with self._lock:
                    r = self._models[model_id].replicas[-1]
                    r.host, r.key = proxy.host, key
                    rid = r.rid
                    self._update_gauge_locked()
                obs.event('serving.replica.register',
                          model=str(model_id), rid=rid,
                          host=proxy.host, key=key)
            else:
                rid = self.add_replica(model_id, proxy,
                                       host=proxy.host, key=key)
            self.heartbeat.watch(proxy.host)
            self._known[key] = {'rid': rid, 'proxy': proxy,
                                'model_id': model_id, 'host': proxy.host}
            token = d.get('heal_token')
            if token and token in self._heals:
                h = self._heals.pop(token)
                obs.event('serving.replica.reshard',
                          model=str(model_id), host=proxy.host, key=key,
                          token=str(token), lost_host=h.get('lost_host'),
                          mesh=d.get('mesh'),
                          heal_s=round(time.monotonic() - h['t'], 3))
        # voluntary retirement: the registration file vanished but the
        # host still beats — remove the replica; its worker drains it
        gone = sorted(set(self._known) - seen)
        stale = set(self.heartbeat.check(raise_error=False)) if gone \
            else ()
        for key in gone:
            info = self._known[key]
            host = info['host']
            if host in stale:
                continue    # host is stale: _check_hosts owns this key
            self._known.pop(key)
            self.remove_replica(info['model_id'], info['rid'],
                                drain=False, reason='retired')
            info['proxy'].shutdown(drain=True, timeout=0)
            if not any(i['host'] == host for i in self._known.values()):
                self.heartbeat.unwatch(host)

    # -- host loss: detach, re-route, heal ---------------------------------

    def _check_hosts(self):
        from ..parallel import HostLost
        try:
            self.heartbeat.check(raise_error=True)
            return
        except HostLost as e:
            stale = [h for h in e.stale
                     if any(i['host'] == h for i in self._known.values())]
            if not stale:
                return
            for host in stale:
                self._host_lost(host, e)

    def _host_lost(self, host, exc):
        record = {'host': host, 'stale': list(exc.stale),
                  'error': '%s: %s' % (type(exc).__name__, exc),
                  'replicas': 0, 'rerouted': 0, 'healed_models': []}
        lost_models = []
        for key, info in sorted(self._known.items()):
            if info['host'] != host:
                continue
            self._known.pop(key)
            # janitor the orphaned registration (a SIGKILLed host can't
            # clean up its own files) — otherwise the next registry
            # sync would re-adopt the dead replica; a RESTARTED host
            # writes a fresh file and is re-adopted normally
            try:
                os.remove(os.path.join(_registry_dir(self.pod_dir),
                                       'replica.%s.json' % key))
            except OSError:
                pass
            record['replicas'] += 1
            proxy, model_id = info['proxy'], info['model_id']
            pending = proxy.take_pending()
            self.remove_replica(model_id, info['rid'], drain=False,
                                reason='host_lost')
            obs.event('serving.replica.lost', model=str(model_id),
                      rid=info['rid'], host=host, key=key,
                      pending=len(pending))
            lost_models.append(model_id)
            t_exp = time.monotonic() + self._reroute_timeout
            for fut, feed, kwargs in pending:
                if fut.done():
                    continue
                self._reroute(model_id, fut, feed, kwargs, t_exp,
                              record)
        self.heartbeat.unwatch(host)
        # janitor the dead host's advert too: it must stop being a heal/
        # autoscale candidate NOW (a restarted host re-registers fresh)
        try:
            os.remove(os.path.join(_registry_dir(self.pod_dir),
                                   'host.%d.json' % host))
        except OSError:
            pass
        self._hosts.pop(host, None)
        if self.heal:
            for model_id in sorted(set(lost_models)):
                token = self.request_heal(model_id, reason='host_lost',
                                          lost_host=host)
                if token is not None:
                    record['healed_models'].append(model_id)
        self.lost_hosts.append(record)
        obs.event('router.host_lost', host=host,
                  replicas=record['replicas'],
                  rerouted=record['rerouted'],
                  heals=len(record['healed_models']))

    def _reroute(self, model_id, fut, feed, kwargs, t_expire,
                 record=None):
        """Send a detached request to a survivor, splicing the result
        into the caller's ORIGINAL future. Unroutable now (no survivor
        yet) -> parked and retried each poll until t_expire. A STREAMED
        request takes the checkpoint-resume path instead."""
        if kwargs.get('on_token') is not None or kwargs.get('sid'):
            return self._reroute_stream(model_id, fut, feed, kwargs,
                                        t_expire, record)
        # re-enter the request's ORIGINAL trace context (captured by the
        # proxy at submit time): the survivor's serve span lands on the
        # same timeline the lost host's orphan span belongs to
        with trace.activate(trace.from_headers(kwargs.get('_trace')),
                            node='router'):
            try:
                new_fut = self.submit(model_id, feed, **kwargs)
            except Exception:  # noqa: BLE001 — park: heal may be coming
                self._parked.append((model_id, fut, feed, kwargs,
                                     t_expire))
                return False
            _chain(new_fut, fut)
            _C_REROUTED.inc()
            if record is not None:
                record['rerouted'] += 1
            obs.event('serving.pod.reroute', model=str(model_id))
        return True

    def _reroute_stream(self, model_id, fut, feed, kwargs, t_expire,
                        record=None):
        """Decode-stream failover: resume the stream on a survivor from
        its last decode-state checkpoint, TOKEN-EXACT. The worker
        checkpointed the slot's full decode state every `ckpt_every`
        tokens (streams/ckpt.<sid>.npz); the survivor resumes at
        checkpoint step + 1 via the engine's `resume=` path (eager
        row writes — zero new compile signatures). Tokens 1..ckpt are
        replayed into the client callback first, so a consumer that saw
        FEWER than ckpt tokens (frames lost with the host) still gets
        every index; the consumer's ordering contract (TokenStream
        dedup) absorbs whatever it already saw.

        With checkpointing OFF (ckpt_every=0) the stream fails with
        the typed HostLost: silently re-decoding everything the
        consumer already acted on is the one thing a stream must never
        do quietly, and the cadence knob is the caller's opt-in. A
        stream lost BEFORE its first checkpoint restarts from scratch
        — fewer than ckpt_every tokens of replayed work, all absorbed
        by the dedup."""
        # the resumed segment continues the ORIGINAL stream's trace:
        # same trace_id across the failover, so the stitched timeline
        # shows dead-host orphan -> resume -> completion as one request
        with trace.activate(trace.from_headers(kwargs.get('_trace')),
                            node='router'):
            return self._resume_stream(model_id, fut, feed, kwargs,
                                       t_expire, record)

    def _resume_stream(self, model_id, fut, feed, kwargs, t_expire,
                       record):
        from ..parallel import HostLost
        sid = kwargs.get('sid')
        ckpt_every = int(kwargs.get('ckpt_every') or 0)
        seen_t = int(kwargs.get('_last_t') or 0)
        if not sid or not ckpt_every:
            _C_STREAM_FAILOVERS.inc()
            obs.event('serving.stream.failover', model=str(model_id),
                      sid=str(sid), resumed=False, seen_t=seen_t)
            _complete(fut, exc=HostLost(
                'decode stream lost with checkpointing disabled '
                '(ckpt_every=0): %d streamed token(s) cannot be resumed '
                'token-exact — pass ckpt_every= to stream() to opt into '
                'failover' % seen_t))
            return True
        state = None
        path = os.path.join(_streams_dir(self.pod_dir),
                            'ckpt.%s.npz' % sid)
        try:
            with np.load(path, allow_pickle=False) as z:
                state = {k: np.asarray(z[k]) for k in z.files}
        except Exception:  # noqa: BLE001 — no/torn ckpt: from scratch
            state = None
        ckpt_t = int(state['step']) if state is not None else 0
        cb = kwargs.get('on_token')
        if state is not None and cb is not None:
            ids = np.asarray(state['ids'])
            for s in range(1, ckpt_t + 1):
                try:
                    cb(s, ids[s - 1])
                except Exception:  # noqa: BLE001 — consumer's problem
                    pass
        kwargs2 = dict(kwargs)
        if state is not None:
            kwargs2['resume'] = state
        try:
            new_fut = self.submit(model_id, feed, **kwargs2)
        except Exception:  # noqa: BLE001 — park: a heal may be coming
            self._parked.append((model_id, fut, feed, kwargs2, t_expire))
            return False
        _chain(new_fut, fut)
        _C_REROUTED.inc()
        _C_STREAM_FAILOVERS.inc()
        _C_STREAM_RESUMES.inc()
        replayed = max(0, seen_t - ckpt_t)
        if record is not None:
            record['rerouted'] += 1
        obs.event('serving.stream.resume', model=str(model_id),
                  sid=str(sid), from_t=ckpt_t, seen_t=seen_t,
                  replayed=replayed)
        return True

    def _retry_parked(self):
        from ..parallel import HostLost
        parked, self._parked = self._parked, []
        now = time.monotonic()
        for model_id, fut, feed, kwargs, t_exp in parked:
            if fut.done():
                continue
            if now > t_exp:
                _complete(fut, exc=HostLost(
                    'request could not be re-routed within %.1fs of its '
                    'serving host dying (no survivor took it)'
                    % self._reroute_timeout))
                continue
            self._reroute(model_id, fut, feed, kwargs, t_exp)

    # -- streamed decode ---------------------------------------------------

    def stream(self, model_id, feed, ckpt_every=0, **kwargs):
        """Per-token streamed decode across the pod (`Router.stream`
        over the rpc proxies). `ckpt_every` > 0 opts the stream into
        decode-state checkpointing at that token cadence: if the
        serving host dies mid-generation, the stream is re-routed to a
        survivor and resumed TOKEN-EXACT from the last checkpoint
        (serving.stream.resume); with 0, a host loss fails the stream
        with the typed HostLost. The checkpoint rides the shared pod
        filesystem (streams/ckpt.<sid>.npz), so any survivor can pick
        it up."""
        if ckpt_every:
            kwargs['sid'] = uuid.uuid4().hex[:12]
            kwargs['ckpt_every'] = int(ckpt_every)
        return Router.stream(self, model_id, feed, **kwargs)

    # -- healing -----------------------------------------------------------

    def request_heal(self, model_id, reason='heal', lost_host=None,
                     exclude_hosts=()):
        """Ask the least-loaded live host with a builder for `model_id`
        to build+register a replacement replica (it re-shards the
        checkpoint onto its own topology). Returns the heal token, or
        None when no candidate host exists (retried implicitly when a
        capable host appears? no — callers re-request)."""
        stale = set(self.heartbeat.check(raise_error=False))
        if lost_host is not None:
            stale.add(lost_host)
        stale.update(exclude_hosts)
        cands = [h for h, d in sorted(self._hosts.items())
                 if h not in stale
                 and str(model_id) in (d.get('builders') or [])]
        if not cands:
            obs.event('serving.pod.heal_unroutable',
                      model=str(model_id), reason=reason)
            return None
        # least-loaded host = fewest replicas currently registered on it
        load = collections.Counter(i['host']
                                   for i in self._known.values())
        host = min(cands, key=lambda h: (load.get(h, 0), h))
        token = uuid.uuid4().hex[:12]
        self._heals[token] = {'model': model_id, 'lost_host': lost_host,
                              'host': host, 't': time.monotonic(),
                              'reason': reason,
                              'exclude': sorted(set(exclude_hosts))}
        # the heal order carries a trace context (continuing the caller's
        # when inside one), so the whole recovery — this request, the
        # target host's build/re-shard, the registration — stitches into
        # ONE timeline the collector can render
        ctx = trace.current()
        if ctx is None:
            ctx = trace.new_trace()
        os.makedirs(_ctl_dir(self.pod_dir, host), exist_ok=True)
        _atomic_json(os.path.join(_ctl_dir(self.pod_dir, host),
                                  'cmd.%s.json' % token),
                     {'cmd': 'heal', 'model': str(model_id),
                      'token': token, 'reason': reason,
                      'lost_host': lost_host,
                      'trace': trace.headers(ctx)})
        _C_HEALS.inc()
        with trace.activate(ctx, node='router'):
            obs.event('serving.pod.heal_requested', model=str(model_id),
                      host=host, token=token, reason=reason)
        return token

    def _check_heal_failures(self):
        reg = _registry_dir(self.pod_dir)
        try:
            names = os.listdir(reg)
        except OSError:
            return
        for fname in names:
            if not (fname.startswith('healfail.')
                    and fname.endswith('.json')):
                continue
            d = _read_json(os.path.join(reg, fname))
            try:
                os.remove(os.path.join(reg, fname))
            except OSError:
                continue
            token = (d or {}).get('token')
            h = self._heals.pop(token, None)
            if h is None:
                continue
            obs.event('serving.pod.heal_redispatch',
                      model=str(h['model']), failed_host=d.get('host'),
                      token=str(token),
                      error=str(d.get('error'))[:200])
            # bounded re-dispatch: the exclude set ACCUMULATES through
            # the token chain, so with every capable host failed the
            # chain terminates in heal_unroutable instead of
            # ping-ponging between two broken builders forever
            exclude = set(h.get('exclude') or ())
            if d.get('host') is not None:
                exclude.add(d['host'])
            self.request_heal(h['model'], reason=h.get('reason', 'heal'),
                              lost_host=h.get('lost_host'),
                              exclude_hosts=sorted(exclude))

    def pending_heals(self):
        with self._pod_lock:
            return {t: dict(h) for t, h in self._heals.items()}

    # -- autoscaling -------------------------------------------------------

    def enable_autoscale(self, model_id, policy, builder=None):
        """Tick an Autoscaler for `model_id` every poll. Default
        scale-up goes through a heal command (the replica lands on the
        least-loaded capable HOST); pass `builder` to add in-process
        replicas instead. Scale-down drains the least-loaded replica
        through the removal seam either way."""
        scale_up = None
        if builder is None:
            scale_up = lambda reason: self.request_heal(  # noqa: E731
                model_id, reason=reason)
        a = Autoscaler(self, model_id, policy, builder=builder,
                       scale_up=scale_up)
        with self._pod_lock:
            self._autoscalers[model_id] = a
        return a

    # -- drill/bench conveniences ------------------------------------------

    def wait_for_replicas(self, model_id, n, timeout=30.0):
        """Block until `model_id` has >= n routable replicas (drills:
        'pod is up'). Returns the replica view or raises TimeoutError."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            self.poll()
            try:
                view = self.replicas(model_id)
            except KeyError:
                view = []
            if len(view) >= n:
                return view
            time.sleep(self._poll_s)
        raise TimeoutError(
            'model %r has %d of %d wanted replicas after %.1fs'
            % (model_id, len(view), n, timeout))

    def shutdown(self, drain=True, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout or 10.0)
        ok = Router.shutdown(self, drain=drain, timeout=timeout)
        self.spill_traces(force=True)   # final flush: no span lost
        return ok
