"""Continuous batching for autoregressive beam decode.

The serving engine (engine.py) batches INDEPENDENT single-shot requests;
an autoregressive decode request is a SEQUENCE of coupled steps, and
whole-batch lockstep decode (`attention_lstm_beam_decode`: one fused
lax.scan over max_len) makes every request in a batch pay the longest
request's step count and makes new requests wait for the whole batch to
drain. This module serves the same decoder with ORCA/vLLM-style
iteration-level scheduling instead:

  * a fixed-capacity SLOT POOL holds per-sequence decode state (token
    buffer, beam scores, LSTM cache rows, encoder rows) as persistable
    device arrays of shape [slots, ...];
  * ONE jitted decode-step module (`attention_lstm_beam_decode_step`,
    the lockstep scan body factored into step form — fetch-equivalent by
    construction) advances every ACTIVE slot per call; active-slot
    masking (`where`-select, the anomaly-guard pattern) keeps dead and
    poisoned slots from perturbing live ones;
  * per-sequence JOIN/LEAVE happens between steps on the host: a
    finished sequence (all beams ended, or its per-request token limit
    reached) releases its slot and resolves its Future immediately;
    queued requests are admitted into free slots mid-flight — no
    barrier, no lockstep drain;
  * admission prefill (the encoder) runs in batches padded to
    power-of-two BUCKETS (serving/buckets.py), and the step module has
    exactly ONE signature, so the jit-signature set is closed and
    `warmup()` leaves steady-state serving at ZERO compiles;
  * the slot state is persistable and WRITTEN by the step op, so
    `passes.memory_plan` donates exactly the state buffers — in-place
    HBM updates per step, driven through `Executor.acquire_step`'s
    pinned StepHandle (no per-step prepare pass).

Observability: decode.slots.occupied / decode.queue.depth gauges,
decode.step.seconds + decode.ttft.seconds histograms, join/release/
poison events and token counters — `tools/obs_report.py` renders a
decode section from them (docs/serving.md has the catalog and the slot
lifecycle diagram).
"""
import collections
import concurrent.futures
import threading
import time

import numpy as np

from .. import obs
from . import buckets as _buckets
from .engine import (DeadlineExceeded, ServerClosed, ServerOverloaded,
                     _POLL_S)

__all__ = ['DecodeConfig', 'DecodeEngine', 'DecodeSlotPoisoned',
           'LockstepDecoder', 'mt_weights', 'program_prefill']

WEIGHT_KEYS = ('w_dec', 'u_dec', 'b_dec', 'w_q', 'w_emb', 'w_out', 'b_out')

# state carried per slot; written entries are donated in place by the
# memory plan, read-only ones (enc/mask/limit) keep their buffers
_WRITTEN_STATE = ('h', 'c', 'prev_ids', 'acc', 'fin', 'ids_hist',
                  'par_hist', 'step', 'active')
_READONLY_STATE = ('enc', 'mask', 'limit')


class DecodeSlotPoisoned(RuntimeError):
    """Non-finite values appeared in one slot's beam scores (a poisoned
    feed / encoder fault). Only that slot's future receives this error;
    the slot is freed and every other in-flight sequence is untouched
    (the step's where-select masking isolates rows)."""


class DecodeConfig(object):
    """Slot-pool / admission policy for a DecodeEngine.

    slots:        fixed capacity of the slot pool — the decode step
                  module's batch dimension. Admission prefill buckets
                  are the powers of two up to `slots`
                  (serving/buckets.py), so the signature set is closed.
    beam_size:    beam width per sequence.
    max_len:      token-buffer capacity per slot; a request's
                  max_new_tokens may not exceed it.
    start_id/end_id: decode vocabulary sentinels (the lockstep op's
                  attrs).
    src_cap:      encoder-row capacity per slot ([src_cap, enc_dim]
                  cache rows); prefill outputs are zero-padded to it.
    bundle:       decode steps run INSIDE one dispatched module call
                  (the PR 4 K-step-bundling move applied to decode:
                  per-call dispatch/sync cost is paid once per bundle).
                  Slots finishing mid-bundle freeze in-graph, so results
                  are bit-identical to bundle=1; join/leave and release
                  granularity coarsen to the bundle boundary (TTFT/
                  tail-latency vs throughput knob).
    queue_capacity / overflow / default_deadline_ms: admission control,
                  same semantics as ServingConfig (typed
                  ServerOverloaded / DeadlineExceeded).
    """

    def __init__(self, slots=8, beam_size=3, max_len=32, start_id=0,
                 end_id=1, src_cap=16, bundle=1, queue_capacity=256,
                 overflow='block', default_deadline_ms=None):
        if overflow not in ('block', 'reject'):
            raise ValueError("overflow must be 'block' or 'reject', got %r"
                             % (overflow,))
        if slots < 1:
            raise ValueError('slots must be >= 1')
        if max_len < 1 or src_cap < 1 or beam_size < 1:
            raise ValueError('beam_size, max_len and src_cap must be >= 1')
        if not 1 <= int(bundle) <= int(max_len):
            raise ValueError('bundle must be in [1, max_len=%d], got %r'
                             % (max_len, bundle))
        self.bundle = int(bundle)
        self.slots = int(slots)
        self.beam_size = int(beam_size)
        self.max_len = int(max_len)
        self.start_id = int(start_id)
        self.end_id = int(end_id)
        self.src_cap = int(src_cap)
        self.queue_capacity = int(queue_capacity)
        self.overflow = overflow
        self.default_deadline_ms = default_deadline_ms
        self.admit_buckets = _buckets.default_buckets(self.slots)


def mt_weights(scope, name='mt'):
    """Collect the machine_translation decoder's weights from a trained
    scope into the dict DecodeEngine takes (the step reuses the training
    parameters by name, like models/machine_translation._beam_decode)."""
    pick = lambda suffix: np.asarray(scope._chain_get(name + suffix))
    return {'w_dec': pick('_w_dec'), 'u_dec': pick('_u_dec'),
            'b_dec': pick('_b_dec'), 'w_q': pick('_w_attnq'),
            'w_emb': pick('_trg_emb'), 'w_out': pick('_w_out'),
            'b_out': pick('_b_out')}


def program_prefill(executor, program, scope, feed_name, fetch,
                    token_cap):
    """Build a DecodeEngine prefill callable from an ENCODER Program
    (e.g. the machine_translation generating program pruned at
    `encoded_vector`). Each request feed is {feed_name: int token array
    [L] or [L, 1]}; tokens are padded to `token_cap` rows so every
    bucket size has exactly one feed signature. Returns
    (enc [n, token_cap, D], src_len [n])."""
    from ..fluid.lowering import SeqValue

    def prefill(feeds):
        toks, lens = [], []
        for f in feeds:
            t = np.asarray(f[feed_name]).reshape(-1)
            if t.shape[0] > token_cap:
                raise ValueError(
                    'source of %d token(s) exceeds the prefill token cap '
                    '%d' % (t.shape[0], token_cap))
            lens.append(t.shape[0])
            toks.append(np.pad(t, (0, token_cap - t.shape[0])))
        data = np.stack(toks).astype(np.int64)[:, :, None]
        sv = SeqValue(data, np.asarray(lens, np.int32))
        out, = executor.run(program, feed={feed_name: sv},
                            fetch_list=[fetch], scope=scope,
                            return_numpy=False)
        from ..fluid.lod_tensor import LoDTensor
        if isinstance(out, LoDTensor):
            out = out.to_seq_value(pad_to=token_cap)
            enc = np.asarray(out.data)
        else:
            enc = np.asarray(out)
        return enc, np.asarray(lens, np.int32)

    return prefill


class LockstepDecoder(object):
    """Whole-batch LOCKSTEP baseline over the same decoder weights: the
    fused `attention_lstm_beam_decode` op (one lax.scan over max_len)
    fed pre-computed encoder rows. This is the A/B reference the
    continuous engine must match token-for-token (tests/test_decode.py)
    and the baseline `tools/serve_bench.py --workload decode` measures
    against: every request in a batch pays max_len steps and new
    requests wait for the whole batch."""

    def __init__(self, weights, beam_size, max_len, src_cap, start_id=0,
                 end_id=1, place=None):
        import jax.numpy as jnp
        from ..fluid import core, framework
        from ..fluid.executor import Executor, Scope

        self.beam_size = int(beam_size)
        self.max_len = int(max_len)
        self.src_cap = int(src_cap)
        self._scope = Scope()
        self._exe = Executor(place or core.CPUPlace())
        enc_dim = int(np.asarray(weights['w_q']).shape[1])
        prog = framework.Program()
        blk = prog.global_block()
        enc = blk.create_var(name='ls_enc', shape=[-1, src_cap, enc_dim],
                             dtype='float32', lod_level=1, is_data=True)
        wvars = {}
        for k in WEIGHT_KEYS:
            a = np.asarray(weights[k], np.float32)
            wvars[k] = blk.create_var(name='ls_' + k, shape=list(a.shape),
                                      dtype='float32', persistable=True)
            self._scope.vars['ls_' + k] = jnp.asarray(a)
        ids = blk.create_var(name='ls_sent_ids', shape=None, dtype='int64')
        scores = blk.create_var(name='ls_sent_scores', shape=None,
                                dtype='float32')
        blk.append_op(
            type='attention_lstm_beam_decode',
            inputs={'EncOut': [enc], 'WDec': [wvars['w_dec']],
                    'UDec': [wvars['u_dec']], 'BDec': [wvars['b_dec']],
                    'WAttnQ': [wvars['w_q']], 'WEmb': [wvars['w_emb']],
                    'WOut': [wvars['w_out']], 'BOut': [wvars['b_out']]},
            outputs={'SentenceIds': [ids], 'SentenceScores': [scores]},
            attrs={'beam_size': self.beam_size, 'max_len': self.max_len,
                   'start_id': int(start_id), 'end_id': int(end_id)})
        self._program = prog
        self._fetch = [ids, scores]

    def run(self, enc, src_len):
        """enc [n, S<=src_cap, D] float32, src_len [n] -> (sentence_ids
        [n, beam, max_len] int64, sentence_scores [n, beam] float32)."""
        from ..fluid.lowering import SeqValue
        enc = np.asarray(enc, np.float32)
        if enc.shape[1] < self.src_cap:
            enc = np.pad(enc, ((0, 0), (0, self.src_cap - enc.shape[1]),
                               (0, 0)))
        sv = SeqValue(enc, np.asarray(src_len, np.int32))
        ids, scores = self._exe.run(self._program, feed={'ls_enc': sv},
                                    fetch_list=self._fetch,
                                    scope=self._scope)
        return np.asarray(ids), np.asarray(scores)


class _Request(object):
    __slots__ = ('feed', 'limit', 'future', 't_submit', 'deadline',
                 't_join')

    def __init__(self, feed, limit, future, t_submit, deadline):
        self.feed = feed
        self.limit = limit
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.t_join = None


# process-wide decode telemetry (docs/serving.md); per-engine views live
# in engine.stats / stats_window()
_G_SLOTS = obs.gauge('decode.slots.occupied')
_G_QDEPTH = obs.gauge('decode.queue.depth')
_H_STEP = obs.histogram('decode.step.seconds')
_H_TTFT = obs.histogram('decode.ttft.seconds')
_H_REQ_TOKENS = obs.histogram('decode.request.tokens',
                              buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256,
                                       512, 1024))
_C_REQUESTS = obs.counter('decode.requests')
_C_TOKENS = obs.counter('decode.tokens')
_C_JOINS = obs.counter('decode.joins')
_C_RELEASES = obs.counter('decode.releases')
_C_POISONED = obs.counter('decode.poisoned')
_C_SHED = obs.counter('decode.shed')
_C_REJECTED = obs.counter('decode.rejected')
_C_STEPS = obs.counter('decode.steps')


class DecodeEngine(object):
    """Slot-based continuous-batching front end over one attention-LSTM
    beam decoder (module docstring has the architecture).

    weights: dict with keys w_dec/u_dec/b_dec/w_q/w_emb/w_out/b_out
    (WEIGHT_KEYS) — the decoder tensors the lockstep
    `attention_lstm_beam_decode` op takes (`mt_weights` collects them
    from a trained machine_translation scope).

    prefill: optional callable(list of per-request feed dicts) ->
    (enc [n, S, D] float array with FINITE padding, src_len [n]); it is
    invoked with the batch count padded up to a power-of-two bucket
    (trailing feeds repeated), so it must keep one feed signature per
    bucket size for the zero-compile warmup contract
    (`program_prefill` builds a compliant one from an encoder Program).
    Without a prefill, each request feed carries the encoder rows
    directly: {'enc': [S, D] float array} with S <= config.src_cap.

    Requests enter through `submit(feed, max_new_tokens=...)` and
    resolve to (sentence_ids int [beam_size, max_new_tokens],
    sentence_scores float32 [beam_size]) — bit-identical rows to what
    the whole-batch lockstep op with max_len=max_new_tokens emits for
    the same encoder rows (tests/test_decode.py drills it under
    randomized join/leave).
    """

    def __init__(self, weights, config=None, place=None, prefill=None):
        from ..fluid import core
        from ..fluid.executor import Executor, Scope

        self.config = config or DecodeConfig()
        self._prefill = prefill
        missing = [k for k in WEIGHT_KEYS if k not in weights]
        if missing:
            raise ValueError('decode weights missing %r (need %r)'
                             % (missing, list(WEIGHT_KEYS)))
        self._scope = Scope()
        self._exe = Executor(place or core.CPUPlace())
        self._hidden = int(np.asarray(weights['u_dec']).shape[0])
        self._enc_dim = int(np.asarray(weights['w_q']).shape[1])
        self._build_step_program(weights)
        self._handle = None          # acquired lazily (first step/warmup)
        self._warm = False

        self._lock = threading.Lock()
        self._handle_lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._shutdown = False
        self._drain = True
        # slot table: owned by the decode-loop thread only
        self._occupant = [None] * self.config.slots
        self._slot_steps = [0] * self.config.slots
        # cumulative stats (+ the windowed counterparts stats_window()
        # reads-and-resets; the router balances on the window)
        self._n = collections.Counter()
        self._win = collections.Counter()
        self._q_high_water = 0

        self._thread = threading.Thread(target=self._loop,
                                        name='decode-loop', daemon=True)
        self._thread.start()

    # -- program build -----------------------------------------------------

    def _build_step_program(self, weights):
        """The step-form decode Program: one
        `attention_lstm_beam_decode_step` op over persistable slot state
        + the decoder weights. Exported by `export_step_program` (and
        linted by tools/lint.sh) as an ordinary __model__ artifact."""
        import jax.numpy as jnp
        from ..fluid import framework

        cfg = self.config
        prog = framework.Program()
        blk = prog.global_block()
        C, K, T, S = cfg.slots, cfg.beam_size, cfg.max_len, cfg.src_cap
        H, D = self._hidden, self._enc_dim

        def pvar(name, shape, dtype):
            return blk.create_var(name='cbd_' + name, shape=shape,
                                  dtype=dtype, persistable=True)

        wvars = {}
        for k in WEIGHT_KEYS:
            a = np.asarray(weights[k], np.float32)
            wvars[k] = pvar(k, list(a.shape), 'float32')
            self._scope.vars['cbd_' + k] = jnp.asarray(a)

        spec = {'h': ([C, K, H], 'float32'), 'c': ([C, K, H], 'float32'),
                'prev_ids': ([C, K], 'int32'), 'acc': ([C, K], 'float32'),
                'fin': ([C, K], 'bool'), 'enc': ([C, S, D], 'float32'),
                'mask': ([C, S], 'float32'),
                'ids_hist': ([C, T, K], 'int32'),
                'par_hist': ([C, T, K], 'int32'),
                'step': ([C], 'int32'), 'limit': ([C], 'int32'),
                'active': ([C], 'bool')}
        svars = {}
        for name, (shape, dtype) in spec.items():
            svars[name] = pvar(name, shape, dtype)
            self._scope.vars['cbd_' + name] = jnp.zeros(
                shape, np.dtype(dtype))
        done = blk.create_var(name='cbd_done', shape=[C], dtype='bool')
        bad = blk.create_var(name='cbd_bad', shape=[C], dtype='bool')

        blk.append_op(
            type='attention_lstm_beam_decode_step',
            inputs={'H': [svars['h']], 'C': [svars['c']],
                    'PrevIds': [svars['prev_ids']], 'Acc': [svars['acc']],
                    'Fin': [svars['fin']], 'Enc': [svars['enc']],
                    'Mask': [svars['mask']],
                    'IdsHist': [svars['ids_hist']],
                    'ParHist': [svars['par_hist']],
                    'Step': [svars['step']], 'Limit': [svars['limit']],
                    'Active': [svars['active']],
                    'WDec': [wvars['w_dec']], 'UDec': [wvars['u_dec']],
                    'BDec': [wvars['b_dec']], 'WAttnQ': [wvars['w_q']],
                    'WEmb': [wvars['w_emb']], 'WOut': [wvars['w_out']],
                    'BOut': [wvars['b_out']]},
            outputs={'HOut': [svars['h']], 'COut': [svars['c']],
                     'PrevIdsOut': [svars['prev_ids']],
                     'AccOut': [svars['acc']], 'FinOut': [svars['fin']],
                     'IdsHistOut': [svars['ids_hist']],
                     'ParHistOut': [svars['par_hist']],
                     'StepOut': [svars['step']],
                     'ActiveOut': [svars['active']],
                     'Done': [done], 'Bad': [bad]},
            attrs={'beam_size': cfg.beam_size, 'end_id': cfg.end_id,
                   'bundle': cfg.bundle})
        self._step_program = prog
        # fetching the state with every step makes a slot release a pure
        # numpy slice (one host sync per dispatch that released
        # something) instead of per-release device gathers — on a CPU
        # box device dispatch costs more than the decode math. Releases
        # are LEVEL-triggered off Active (occupied slot now inactive;
        # poisoning detected from NaN in the fetched scores), not off
        # the per-dispatch Done edge: an extra dispatch (e.g. warmup's
        # no-op step racing live traffic) can swallow an edge, but a
        # level can't be lost.
        self._fetch_vars = [svars['active'], svars['ids_hist'],
                            svars['par_hist'], svars['acc'],
                            svars['step']]
        self._state_names = ['cbd_' + n
                            for n in _WRITTEN_STATE + _READONLY_STATE]
        self._join_fn = self._build_join_fn()

    def _build_join_fn(self):
        """One jitted row-scatter admitting a BUCKET of joining requests
        into their slots in a single dispatch, state donated so the
        update is in place. Rows padded past the real join count carry
        valid=False and scatter to index `slots`, which mode='drop'
        discards — so the signature set is exactly cfg.admit_buckets
        (pre-compiled by warmup, like the prefill buckets)."""
        import jax
        import jax.numpy as jnp
        cfg = self.config
        K, H = cfg.beam_size, self._hidden
        neg = float(np.finfo(np.float32).min)
        acc0 = np.full((K,), neg, np.float32)
        acc0[0] = 0.0

        def join(st, slot_idx, valid, enc, mask, limit):
            idx = jnp.where(valid, slot_idx, cfg.slots)   # drop padding
            m = slot_idx.shape[0]

            def put(name, rows):
                full = 'cbd_' + name
                st[full] = st[full].at[idx].set(
                    rows.astype(st[full].dtype), mode='drop')

            put('h', jnp.zeros((m, K, H), jnp.float32))
            put('c', jnp.zeros((m, K, H), jnp.float32))
            put('prev_ids', jnp.full((m, K), cfg.start_id, jnp.int32))
            put('acc', jnp.broadcast_to(jnp.asarray(acc0), (m, K)))
            put('fin', jnp.zeros((m, K), bool))
            put('enc', enc)
            put('mask', mask)
            put('step', jnp.zeros((m,), jnp.int32))
            put('limit', limit)
            put('active', valid)
            return st

        return jax.jit(join, donate_argnums=(0,))

    def _scatter_join(self, slot_idx, valid, enc, mask, limit):
        """Run the jitted join over the handle's live state; inputs are
        bucket-padded host arrays. Serialized with handle creation and
        the step dispatch via _handle_lock (warmup's bucket probes run
        on the caller thread)."""
        handle = self._acquire()
        with self._handle_lock:
            st_all = handle.state
            st = {n: st_all[n] for n in self._state_names}
            new = self._join_fn(st, np.asarray(slot_idx, np.int32),
                                np.asarray(valid, bool),
                                np.asarray(enc, np.float32),
                                np.asarray(mask, np.float32),
                                np.asarray(limit, np.int32))
            for name, val in new.items():
                handle.set_state(name, val)

    def _acquire(self):
        # RLock: warmup() runs on the caller thread while the decode
        # loop may be admitting/stepping — handle creation and every
        # donated-state mutation (_scatter_join, step) serialize on it
        with self._handle_lock:
            if self._handle is None:
                self._handle = self._exe.acquire_step(
                    self._step_program, feed=None,
                    fetch_list=self._fetch_vars, scope=self._scope)
                plan = self._handle._compiled.plan
                obs.event('decode.memory_plan', donates=plan.donates,
                          writes=sorted(plan.write_set))
            return self._handle

    def export_step_program(self, dirname):
        """Save the step-form decode Program (+ its weight/state
        persistables) as an ordinary inference artifact —
        tools/program_lint.py lints it like any saved __model__
        (tools/lint.sh wires that in)."""
        from ..fluid import io
        from ..fluid.executor import scope_guard
        # _handle_lock: the decode loop's in-flight dispatch donates the
        # scope's state buffers mid-step; exporting must not read them
        with self._handle_lock:
            with scope_guard(self._scope):
                io.save_inference_model(dirname, [],
                                        list(self._fetch_vars),
                                        self._exe,
                                        main_program=self._step_program)
        return dirname

    # -- admission ---------------------------------------------------------

    def submit(self, feed, max_new_tokens=None, deadline_ms=None,
               timeout=None):
        """Enqueue one decode request; returns a Future resolving to
        (sentence_ids [beam, max_new_tokens] int, sentence_scores [beam]
        float32). Raises ServerClosed after shutdown, ServerOverloaded
        under the 'reject' policy (or a 'block' admission timeout), and
        ValueError for malformed feeds. A deadline sheds the request
        with DeadlineExceeded if it is still QUEUED when it passes (an
        already-decoding sequence completes)."""
        cfg = self.config
        limit = cfg.max_len if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= limit <= cfg.max_len:
            raise ValueError(
                'max_new_tokens=%d out of range [1, %d] (the slot token '
                'buffer is fixed at engine build)' % (limit, cfg.max_len))
        if self._prefill is None:
            if 'enc' not in feed:
                raise ValueError(
                    "an engine without a prefill takes encoder rows "
                    "directly: feed must carry 'enc' (got %r)"
                    % sorted(feed))
            enc = np.asarray(feed['enc'], np.float32)
            if enc.ndim != 2 or not 1 <= enc.shape[0] <= cfg.src_cap \
                    or enc.shape[1] != self._enc_dim:
                raise ValueError(
                    "feed['enc'] must be [1<=S<=%d, %d], got %r"
                    % (cfg.src_cap, self._enc_dim, enc.shape))
            feed = {'enc': enc}
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        now = time.monotonic()
        deadline = now + deadline_ms / 1000.0 if deadline_ms is not None \
            else None
        fut = concurrent.futures.Future()
        req = _Request(feed, limit, fut, now, deadline)
        t_give_up = now + timeout if timeout is not None else None
        with self._lock:
            while True:
                if self._shutdown:
                    raise ServerClosed('decode engine is shut down')
                if len(self._queue) < cfg.queue_capacity:
                    break
                if cfg.overflow == 'reject':
                    self._n['rejected'] += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('decode.reject',
                              queue_depth=len(self._queue),
                              capacity=cfg.queue_capacity)
                    raise ServerOverloaded(
                        'decode queue is full (%d request(s), capacity '
                        '%d) and the overflow policy is reject'
                        % (len(self._queue), cfg.queue_capacity))
                remaining = _POLL_S if t_give_up is None else \
                    min(_POLL_S, t_give_up - time.monotonic())
                if t_give_up is not None and remaining <= 0:
                    self._n['rejected'] += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('decode.reject',
                              queue_depth=len(self._queue),
                              capacity=cfg.queue_capacity,
                              waited_s=timeout)
                    raise ServerOverloaded(
                        'decode queue stayed full for %.3fs (capacity %d)'
                        % (timeout, cfg.queue_capacity))
                self._not_full.wait(remaining)
            self._queue.append(req)
            self._n['submitted'] += 1
            self._win['submitted'] += 1
            depth = len(self._queue)
            self._q_high_water = max(self._q_high_water, depth)
            self._win['queue_high_water'] = max(
                self._win['queue_high_water'], depth)
            _C_REQUESTS.inc()
            _G_QDEPTH.set(depth)
            self._not_empty.notify()
        return fut

    def predict(self, feed, max_new_tokens=None, deadline_ms=None,
                timeout=None):
        """Synchronous convenience: submit + wait, one wall-clock budget
        for admission and result (ServingEngine.predict semantics)."""
        t0 = time.monotonic()
        fut = self.submit(feed, max_new_tokens=max_new_tokens,
                          deadline_ms=deadline_ms, timeout=timeout)
        remaining = None if timeout is None else \
            max(0.0, timeout - (time.monotonic() - t0))
        try:
            return fut.result(remaining)
        except concurrent.futures.TimeoutError:
            if fut.done():
                return fut.result()
            if fut.cancel():
                raise DeadlineExceeded(
                    'no result within the %.3fs predict() timeout; the '
                    'queued decode request was cancelled' % timeout)
            raise DeadlineExceeded(
                'no result within the %.3fs predict() timeout; the '
                'sequence is already decoding — it completes but the '
                'result is discarded' % timeout)

    # -- warmup ------------------------------------------------------------

    def warmup(self, example_feed=None):
        """Pre-compile the closed signature set — the ONE decode-step
        module plus one prefill signature per admission bucket — so
        steady-state decoding performs zero compiles (assert via
        `cache_stats`; the acceptance drill does). Returns the bucket
        list. With a prefill, `example_feed` (any single request feed)
        seeds the per-bucket probe batches."""
        cfg = self.config
        handle = self._acquire()
        with self._handle_lock:
            handle.step()             # all slots inactive: a no-op step
        for b in cfg.admit_buckets:   # join-scatter kernel per bucket
            with obs.span('decode.warmup', bucket=b, kind='join'):
                self._scatter_join(
                    np.zeros(b, np.int32), np.zeros(b, bool),
                    np.zeros((b, cfg.src_cap, self._enc_dim), np.float32),
                    np.zeros((b, cfg.src_cap), np.float32),
                    np.zeros(b, np.int32))
        if self._prefill is not None:
            if example_feed is None:
                raise ValueError(
                    'warmup() needs example_feed when the engine owns a '
                    'prefill (it cannot synthesize model inputs)')
            for b in cfg.admit_buckets:
                with obs.span('decode.warmup', bucket=b, kind='prefill'):
                    self._prefill([dict(example_feed)] * b)
        self._warm = True
        return list(cfg.admit_buckets)

    # -- decode loop -------------------------------------------------------

    def _pop_live_locked(self, now, shed, cap):
        """Pop up to `cap` still-wanted requests; expired ones collect
        into `shed` (failed by the caller OUTSIDE the lock, like the
        serving engine's batcher)."""
        out = []
        while self._queue and len(out) < cap:
            req = self._queue.popleft()
            self._not_full.notify()
            if req.deadline is not None and now > req.deadline:
                shed.append(req)
                continue
            if not req.future.set_running_or_notify_cancel():
                continue              # cancelled while queued
            out.append(req)
        _G_QDEPTH.set(len(self._queue))
        return out

    def _fail_shed(self, shed):
        now = time.monotonic()
        for req in shed:
            if not req.future.set_running_or_notify_cancel():
                continue
            with self._lock:   # _win races stats_window's copy+reset
                self._n['shed'] += 1
                self._win['shed'] += 1
            _C_SHED.inc()
            waited = now - req.t_submit
            obs.event('decode.shed', waited_s=waited)
            req.future.set_exception(DeadlineExceeded(
                'decode request shed after waiting %.3fs: its deadline '
                'passed before a slot opened' % waited))

    def _admit(self, joins):
        """Prefill + scatter the joining requests' slot state in ONE
        bucket-padded jitted join (loop thread only). A prefill/feed
        failure fails ONLY the joining futures."""
        cfg = self.config
        b = _buckets.pick_bucket(len(joins), cfg.admit_buckets)
        try:
            if self._prefill is not None:
                feeds = [r.feed for r in joins]
                feeds += [joins[-1].feed] * (b - len(joins))
                enc, src_len = self._prefill(feeds)
                enc = np.asarray(enc, np.float32)[:len(joins)]
                src_len = np.asarray(src_len, np.int32)[:len(joins)]
                # a short/misshapen prefill return must fail HERE, not
                # broadcast silently into the batch assembly below
                if enc.ndim != 3 or enc.shape[0] != len(joins):
                    raise ValueError(
                        'prefill returned enc of shape %r for %d '
                        'request(s) (want [n, S, %d])'
                        % (getattr(enc, 'shape', None), len(joins),
                           self._enc_dim))
                if src_len.shape != (len(joins),):
                    raise ValueError(
                        'prefill returned src_len of shape %r for %d '
                        'request(s)' % (src_len.shape, len(joins)))
                if enc.shape[1] > cfg.src_cap:
                    raise ValueError(
                        'prefill returned %d encoder rows > src_cap=%d'
                        % (enc.shape[1], cfg.src_cap))
            else:
                src_len = np.asarray([r.feed['enc'].shape[0]
                                      for r in joins], np.int32)
                enc = np.zeros((len(joins), int(src_len.max()),
                                self._enc_dim), np.float32)
                for i, r in enumerate(joins):
                    enc[i, :src_len[i]] = r.feed['enc']
            # bucket-padded batch ASSEMBLY stays inside the try: a
            # malformed prefill product failing here must resolve only
            # the joining futures, never reach the loop's crash guard
            pad = b - len(joins)
            valid = np.asarray([True] * len(joins) + [False] * pad)
            enc_b = np.zeros((b, cfg.src_cap, self._enc_dim), np.float32)
            enc_b[:len(joins), :enc.shape[1]] = enc
            mask_b = np.zeros((b, cfg.src_cap), np.float32)
            mask_b[:len(joins)] = (np.arange(cfg.src_cap)[None, :]
                                   < src_len[:, None])
            limit_b = np.zeros(b, np.int32)
            limit_b[:len(joins)] = [r.limit for r in joins]
        except Exception as e:  # noqa: BLE001 — the joiners' futures own it
            for r in joins:
                if not r.future.done():
                    r.future.set_exception(e)
            obs.event('decode.prefill.error',
                      requests=len(joins),
                      error='%s: %s' % (type(e).__name__, e))
            return

        free = [i for i, occ in enumerate(self._occupant) if occ is None]
        slot_idx = np.asarray(free[:len(joins)] + [0] * (b - len(joins)),
                              np.int32)
        self._scatter_join(slot_idx, valid, enc_b, mask_b, limit_b)
        now = time.monotonic()
        for i, req in enumerate(joins):
            slot = free[i]
            self._occupant[slot] = req
            self._slot_steps[slot] = 0
            req.t_join = now
            with self._lock:
                self._n['joins'] += 1
                self._win['joins'] += 1
            _C_JOINS.inc()
            obs.event('decode.join', slot=slot, limit=req.limit,
                      src_len=int(src_len[i]))
        _G_SLOTS.set(sum(o is not None for o in self._occupant))

    def _release(self, slot, poisoned, ids_np, par_np, acc_np):
        """Resolve the slot's future from the step's fetched token
        history (host arrays — no device traffic here) and free it
        (loop thread only)."""
        from ..fluid.ops_impl.lod_beam import backtrace_beams
        req = self._occupant[slot]
        self._occupant[slot] = None
        taken = self._slot_steps[slot]
        with self._lock:
            self._n['releases'] += 1
            self._win['releases'] += 1
        _C_RELEASES.inc()
        _G_SLOTS.set(sum(o is not None for o in self._occupant))
        if req is None:
            return
        if poisoned:
            with self._lock:
                self._n['poisoned'] += 1
                self._win['poisoned'] += 1
            _C_POISONED.inc()
            obs.event('decode.poisoned', slot=slot, steps=taken)
            req.future.set_exception(DecodeSlotPoisoned(
                'slot %d produced non-finite beam scores after %d '
                'step(s); the request was aborted (other in-flight '
                'sequences are unaffected)' % (slot, taken)))
            return
        acc = acc_np[slot]
        toks = backtrace_beams(ids_np[slot, :taken],
                               par_np[slot, :taken])    # [K, taken]
        if taken < req.limit:
            # the fused lockstep scan keeps emitting end_id with
            # identity parents once every beam finished — pad instead
            # of stepping (lod_beam.backtrace_beams documents why this
            # is bit-exact)
            pad = np.full((self.config.beam_size, req.limit - taken),
                          self.config.end_id, toks.dtype)
            toks = np.concatenate([toks, pad], axis=1)
        with self._lock:
            self._n['completed'] += 1
            self._win['completed'] += 1
            self._n['tokens'] += taken
            self._win['tokens'] += taken
        _H_REQ_TOKENS.observe(taken)
        obs.event('decode.release', slot=slot, steps=taken,
                  finished=taken < req.limit)
        req.future.set_result((toks.astype(np.int64), acc))

    def _loop(self):
        """Decode-loop thread wrapper: a loop bug must fail every
        in-flight and queued future loudly instead of stranding them
        (the serving batcher's last-resort guard, same rationale)."""
        try:
            self._loop_body()
        except BaseException as e:  # noqa: BLE001 — resolved into futures
            obs.event('decode.loop.error',
                      error='%s: %s' % (type(e).__name__, e))
            with self._lock:
                self._shutdown = True
                self._drain = False
                doomed = [r for r in self._queue]
                self._queue.clear()
                _G_QDEPTH.set(0)
            doomed += [occ for occ in self._occupant if occ is not None]
            self._occupant = [None] * self.config.slots
            _G_SLOTS.set(0)
            for r in doomed:
                try:
                    # queued futures are PENDING and must be claimed;
                    # in-flight ones are already RUNNING and raise here
                    r.future.set_running_or_notify_cancel()
                except RuntimeError:
                    pass
                if not r.future.done():
                    r.future.set_exception(e)

    def _loop_body(self):
        cfg = self.config
        while True:
            shed, joins, doomed = [], [], []
            with self._lock:
                free = sum(o is None for o in self._occupant)
                if free:
                    joins = self._pop_live_locked(time.monotonic(), shed,
                                                  free)
                pending = len(self._queue)
                closing = self._shutdown
                if closing and not self._drain:
                    # queued requests fail with ServerClosed; active
                    # slots still finish. Futures resolve OUTSIDE the
                    # lock (a done-callback may re-enter the engine)
                    while self._queue:
                        doomed.append(self._queue.popleft())
                    doomed += joins     # claimed but not yet admitted
                    joins = []
                    pending = 0
                    _G_QDEPTH.set(0)
            for r in doomed:
                try:
                    r.future.set_running_or_notify_cancel()
                except RuntimeError:
                    pass                # already claimed as a join
                if not r.future.done():
                    r.future.set_exception(ServerClosed(
                        'decode engine shut down without draining'))
            self._fail_shed(shed)
            if joins:
                self._admit(joins)
            n_active = sum(o is not None for o in self._occupant)
            if n_active == 0:
                if closing and pending == 0:
                    break
                with self._lock:
                    if not self._queue and not self._shutdown:
                        self._not_empty.wait(_POLL_S)
                continue
            handle = self._acquire()
            t0 = time.perf_counter()
            with self._handle_lock:   # vs warmup's join/step probes
                active_v, ids_v, par_v, acc_v, step_v = handle.step()
                # fetch conversion stays INSIDE the lock: the fetched
                # arrays alias donated state, and a concurrent warmup
                # dispatch would delete the buffers under us
                active_np = np.asarray(active_v)
                steps_np = np.asarray(step_v)
                finished = [slot for slot, occ
                            in enumerate(self._occupant)
                            if occ is not None and not active_np[slot]]
                if finished:
                    # one host sync for every release this bundle
                    ids_np = np.asarray(ids_v)
                    par_np = np.asarray(par_v)
                    acc_np = np.asarray(acc_v)
            dt = time.perf_counter() - t0
            _H_STEP.observe(dt)
            _C_STEPS.inc()
            with self._lock:
                self._n['steps'] += 1
                self._win['steps'] += 1
            now = time.monotonic()
            for slot, occ in enumerate(self._occupant):
                if occ is None:
                    continue
                prev_steps = self._slot_steps[slot]
                self._slot_steps[slot] = int(steps_np[slot])
                _C_TOKENS.inc(self._slot_steps[slot] - prev_steps)
                if prev_steps == 0 and self._slot_steps[slot] > 0 \
                        and occ.t_join is not None:
                    _H_TTFT.observe(now - occ.t_submit)
                if slot in finished:
                    self._release(slot,
                                  bool(np.isnan(acc_np[slot]).any()),
                                  ids_np, par_np, acc_np)

    # -- lifecycle / stats -------------------------------------------------

    def request_shutdown(self):
        """Signal-safe: flag only (the Trainer preemption pattern)."""
        self._shutdown = True

    def shutdown(self, drain=True, timeout=None):
        """Stop admission; with drain=True every queued request still
        decodes, else queued futures fail with ServerClosed (in-flight
        sequences always finish). No future is ever lost."""
        with self._lock:
            self._drain = drain
            self._shutdown = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
        self._thread.join(timeout)
        done = not self._thread.is_alive()
        obs.event('decode.shutdown', drained=drain, clean=done,
                  completed=self._n['completed'],
                  tokens=self._n['tokens'])
        return done

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    @property
    def stats(self):
        """Cumulative decode statistics + instantaneous depth/occupancy
        (the windowed signal the router balances on is stats_window())."""
        with self._lock:
            depth = len(self._queue)
        out = {k: self._n.get(k, 0) for k in
               ('submitted', 'completed', 'rejected', 'shed', 'poisoned',
                'joins', 'releases', 'steps', 'tokens')}
        out['queue_depth'] = depth
        out['queue_high_water'] = self._q_high_water
        out['slots'] = self.config.slots
        out['slots_occupied'] = sum(o is not None for o in self._occupant)
        out['warm'] = self._warm
        return out

    def stats_window(self):
        """Admission-pressure counters SINCE THE LAST CALL — the
        windowed signal (queue high-water mark, shed/reject counts) the
        router's least-loaded policy needs; instantaneous depth alone
        reads zero between bursts (docs/serving.md). Reading resets the
        window."""
        with self._lock:
            win = dict(self._win)
            self._win.clear()
            depth = len(self._queue)
        for k in ('queue_high_water', 'shed', 'rejected', 'submitted',
                  'completed', 'tokens'):
            win.setdefault(k, 0)
        win['queue_depth'] = depth
        win['inflight'] = sum(o is not None for o in self._occupant)
        # 'capacity' is the ADMISSION queue capacity on every engine
        # kind (a consumer normalizing pressure by it must get the same
        # units from ServingEngine and DecodeEngine replicas); the slot
        # pool is reported separately
        win['capacity'] = self.config.queue_capacity
        win['slots'] = self.config.slots
        return win

    def cache_stats(self):
        """The underlying executor's compile/cache counters (the
        zero-steady-state-compiles assertion reads misses before/after
        traffic)."""
        return self._exe.cache_stats
