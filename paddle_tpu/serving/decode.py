"""Continuous batching for autoregressive beam decode.

The serving engine (engine.py) batches INDEPENDENT single-shot requests;
an autoregressive decode request is a SEQUENCE of coupled steps, and
whole-batch lockstep decode (`attention_lstm_beam_decode`: one fused
lax.scan over max_len) makes every request in a batch pay the longest
request's step count and makes new requests wait for the whole batch to
drain. This module serves the same decoder with ORCA/vLLM-style
iteration-level scheduling instead:

  * a fixed-capacity SLOT POOL holds per-sequence decode state (token
    buffer, beam scores, LSTM cache rows, encoder rows) as persistable
    device arrays of shape [slots, ...];
  * ONE jitted decode-step module (`attention_lstm_beam_decode_step`,
    the lockstep scan body factored into step form — fetch-equivalent by
    construction) advances every ACTIVE slot per call; active-slot
    masking (`where`-select, the anomaly-guard pattern) keeps dead and
    poisoned slots from perturbing live ones;
  * per-sequence JOIN/LEAVE happens between steps on the host: a
    finished sequence (all beams ended, or its per-request token limit
    reached) releases its slot and resolves its Future immediately;
    queued requests are admitted into free slots mid-flight — no
    barrier, no lockstep drain;
  * admission prefill (the encoder) runs in batches padded to
    power-of-two BUCKETS (serving/buckets.py), and the step module has
    exactly ONE signature, so the jit-signature set is closed and
    `warmup()` leaves steady-state serving at ZERO compiles;
  * the slot state is persistable and WRITTEN by the step op, so
    `passes.memory_plan` donates exactly the state buffers — in-place
    HBM updates per step, driven through `Executor.acquire_step`'s
    pinned StepHandle (no per-step prepare pass).

Beyond the slot pool, three LLM-serving moves live here (each drilled
bit-exact/token-exact against the plain engine — docs/serving.md):

  * PAGED state memory (`DecodeConfig(page_size=, pages=)`): token
    history and encoder rows live in fixed-size pages claimed at
    admission for each request's OWN limit/source length
    (serving/pages.py allocator; int32 page tables, in-graph
    gather/scatter) — several times the concurrent streams per state
    byte; pool exhaustion blocks/rejects typed (`reason=pages`);
  * PREFIX caching: released encoder pages stay resident keyed by
    request content; a shared system-prompt/encoder prefix joins
    WITHOUT re-prefilling (refcounts, LRU eviction through the pool);
  * SPECULATIVE decoding (`spec_k=K` + `DecodeEngine(draft=...)`): a
    small draft proposes K tokens, the target verifies all K in ONE
    dispatched module with in-graph accept/rollback — the verify
    batches the vocab-sized projections across positions.

Observability: decode.slots.occupied / decode.queue.depth /
decode.pages.free gauges, decode.step.seconds + decode.ttft.seconds
histograms, join/release/poison/prefix/spec events and token counters
— `tools/obs_report.py` renders a decode section from them
(docs/serving.md has the catalog and the slot lifecycle diagram).
"""
import collections
import concurrent.futures
import threading
import time

import numpy as np

from .. import obs
from . import buckets as _buckets
from . import pages as _pages
from .engine import (DeadlineExceeded, ServerClosed, ServerOverloaded,
                     _POLL_S)

__all__ = ['DecodeConfig', 'DecodeEngine', 'DecodeSlotPoisoned',
           'LockstepDecoder', 'StreamCancelled', 'mt_weights',
           'program_prefill']

WEIGHT_KEYS = ('w_dec', 'u_dec', 'b_dec', 'w_q', 'w_emb', 'w_out', 'b_out')

# state carried per slot; written entries are donated in place by the
# memory plan, read-only ones (enc/mask/limit) keep their buffers
_WRITTEN_STATE = ('h', 'c', 'prev_ids', 'acc', 'fin', 'ids_hist',
                  'par_hist', 'step', 'active')
_READONLY_STATE = ('enc', 'mask', 'limit')
# paged mode: history/encoder rows live in page POOLS; the per-slot
# page tables and the encoder pools are written only at join time
# (through the join scatter), so the step never copies them
_WRITTEN_STATE_PAGED = ('h', 'c', 'prev_ids', 'acc', 'fin', 'hist_ids',
                        'hist_par', 'step', 'active')
_READONLY_STATE_PAGED = ('pt_hist', 'pt_enc', 'enc_pages', 'mask_pages',
                         'limit')
_DRAFT_STATE = ('draft_h', 'draft_c')     # spec_k + weights draft only


class DecodeSlotPoisoned(RuntimeError):
    """Non-finite values appeared in one slot's beam scores (a poisoned
    feed / encoder fault). Only that slot's future receives this error;
    the slot is freed and every other in-flight sequence is untouched
    (the step's where-select masking isolates rows)."""


class StreamCancelled(RuntimeError):
    """The request was cancelled before completing — a streaming
    consumer disconnected mid-generation, or `cancel()` was called
    explicitly. The slot and its pages are already back in the pool
    when this resolves the future."""


class DecodeConfig(object):
    """Slot-pool / admission policy for a DecodeEngine.

    slots:        fixed capacity of the slot pool — the decode step
                  module's batch dimension. Admission prefill buckets
                  are the powers of two up to `slots`
                  (serving/buckets.py), so the signature set is closed.
    beam_size:    beam width per sequence.
    max_len:      token-buffer capacity per slot; a request's
                  max_new_tokens may not exceed it.
    start_id/end_id: decode vocabulary sentinels (the lockstep op's
                  attrs).
    src_cap:      encoder-row capacity per slot ([src_cap, enc_dim]
                  cache rows); prefill outputs are zero-padded to it.
    bundle:       decode steps run INSIDE one dispatched module call
                  (the PR 4 K-step-bundling move applied to decode:
                  per-call dispatch/sync cost is paid once per bundle).
                  Slots finishing mid-bundle freeze in-graph, so results
                  are bit-identical to bundle=1; join/leave and release
                  granularity coarsen to the bundle boundary (TTFT/
                  tail-latency vs throughput knob).
    queue_capacity / overflow / default_deadline_ms: admission control,
                  same semantics as ServingConfig (typed
                  ServerOverloaded / DeadlineExceeded).
    page_size:    switches the engine to PAGED state memory
                  (serving/pages.py, docs/serving.md "Paged decode
                  memory"): token history and encoder rows live in
                  fixed-size pages claimed at admission for the
                  request's OWN limit/source length instead of dense
                  worst-case per-slot buffers — the capacity knob that
                  lets the same state bytes serve several times the
                  concurrent streams. Bit-exact vs the dense engine.
    pages:        history-pool size (required with page_size). A
                  request claims ceil(limit/page_size) of them.
    enc_pages:    encoder-pool size (default: one full src_cap window
                  per slot + equal headroom for resident prefixes +
                  the reserved zero page).
    prefix_cache: keep released encoder pages RESIDENT keyed by request
                  content (default True when paged): a request sharing
                  a prefix joins WITHOUT re-prefilling; LRU-evicted
                  under pool pressure.
    spec_k:       speculative decoding (paged + beam_size=1 +
                  bundle=1 only): a draft model proposes spec_k tokens
                  per dispatch and the target verifies them in ONE
                  bundled module (accept/rollback in-graph; the engine
                  takes the draft via DecodeEngine(draft=...)).
    """

    def __init__(self, slots=8, beam_size=3, max_len=32, start_id=0,
                 end_id=1, src_cap=16, bundle=1, queue_capacity=256,
                 overflow='block', default_deadline_ms=None,
                 page_size=None, pages=None, enc_pages=None,
                 prefix_cache=None, spec_k=None):
        if overflow not in ('block', 'reject'):
            raise ValueError("overflow must be 'block' or 'reject', got %r"
                             % (overflow,))
        if slots < 1:
            raise ValueError('slots must be >= 1')
        if max_len < 1 or src_cap < 1 or beam_size < 1:
            raise ValueError('beam_size, max_len and src_cap must be >= 1')
        if not 1 <= int(bundle) <= int(max_len):
            raise ValueError('bundle must be in [1, max_len=%d], got %r'
                             % (max_len, bundle))
        self.bundle = int(bundle)
        self.slots = int(slots)
        self.beam_size = int(beam_size)
        self.max_len = int(max_len)
        self.start_id = int(start_id)
        self.end_id = int(end_id)
        self.src_cap = int(src_cap)
        self.queue_capacity = int(queue_capacity)
        self.overflow = overflow
        self.default_deadline_ms = default_deadline_ms
        self.admit_buckets = _buckets.default_buckets(self.slots)
        # -- paged state memory -------------------------------------------
        self.paged = page_size is not None
        self.page_size = int(page_size) if self.paged else 0
        self.spec_k = int(spec_k) if spec_k is not None else 0
        if not self.paged:
            if pages is not None or enc_pages is not None:
                raise ValueError('pages/enc_pages require page_size '
                                 '(the paged engine)')
            if prefix_cache:
                raise ValueError('prefix_cache requires page_size (the '
                                 'cache is resident PAGES)')
            if self.spec_k:
                raise ValueError('spec_k requires page_size (speculative '
                                 'decoding runs on the paged engine)')
            self.pages = self.enc_pages = 0
            self.prefix_cache = False
            self.hist_table_width = self.enc_table_width = 0
            return
        if self.page_size < 1:
            raise ValueError('page_size must be >= 1')
        # per-slot page-table widths (static shapes)
        self.hist_table_width = _pages.pages_for(self.max_len,
                                                 self.page_size)
        self.enc_table_width = _pages.pages_for(self.src_cap,
                                                self.page_size)
        if pages is None:
            raise ValueError('paged mode needs pages=N (the history '
                             'pool size; a request claims '
                             'ceil(limit/page_size) of them)')
        self.pages = int(pages)
        if self.pages < self.hist_table_width:
            raise ValueError(
                'pages=%d cannot back even one max_len=%d request '
                '(needs %d pages of %d rows)'
                % (self.pages, self.max_len, self.hist_table_width,
                   self.page_size))
        # +1: encoder page 0 is the reserved zero page masked-out rows
        # read through. Default: one worst-case working set for the
        # live slots PLUS equal headroom — without headroom a released
        # prefix is evicted by the very next join and the cache only
        # ever serves CONCURRENT sharers (found by the verify drive)
        self.enc_pages = (1 + 2 * self.slots * self.enc_table_width
                          if enc_pages is None else int(enc_pages))
        if self.enc_pages < 1 + self.enc_table_width:
            raise ValueError(
                'enc_pages=%d cannot back one src_cap=%d request plus '
                'the reserved zero page (needs %d)'
                % (self.enc_pages, self.src_cap,
                   1 + self.enc_table_width))
        self.prefix_cache = True if prefix_cache is None \
            else bool(prefix_cache)
        if self.spec_k:
            if self.spec_k < 1:
                raise ValueError('spec_k must be >= 1')
            if self.beam_size != 1:
                raise ValueError(
                    'speculative decoding is greedy: spec_k requires '
                    'beam_size=1 (got %d)' % self.beam_size)
            if self.bundle != 1:
                raise ValueError(
                    'spec_k and bundle>1 are mutually exclusive: the '
                    'verify pass IS the bundled dispatch (spec_k '
                    'tokens per module call)')


def mt_weights(scope, name='mt'):
    """Collect the machine_translation decoder's weights from a trained
    scope into the dict DecodeEngine takes (the step reuses the training
    parameters by name, like models/machine_translation._beam_decode)."""
    pick = lambda suffix: np.asarray(scope._chain_get(name + suffix))
    return {'w_dec': pick('_w_dec'), 'u_dec': pick('_u_dec'),
            'b_dec': pick('_b_dec'), 'w_q': pick('_w_attnq'),
            'w_emb': pick('_trg_emb'), 'w_out': pick('_w_out'),
            'b_out': pick('_b_out')}


def program_prefill(executor, program, scope, feed_name, fetch,
                    token_cap):
    """Build a DecodeEngine prefill callable from an ENCODER Program
    (e.g. the machine_translation generating program pruned at
    `encoded_vector`). Each request feed is {feed_name: int token array
    [L] or [L, 1]}; tokens are padded to `token_cap` rows so every
    bucket size has exactly one feed signature. Returns
    (enc [n, token_cap, D], src_len [n])."""
    from ..fluid.lowering import SeqValue

    def prefill(feeds):
        toks, lens = [], []
        for f in feeds:
            t = np.asarray(f[feed_name]).reshape(-1)
            if t.shape[0] > token_cap:
                raise ValueError(
                    'source of %d token(s) exceeds the prefill token cap '
                    '%d' % (t.shape[0], token_cap))
            lens.append(t.shape[0])
            toks.append(np.pad(t, (0, token_cap - t.shape[0])))
        data = np.stack(toks).astype(np.int64)[:, :, None]
        sv = SeqValue(data, np.asarray(lens, np.int32))
        out, = executor.run(program, feed={feed_name: sv},
                            fetch_list=[fetch], scope=scope,
                            return_numpy=False)
        from ..fluid.lod_tensor import LoDTensor
        if isinstance(out, LoDTensor):
            out = out.to_seq_value(pad_to=token_cap)
            enc = np.asarray(out.data)
        else:
            enc = np.asarray(out)
        return enc, np.asarray(lens, np.int32)

    return prefill


class LockstepDecoder(object):
    """Whole-batch LOCKSTEP baseline over the same decoder weights: the
    fused `attention_lstm_beam_decode` op (one lax.scan over max_len)
    fed pre-computed encoder rows. This is the A/B reference the
    continuous engine must match token-for-token (tests/test_decode.py)
    and the baseline `tools/serve_bench.py --workload decode` measures
    against: every request in a batch pays max_len steps and new
    requests wait for the whole batch."""

    def __init__(self, weights, beam_size, max_len, src_cap, start_id=0,
                 end_id=1, place=None):
        import jax.numpy as jnp
        from ..fluid import core, framework
        from ..fluid.executor import Executor, Scope

        self.beam_size = int(beam_size)
        self.max_len = int(max_len)
        self.src_cap = int(src_cap)
        self._scope = Scope()
        self._exe = Executor(place or core.CPUPlace())
        enc_dim = int(np.asarray(weights['w_q']).shape[1])
        prog = framework.Program()
        blk = prog.global_block()
        enc = blk.create_var(name='ls_enc', shape=[-1, src_cap, enc_dim],
                             dtype='float32', lod_level=1, is_data=True)
        wvars = {}
        for k in WEIGHT_KEYS:
            a = np.asarray(weights[k], np.float32)
            wvars[k] = blk.create_var(name='ls_' + k, shape=list(a.shape),
                                      dtype='float32', persistable=True)
            self._scope.vars['ls_' + k] = jnp.asarray(a)
        ids = blk.create_var(name='ls_sent_ids', shape=None, dtype='int64')
        scores = blk.create_var(name='ls_sent_scores', shape=None,
                                dtype='float32')
        blk.append_op(
            type='attention_lstm_beam_decode',
            inputs={'EncOut': [enc], 'WDec': [wvars['w_dec']],
                    'UDec': [wvars['u_dec']], 'BDec': [wvars['b_dec']],
                    'WAttnQ': [wvars['w_q']], 'WEmb': [wvars['w_emb']],
                    'WOut': [wvars['w_out']], 'BOut': [wvars['b_out']]},
            outputs={'SentenceIds': [ids], 'SentenceScores': [scores]},
            attrs={'beam_size': self.beam_size, 'max_len': self.max_len,
                   'start_id': int(start_id), 'end_id': int(end_id)})
        self._program = prog
        self._fetch = [ids, scores]

    def run(self, enc, src_len):
        """enc [n, S<=src_cap, D] float32, src_len [n] -> (sentence_ids
        [n, beam, max_len] int64, sentence_scores [n, beam] float32)."""
        from ..fluid.lowering import SeqValue
        enc = np.asarray(enc, np.float32)
        if enc.shape[1] < self.src_cap:
            enc = np.pad(enc, ((0, 0), (0, self.src_cap - enc.shape[1]),
                               (0, 0)))
        sv = SeqValue(enc, np.asarray(src_len, np.int32))
        ids, scores = self._exe.run(self._program, feed={'ls_enc': sv},
                                    fetch_list=self._fetch,
                                    scope=self._scope)
        return np.asarray(ids), np.asarray(scores)


class _Request(object):
    __slots__ = ('feed', 'limit', 'future', 't_submit', 'deadline',
                 't_join', 'pkey', 'hist_need', 'enc_need', 'on_token',
                 'resume', 'checkpoint', 'ckpt_every', 'aborted')

    def __init__(self, feed, limit, future, t_submit, deadline,
                 pkey=None, hist_need=0, enc_need=0, on_token=None,
                 resume=None, checkpoint=None, ckpt_every=0):
        self.feed = feed
        self.limit = limit
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline
        self.t_join = None
        # paged admission: content key for the prefix cache + the
        # worst-case page claim the admission gate budgets against
        self.pkey = pkey
        self.hist_need = hist_need
        self.enc_need = enc_need
        # streaming: per-token callback, failover checkpoint sink +
        # cadence, resume state, and the consumer-gone flag (set from
        # any thread; the decode loop frees the slot at the next
        # dispatch boundary)
        self.on_token = on_token
        self.resume = resume
        self.checkpoint = checkpoint
        self.ckpt_every = ckpt_every
        self.aborted = False


# process-wide decode telemetry (docs/serving.md); per-engine views live
# in engine.stats / stats_window()
_G_SLOTS = obs.gauge('decode.slots.occupied')
_G_QDEPTH = obs.gauge('decode.queue.depth')
_H_STEP = obs.histogram('decode.step.seconds')
_H_TTFT = obs.histogram('decode.ttft.seconds')
_H_REQ_TOKENS = obs.histogram('decode.request.tokens',
                              buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256,
                                       512, 1024))
_C_REQUESTS = obs.counter('decode.requests')
_C_TOKENS = obs.counter('decode.tokens')
_C_JOINS = obs.counter('decode.joins')
_C_RELEASES = obs.counter('decode.releases')
_C_POISONED = obs.counter('decode.poisoned')
_C_SHED = obs.counter('decode.shed')
_C_REJECTED = obs.counter('decode.rejected')
_C_STEPS = obs.counter('decode.steps')
# paged state memory + prefix cache + speculative decoding
_G_PAGES_FREE = obs.gauge('decode.pages.free')
_C_PREFIX_HITS = obs.counter('decode.prefix.hits')
_C_PREFIX_MISSES = obs.counter('decode.prefix.misses')
_C_PREFIX_EVICT = obs.counter('decode.prefix.evictions')
_C_SPEC_PROPOSED = obs.counter('decode.spec.proposed')
_C_SPEC_ACCEPTED = obs.counter('decode.spec.accepted')
# streaming / failover (docs/serving.md#streams)
_C_CANCELLED = obs.counter('decode.cancelled')
_C_RESUMED = obs.counter('decode.resumed')


class DecodeEngine(object):
    """Slot-based continuous-batching front end over one attention-LSTM
    beam decoder (module docstring has the architecture).

    weights: dict with keys w_dec/u_dec/b_dec/w_q/w_emb/w_out/b_out
    (WEIGHT_KEYS) — the decoder tensors the lockstep
    `attention_lstm_beam_decode` op takes (`mt_weights` collects them
    from a trained machine_translation scope).

    prefill: optional callable(list of per-request feed dicts) ->
    (enc [n, S, D] float array with FINITE padding, src_len [n]); it is
    invoked with the batch count padded up to a power-of-two bucket
    (trailing feeds repeated), so it must keep one feed signature per
    bucket size for the zero-compile warmup contract
    (`program_prefill` builds a compliant one from an encoder Program).
    Without a prefill, each request feed carries the encoder rows
    directly: {'enc': [S, D] float array} with S <= config.src_cap.

    Requests enter through `submit(feed, max_new_tokens=...)` and
    resolve to (sentence_ids int [beam_size, max_new_tokens],
    sentence_scores float32 [beam_size]) — bit-identical rows to what
    the whole-batch lockstep op with max_len=max_new_tokens emits for
    the same encoder rows (tests/test_decode.py drills it under
    randomized join/leave).
    """

    def __init__(self, weights, config=None, place=None, prefill=None,
                 draft=None):
        from ..fluid import core
        from ..fluid.executor import Executor, Scope

        self.config = config or DecodeConfig()
        self._prefill = prefill
        missing = [k for k in WEIGHT_KEYS if k not in weights]
        if missing:
            raise ValueError('decode weights missing %r (need %r)'
                             % (missing, list(WEIGHT_KEYS)))
        self._scope = Scope()
        self._exe = Executor(place or core.CPUPlace())
        self._hidden = int(np.asarray(weights['u_dec']).shape[0])
        self._enc_dim = int(np.asarray(weights['w_q']).shape[1])
        self._vocab = int(np.asarray(weights['w_out']).shape[1])
        self._draft = self._check_draft(draft)
        # host side of the paged state: the allocator + prefix cache
        # (loop-thread owned; the integer counters are read lock-free by
        # the stats surface) and per-slot page assignments
        cfg = self.config
        self._hist_pool = self._enc_pool = self._prefix = None
        self._slot_pages = [None] * cfg.slots
        self._pages_starved = False
        if cfg.paged:
            self._hist_pool = _pages.PagePool(cfg.pages)
            self._enc_pool = _pages.PagePool(cfg.enc_pages, reserved=1)
            if cfg.prefix_cache:
                self._prefix = _pages.PrefixCache(
                    self._enc_pool, on_evict=self._on_prefix_evict)
        self._build_step_program(weights)
        self._handle = None          # acquired lazily (first step/warmup)
        self._warm = False

        self._lock = threading.Lock()
        self._handle_lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._shutdown = False
        self._drain = True
        # slot table: owned by the decode-loop thread only
        self._occupant = [None] * self.config.slots
        self._slot_steps = [0] * self.config.slots
        # cumulative stats (+ the windowed counterparts stats_window()
        # reads-and-resets; the router balances on the window)
        self._n = collections.Counter()
        self._win = collections.Counter()
        self._q_high_water = 0

        self._thread = threading.Thread(target=self._loop,
                                        name='decode-loop', daemon=True)
        self._thread.start()

    # -- program build -----------------------------------------------------

    def _check_draft(self, draft):
        """Validate the speculative draft: a small attention-LSTM
        weights dict (same vocab + enc_dim as the target, any hidden /
        embedding size) or a [vocab] int next-token TABLE (the n-gram /
        prompt-lookup speculator). Returns ('weights', dict) /
        ('table', np.int32 array) / None."""
        cfg = self.config
        if not cfg.spec_k:
            if draft is not None:
                raise ValueError('draft= needs DecodeConfig(spec_k=K)')
            return None
        if draft is None:
            raise ValueError('DecodeConfig(spec_k=%d) needs a draft: '
                             'DecodeEngine(draft=weights dict or [vocab]'
                             ' next-token table)' % cfg.spec_k)
        if isinstance(draft, dict):
            missing = [k for k in WEIGHT_KEYS if k not in draft]
            if missing:
                raise ValueError('draft weights missing %r' % (missing,))
            d_enc = int(np.asarray(draft['w_q']).shape[1])
            d_vocab = int(np.asarray(draft['w_out']).shape[1])
            if d_enc != self._enc_dim or d_vocab != self._vocab:
                raise ValueError(
                    'draft must share the target vocab (%d) and enc_dim '
                    '(%d); got vocab=%d enc_dim=%d'
                    % (self._vocab, self._enc_dim, d_vocab, d_enc))
            return ('weights', draft)
        table = np.asarray(draft)
        if table.ndim != 1 or table.shape[0] != self._vocab \
                or not np.issubdtype(table.dtype, np.integer):
            raise ValueError(
                'a table draft must be a [vocab=%d] int next-token '
                'array, got %r %r' % (self._vocab, table.dtype,
                                      table.shape))
        return ('table', table.astype(np.int32))

    def _on_prefix_evict(self, key, pages):
        _C_PREFIX_EVICT.inc()
        obs.event('decode.prefix.evict', key=key[:12], pages=len(pages))

    def _build_step_program(self, weights):
        """The step-form decode Program: one
        `attention_lstm_beam_decode_step` op over persistable slot state
        + the decoder weights. Exported by `export_step_program` (and
        linted by tools/lint.sh) as an ordinary __model__ artifact."""
        import jax.numpy as jnp
        from ..fluid import framework

        cfg = self.config
        prog = framework.Program()
        blk = prog.global_block()
        C, K, T, S = cfg.slots, cfg.beam_size, cfg.max_len, cfg.src_cap
        H, D = self._hidden, self._enc_dim
        ps, NPH, NPE = (cfg.page_size, cfg.hist_table_width,
                        cfg.enc_table_width)

        def pvar(name, shape, dtype, init=None):
            v = blk.create_var(name='cbd_' + name, shape=shape,
                               dtype=dtype, persistable=True)
            if init is not None:
                self._scope.vars['cbd_' + name] = jnp.asarray(init)
            return v

        wvars = {}
        for k in WEIGHT_KEYS:
            a = np.asarray(weights[k], np.float32)
            wvars[k] = pvar(k, list(a.shape), 'float32', a)

        spec = {'h': ([C, K, H], 'float32'), 'c': ([C, K, H], 'float32'),
                'prev_ids': ([C, K], 'int32'), 'acc': ([C, K], 'float32'),
                'fin': ([C, K], 'bool'),
                'step': ([C], 'int32'), 'limit': ([C], 'int32'),
                'active': ([C], 'bool')}
        if cfg.paged:
            # the dense [C, T, K] / [C, S, D] buffers become pools +
            # per-slot page tables: a slot only claims the pages its OWN
            # limit and source length need. pt_hist defaults to the
            # out-of-range page (writes drop), pt_enc to the reserved
            # zero page (reads stay finite under the mask).
            spec.update({
                'hist_ids': ([cfg.pages, ps, K], 'int32'),
                'hist_par': ([cfg.pages, ps, K], 'int32'),
                'enc_pages': ([cfg.enc_pages, ps, D], 'float32'),
                'mask_pages': ([cfg.enc_pages, ps], 'float32'),
                'pt_hist': ([C, NPH], 'int32'),
                'pt_enc': ([C, NPE], 'int32')})
        else:
            spec.update({
                'enc': ([C, S, D], 'float32'), 'mask': ([C, S], 'float32'),
                'ids_hist': ([C, T, K], 'int32'),
                'par_hist': ([C, T, K], 'int32')})
        if self._draft and self._draft[0] == 'weights':
            Hd = int(np.asarray(self._draft[1]['u_dec']).shape[0])
            spec.update({'draft_h': ([C, Hd], 'float32'),
                         'draft_c': ([C, Hd], 'float32')})
        svars = {}
        for name, (shape, dtype) in spec.items():
            fill = cfg.pages if name == 'pt_hist' else 0
            svars[name] = pvar(name, shape, dtype,
                               jnp.full(shape, fill, np.dtype(dtype)))
        dvars = {}
        if self._draft and self._draft[0] == 'weights':
            for k in WEIGHT_KEYS:
                a = np.asarray(self._draft[1][k], np.float32)
                dvars[k] = pvar('d_' + k, list(a.shape), 'float32', a)
        elif self._draft:
            dvars['table'] = pvar('d_table',
                                  [self._vocab], 'int32',
                                  self._draft[1])
        done = blk.create_var(name='cbd_done', shape=[C], dtype='bool')
        bad = blk.create_var(name='cbd_bad', shape=[C], dtype='bool')

        weight_ins = {
            'WDec': [wvars['w_dec']], 'UDec': [wvars['u_dec']],
            'BDec': [wvars['b_dec']], 'WAttnQ': [wvars['w_q']],
            'WEmb': [wvars['w_emb']], 'WOut': [wvars['w_out']],
            'BOut': [wvars['b_out']]}
        state_ins = {
            'H': [svars['h']], 'C': [svars['c']],
            'PrevIds': [svars['prev_ids']], 'Acc': [svars['acc']],
            'Fin': [svars['fin']], 'Step': [svars['step']],
            'Limit': [svars['limit']], 'Active': [svars['active']]}
        state_outs = {
            'HOut': [svars['h']], 'COut': [svars['c']],
            'PrevIdsOut': [svars['prev_ids']], 'AccOut': [svars['acc']],
            'FinOut': [svars['fin']], 'StepOut': [svars['step']],
            'ActiveOut': [svars['active']],
            'Done': [done], 'Bad': [bad]}
        if cfg.paged:
            state_ins.update({
                'PtHist': [svars['pt_hist']], 'PtEnc': [svars['pt_enc']],
                'HistIds': [svars['hist_ids']],
                'HistPar': [svars['hist_par']],
                'EncPages': [svars['enc_pages']],
                'MaskPages': [svars['mask_pages']]})
            state_outs.update({'HistIdsOut': [svars['hist_ids']],
                               'HistParOut': [svars['hist_par']]})
        else:
            state_ins.update({
                'Enc': [svars['enc']], 'Mask': [svars['mask']],
                'IdsHist': [svars['ids_hist']],
                'ParHist': [svars['par_hist']]})
            state_outs.update({'IdsHistOut': [svars['ids_hist']],
                               'ParHistOut': [svars['par_hist']]})
        if cfg.spec_k:
            accepted = blk.create_var(name='cbd_accepted', shape=[C],
                                      dtype='int32')
            ins = dict(state_ins)
            ins.update(weight_ins)
            if self._draft[0] == 'weights':
                ins.update({'Draft' + k: [v] for k, v in {
                    'WDec': dvars['w_dec'], 'UDec': dvars['u_dec'],
                    'BDec': dvars['b_dec'], 'WAttnQ': dvars['w_q'],
                    'WEmb': dvars['w_emb'], 'WOut': dvars['w_out'],
                    'BOut': dvars['b_out']}.items()})
                ins.update({'DraftH': [svars['draft_h']],
                            'DraftC': [svars['draft_c']]})
                state_outs.update({'DraftHOut': [svars['draft_h']],
                                   'DraftCOut': [svars['draft_c']]})
            else:
                ins['DraftTable'] = [dvars['table']]
            outs = dict(state_outs)
            outs['Accepted'] = [accepted]
            blk.append_op(
                type='attention_lstm_spec_decode_step', inputs=ins,
                outputs=outs,
                attrs={'end_id': cfg.end_id, 'spec_k': cfg.spec_k,
                       'page_size': ps, 'src_cap': S,
                       'draft': self._draft[0]})
        else:
            ins = dict(state_ins)
            ins.update(weight_ins)
            blk.append_op(
                type='attention_lstm_beam_paged_step' if cfg.paged
                else 'attention_lstm_beam_decode_step',
                inputs=ins, outputs=dict(state_outs),
                attrs=dict({'beam_size': cfg.beam_size,
                            'end_id': cfg.end_id, 'bundle': cfg.bundle},
                           **({'page_size': ps, 'src_cap': S}
                              if cfg.paged else {})))
        self._step_program = prog
        # fetching the state with every step makes a slot release a pure
        # numpy slice (one host sync per dispatch that released
        # something) instead of per-release device gathers — on a CPU
        # box device dispatch costs more than the decode math. Releases
        # are LEVEL-triggered off Active (occupied slot now inactive;
        # poisoning detected from NaN in the fetched scores), not off
        # the per-dispatch Done edge: an extra dispatch (e.g. warmup's
        # no-op step racing live traffic) can swallow an edge, but a
        # level can't be lost.
        ids_n, par_n = ('hist_ids', 'hist_par') if cfg.paged \
            else ('ids_hist', 'par_hist')
        self._fetch_vars = [svars['active'], svars[ids_n], svars[par_n],
                            svars['acc'], svars['step']]
        if cfg.spec_k:
            self._fetch_vars.append(accepted)
        names = _WRITTEN_STATE_PAGED + _READONLY_STATE_PAGED \
            if cfg.paged else _WRITTEN_STATE + _READONLY_STATE
        if 'draft_h' in spec:
            names = names + _DRAFT_STATE
        self._state_names = ['cbd_' + n for n in names]
        self._state_spec = spec
        self._join_fn = self._build_join_fn()

    def _build_join_fn(self):
        """One jitted row-scatter admitting a BUCKET of joining requests
        into their slots in a single dispatch, state donated so the
        update is in place. Rows padded past the real join count carry
        valid=False and scatter to index `slots`, which mode='drop'
        discards — so the signature set is exactly cfg.admit_buckets
        (pre-compiled by warmup, like the prefill buckets).

        Paged form: instead of dense enc/mask rows the join writes the
        slot's PAGE-TABLE rows and scatters the encoder content into
        its freshly-allocated pages. Prefix-cache hits pass the
        out-of-range write page, so resident pages are never rewritten
        (their content is the hit)."""
        import jax
        import jax.numpy as jnp
        cfg = self.config
        K, H = cfg.beam_size, self._hidden
        neg = float(np.finfo(np.float32).min)
        acc0 = np.full((K,), neg, np.float32)
        acc0[0] = 0.0
        draft_hd = None
        if self._draft and self._draft[0] == 'weights':
            draft_hd = int(np.asarray(self._draft[1]['u_dec']).shape[0])

        def base_puts(st, idx, m, valid, limit):
            def put(name, rows):
                full = 'cbd_' + name
                st[full] = st[full].at[idx].set(
                    rows.astype(st[full].dtype), mode='drop')

            put('h', jnp.zeros((m, K, H), jnp.float32))
            put('c', jnp.zeros((m, K, H), jnp.float32))
            put('prev_ids', jnp.full((m, K), cfg.start_id, jnp.int32))
            put('acc', jnp.broadcast_to(jnp.asarray(acc0), (m, K)))
            put('fin', jnp.zeros((m, K), bool))
            put('step', jnp.zeros((m,), jnp.int32))
            put('limit', limit)
            put('active', valid)
            if draft_hd is not None:
                put('draft_h', jnp.zeros((m, draft_hd), jnp.float32))
                put('draft_c', jnp.zeros((m, draft_hd), jnp.float32))
            return put

        if not cfg.paged:
            def join(st, slot_idx, valid, enc, mask, limit):
                idx = jnp.where(valid, slot_idx, cfg.slots)
                m = slot_idx.shape[0]
                put = base_puts(st, idx, m, valid, limit)
                put('enc', enc)
                put('mask', mask)
                return st

            return jax.jit(join, donate_argnums=(0,))

        ps, NPE = cfg.page_size, cfg.enc_table_width

        def join_paged(st, slot_idx, valid, enc, mask, limit,
                       pt_hist_rows, pt_enc_rows, enc_write_pages):
            idx = jnp.where(valid, slot_idx, cfg.slots)
            m = slot_idx.shape[0]
            put = base_puts(st, idx, m, valid, limit)
            put('pt_hist', pt_hist_rows)
            put('pt_enc', pt_enc_rows)
            # page-content scatter: [m, NPE] write pages (out-of-range
            # = drop: bucket padding, prefix hits, zero-page tails)
            pages_flat = enc_write_pages.reshape(-1)
            st['cbd_enc_pages'] = st['cbd_enc_pages'].at[pages_flat].set(
                enc.reshape(m * NPE, ps, enc.shape[-1]), mode='drop')
            st['cbd_mask_pages'] = st['cbd_mask_pages'].at[
                pages_flat].set(mask.reshape(m * NPE, ps), mode='drop')
            return st

        return jax.jit(join_paged, donate_argnums=(0,))

    def _scatter_join(self, slot_idx, valid, enc, mask, limit,
                      pt_hist_rows=None, pt_enc_rows=None,
                      enc_write_pages=None):
        """Run the jitted join over the handle's live state; inputs are
        bucket-padded host arrays. Serialized with handle creation and
        the step dispatch via _handle_lock (warmup's bucket probes run
        on the caller thread)."""
        handle = self._acquire()
        with self._handle_lock:
            st_all = handle.state
            st = {n: st_all[n] for n in self._state_names}
            args = [st, np.asarray(slot_idx, np.int32),
                    np.asarray(valid, bool),
                    np.asarray(enc, np.float32),
                    np.asarray(mask, np.float32),
                    np.asarray(limit, np.int32)]
            if self.config.paged:
                args += [np.asarray(pt_hist_rows, np.int32),
                         np.asarray(pt_enc_rows, np.int32),
                         np.asarray(enc_write_pages, np.int32)]
            new = self._join_fn(*args)
            for name, val in new.items():
                handle.set_state(name, val)

    def _acquire(self):
        # RLock: warmup() runs on the caller thread while the decode
        # loop may be admitting/stepping — handle creation and every
        # donated-state mutation (_scatter_join, step) serialize on it
        with self._handle_lock:
            if self._handle is None:
                self._handle = self._exe.acquire_step(
                    self._step_program, feed=None,
                    fetch_list=self._fetch_vars, scope=self._scope)
                plan = self._handle._compiled.plan
                obs.event('decode.memory_plan', donates=plan.donates,
                          writes=sorted(plan.write_set))
            return self._handle

    def export_step_program(self, dirname):
        """Save the step-form decode Program (+ its weight/state
        persistables) as an ordinary inference artifact —
        tools/program_lint.py lints it like any saved __model__
        (tools/lint.sh wires that in)."""
        from ..fluid import io
        from ..fluid.executor import scope_guard
        # _handle_lock: the decode loop's in-flight dispatch donates the
        # scope's state buffers mid-step; exporting must not read them
        with self._handle_lock:
            with scope_guard(self._scope):
                io.save_inference_model(dirname, [],
                                        list(self._fetch_vars),
                                        self._exe,
                                        main_program=self._step_program)
        return dirname

    def push_rows(self, deltas):
        """Scatter trained row deltas into this replica's LIVE decoder
        weights between dispatches — the streaming train->serve
        freshness path applied to the continuous-batching engine
        (docs/serving.md#delta-push). `deltas` maps a step-program
        persistable name to `(row_ids, rows)`.

        Built on the StepHandle donation-safe mutation seam: the update
        runs under `_handle_lock` (the same lock every dispatch, join
        scatter, and warmup probe serializes on), so a push never
        interleaves an in-flight step, and it lands through
        `StepHandle.set_state` — the handle's view and the scope stay
        one object, so the scope-identity invalidation check keeps
        holding. Only READ-ONLY persistables (the memory plan's
        non-donated set: the decoder weights) take deltas; the donated
        decode-pool state (slot carries, histories, page content) is
        typed DeltaUnsupported — scattering rows into per-slot state
        would corrupt live decodes. A poisoned slot is irrelevant here
        by construction: pushes touch weights, never slot state.
        Returns rows applied."""
        import jax.numpy as jnp
        from .engine import DeltaUnsupported, _validate_delta
        if self._shutdown:
            raise ServerClosed('decode engine is shut down')
        applied = 0
        with self._handle_lock:
            handle = self._acquire()
            for name in sorted(deltas):
                ids, rows = deltas[name]
                if name in handle._donated:
                    raise DeltaUnsupported(
                        'push_rows: %r is donated per-step decode state '
                        '(slot pool), not a weight — row deltas apply '
                        'only to the read-only set %r'
                        % (name, sorted(handle._readonly)))
                w = handle._readonly.get(name)
                if w is None:
                    raise KeyError(
                        'push_rows: no read-only persistable %r in the '
                        'decode step (have %r)'
                        % (name, sorted(handle._readonly)))
                ids, rows = _validate_delta(name, w, ids, rows)
                handle.set_state(name,
                                 jnp.asarray(w).at[ids].set(rows))
                applied += int(ids.shape[0])
            self._n['delta_pushes'] += 1
            self._n['delta_rows'] += applied
        return applied

    # -- admission ---------------------------------------------------------

    def submit(self, feed, max_new_tokens=None, deadline_ms=None,
               timeout=None, on_token=None, resume=None, checkpoint=None,
               ckpt_every=0):
        """Enqueue one decode request; returns a Future resolving to
        (sentence_ids [beam, max_new_tokens] int, sentence_scores [beam]
        float32). Raises ServerClosed after shutdown, ServerOverloaded
        under the 'reject' policy (or a 'block' admission timeout), and
        ValueError for malformed feeds. A deadline sheds the request
        with DeadlineExceeded if it is still QUEUED when it passes (an
        already-decoding sequence completes).

        Streaming (docs/serving.md#streams): `on_token(t, ids)` fires
        from the decode-loop thread for every generated token, t = 1..,
        ids = the [beam_size] raw beam column for that step (the final
        result is still the backtraced history). A callback that RAISES
        marks the consumer gone: the slot is aborted, its pages return
        to the pool, and the future fails typed StreamCancelled.
        `checkpoint(state)` fires every `ckpt_every` tokens with a dict
        that `resume=` accepts verbatim; `resume` overwrites the slot's
        state right after the join so generation continues token-exact
        from state['step'] — the decode-stream failover path. Resume is
        applied EAGERLY through the push_rows seam, so it compiles
        nothing."""
        cfg = self.config
        limit = cfg.max_len if max_new_tokens is None else int(max_new_tokens)
        if not 1 <= limit <= cfg.max_len:
            raise ValueError(
                'max_new_tokens=%d out of range [1, %d] (the slot token '
                'buffer is fixed at engine build)' % (limit, cfg.max_len))
        ckpt_every = int(ckpt_every or 0)
        if on_token is not None and not callable(on_token):
            raise ValueError('on_token must be callable, got %r'
                             % (on_token,))
        if checkpoint is not None and not callable(checkpoint):
            raise ValueError('checkpoint must be callable, got %r'
                             % (checkpoint,))
        if resume is not None:
            resume = {k: np.asarray(v) for k, v in dict(resume).items()}
            need = ('h', 'c', 'prev_ids', 'acc', 'fin', 'step', 'ids',
                    'par')
            missing = [k for k in need if k not in resume]
            if missing:
                raise ValueError('resume state missing %r (need %r)'
                                 % (missing, list(need)))
            t_res = int(resume['step'])
            K = cfg.beam_size
            if not 0 <= t_res <= limit:
                raise ValueError(
                    'resume step=%d out of range [0, max_new_tokens=%d]'
                    % (t_res, limit))
            if tuple(resume['ids'].shape) != (t_res, K) \
                    or tuple(resume['par'].shape) != (t_res, K):
                raise ValueError(
                    'resume ids/par must be [%d, %d] (step x beam), got '
                    '%r / %r' % (t_res, K, tuple(resume['ids'].shape),
                                 tuple(resume['par'].shape)))
            if t_res >= limit:
                # nothing left to generate: the checkpoint already holds
                # the full history — resolve without consuming a slot
                from ..fluid.ops_impl.lod_beam import backtrace_beams
                toks = backtrace_beams(resume['ids'].astype(np.int32),
                                       resume['par'].astype(np.int32))
                fut = concurrent.futures.Future()
                fut.set_running_or_notify_cancel()
                fut.set_result((toks.astype(np.int64),
                                resume['acc'].astype(np.float32)))
                return fut
        if self._prefill is None:
            if 'enc' not in feed:
                raise ValueError(
                    "an engine without a prefill takes encoder rows "
                    "directly: feed must carry 'enc' (got %r)"
                    % sorted(feed))
            enc = np.asarray(feed['enc'], np.float32)
            if enc.ndim != 2 or not 1 <= enc.shape[0] <= cfg.src_cap \
                    or enc.shape[1] != self._enc_dim:
                raise ValueError(
                    "feed['enc'] must be [1<=S<=%d, %d], got %r"
                    % (cfg.src_cap, self._enc_dim, enc.shape))
            feed = {'enc': enc}
        if deadline_ms is None:
            deadline_ms = cfg.default_deadline_ms
        now = time.monotonic()
        deadline = now + deadline_ms / 1000.0 if deadline_ms is not None \
            else None
        pkey, hist_need, enc_need = None, 0, 0
        if cfg.paged:
            hist_need = _pages.pages_for(limit, cfg.page_size)
            if self._prefill is None:
                enc_need = _pages.pages_for(feed['enc'].shape[0],
                                            cfg.page_size)
            else:
                # actual source length is only known after prefill; the
                # admission gate budgets the worst case and the surplus
                # is released right after prefill returns
                enc_need = cfg.enc_table_width
            if cfg.prefix_cache:
                pkey = _pages.content_key(feed)
        fut = concurrent.futures.Future()
        req = _Request(feed, limit, fut, now, deadline, pkey=pkey,
                       hist_need=hist_need, enc_need=enc_need,
                       on_token=on_token, resume=resume,
                       checkpoint=checkpoint, ckpt_every=ckpt_every)
        t_give_up = now + timeout if timeout is not None else None
        with self._lock:
            while True:
                if self._shutdown:
                    raise ServerClosed('decode engine is shut down')
                if len(self._queue) < cfg.queue_capacity:
                    break
                # the queue can be full because joins are blocked on an
                # exhausted page pool — a typed admission signal, not a
                # crash; the reject event says which wall was hit
                reason = 'pages' if self._pages_starved else 'queue'
                if cfg.overflow == 'reject':
                    self._n['rejected'] += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('decode.reject',
                              queue_depth=len(self._queue),
                              capacity=cfg.queue_capacity,
                              reason=reason)
                    raise ServerOverloaded(
                        'decode queue is full (%d request(s), capacity '
                        '%d; blocked on %s) and the overflow policy is '
                        'reject' % (len(self._queue), cfg.queue_capacity,
                                    'free pages' if reason == 'pages'
                                    else 'free slots'))
                remaining = _POLL_S if t_give_up is None else \
                    min(_POLL_S, t_give_up - time.monotonic())
                if t_give_up is not None and remaining <= 0:
                    self._n['rejected'] += 1
                    self._win['rejected'] += 1
                    _C_REJECTED.inc()
                    obs.event('decode.reject',
                              queue_depth=len(self._queue),
                              capacity=cfg.queue_capacity,
                              waited_s=timeout, reason=reason)
                    raise ServerOverloaded(
                        'decode queue stayed full for %.3fs (capacity %d)'
                        % (timeout, cfg.queue_capacity))
                self._not_full.wait(remaining)
            self._queue.append(req)
            self._n['submitted'] += 1
            self._win['submitted'] += 1
            depth = len(self._queue)
            self._q_high_water = max(self._q_high_water, depth)
            self._win['queue_high_water'] = max(
                self._win['queue_high_water'], depth)
            _C_REQUESTS.inc()
            _G_QDEPTH.set(depth)
            self._not_empty.notify()
        return fut

    def predict(self, feed, max_new_tokens=None, deadline_ms=None,
                timeout=None):
        """Synchronous convenience: submit + wait, one wall-clock budget
        for admission and result (ServingEngine.predict semantics)."""
        t0 = time.monotonic()
        fut = self.submit(feed, max_new_tokens=max_new_tokens,
                          deadline_ms=deadline_ms, timeout=timeout)
        remaining = None if timeout is None else \
            max(0.0, timeout - (time.monotonic() - t0))
        try:
            return fut.result(remaining)
        except concurrent.futures.TimeoutError:
            if fut.done():
                return fut.result()
            if fut.cancel():
                raise DeadlineExceeded(
                    'no result within the %.3fs predict() timeout; the '
                    'queued decode request was cancelled' % timeout)
            raise DeadlineExceeded(
                'no result within the %.3fs predict() timeout; the '
                'sequence is already decoding — it completes but the '
                'result is discarded' % timeout)

    def cancel(self, future):
        """Best-effort cancel of one submitted request by its future —
        the mid-stream-disconnect path (the pod worker calls this when
        a stream's client connection dies). A still-QUEUED request
        fails StreamCancelled immediately; one already decoding has its
        slot aborted at the next dispatch boundary, returning the slot
        AND its pages to the pool (no leaked capacity). Returns True if
        the request was found, False if it already completed or was
        never this engine's."""
        with self._lock:
            found = None
            for i, r in enumerate(self._queue):
                if r.future is future:
                    found = r
                    del self._queue[i]
                    _G_QDEPTH.set(len(self._queue))
                    self._not_full.notify()
                    break
        if found is not None:
            if found.future.set_running_or_notify_cancel():
                found.future.set_exception(StreamCancelled(
                    'decode request cancelled while queued'))
            return True
        for occ in list(self._occupant):
            if occ is not None and occ.future is future:
                occ.aborted = True
                return True
        return False

    # -- warmup ------------------------------------------------------------

    def warmup(self, example_feed=None):
        """Pre-compile the closed signature set — the ONE decode-step
        module plus one prefill signature per admission bucket — so
        steady-state decoding performs zero compiles (assert via
        `cache_stats`; the acceptance drill does). Returns the bucket
        list. With a prefill, `example_feed` (any single request feed)
        seeds the per-bucket probe batches."""
        cfg = self.config
        handle = self._acquire()
        with self._handle_lock:
            handle.step()             # all slots inactive: a no-op step
        for b in cfg.admit_buckets:   # join-scatter kernel per bucket
            with obs.span('decode.warmup', bucket=b, kind='join'):
                if cfg.paged:
                    # all-invalid probe: page writes drop, the allocator
                    # is never touched
                    S_pad = cfg.enc_table_width * cfg.page_size
                    self._scatter_join(
                        np.zeros(b, np.int32), np.zeros(b, bool),
                        np.zeros((b, S_pad, self._enc_dim), np.float32),
                        np.zeros((b, S_pad), np.float32),
                        np.zeros(b, np.int32),
                        np.full((b, cfg.hist_table_width), cfg.pages,
                                np.int32),
                        np.zeros((b, cfg.enc_table_width), np.int32),
                        np.full((b, cfg.enc_table_width), cfg.enc_pages,
                                np.int32))
                else:
                    self._scatter_join(
                        np.zeros(b, np.int32), np.zeros(b, bool),
                        np.zeros((b, cfg.src_cap, self._enc_dim),
                                 np.float32),
                        np.zeros((b, cfg.src_cap), np.float32),
                        np.zeros(b, np.int32))
        if self._prefill is not None:
            if example_feed is None:
                raise ValueError(
                    'warmup() needs example_feed when the engine owns a '
                    'prefill (it cannot synthesize model inputs)')
            for b in cfg.admit_buckets:
                with obs.span('decode.warmup', bucket=b, kind='prefill'):
                    self._prefill([dict(example_feed)] * b)
        self._warm = True
        return list(cfg.admit_buckets)

    # -- decode loop -------------------------------------------------------

    def _pop_live_locked(self, now, shed, cap):
        """Pop up to `cap` still-wanted requests; expired ones collect
        into `shed` (failed by the caller OUTSIDE the lock, like the
        serving engine's batcher). In paged mode a head whose page
        claim cannot be covered RIGHT NOW (free + evictable) BLOCKS in
        the queue — FIFO head-of-line, so admission order is preserved;
        its deadline still sheds it, and the engine marks itself
        page-starved for the reject events' reason field."""
        out = []
        budget = None
        pinned = set()
        if self.config.paged:
            budget = {'hist': self._hist_pool.available(),
                      'enc': self._enc_pool.available(self._prefix)}
        starved = False
        while self._queue and len(out) < cap:
            req = self._queue[0]
            if req.deadline is not None and now > req.deadline:
                self._queue.popleft()
                self._not_full.notify()
                shed.append(req)
                continue
            if budget is not None:
                enc_need = req.enc_need
                if req.pkey is not None and self._prefix.peek(req.pkey):
                    # resident prefix: no NEW pages, but the hit PINS
                    # the entry (refs>0), taking its pages out of the
                    # evictable budget batch-mates were counting on —
                    # charge that once per key or the admit-time alloc
                    # comes up short and fails the whole batch
                    enc_need = 0 if req.pkey in pinned \
                        else self._prefix.pinnable_pages(req.pkey)
                if req.hist_need > budget['hist'] \
                        or enc_need > budget['enc']:
                    starved = True        # head-of-line blocks on pages
                    break
                budget['hist'] -= req.hist_need
                budget['enc'] -= enc_need
                if req.pkey is not None:
                    pinned.add(req.pkey)
            self._queue.popleft()
            self._not_full.notify()
            if not req.future.set_running_or_notify_cancel():
                continue              # cancelled while queued
            out.append(req)
        self._pages_starved = starved
        _G_QDEPTH.set(len(self._queue))
        return out

    def _fail_shed(self, shed):
        now = time.monotonic()
        for req in shed:
            if not req.future.set_running_or_notify_cancel():
                continue
            with self._lock:   # _win races stats_window's copy+reset
                self._n['shed'] += 1
                self._win['shed'] += 1
            _C_SHED.inc()
            waited = now - req.t_submit
            obs.event('decode.shed', waited_s=waited)
            req.future.set_exception(DeadlineExceeded(
                'decode request shed after waiting %.3fs: its deadline '
                'passed before a slot opened' % waited))

    def _admit(self, joins):
        """Prefill + scatter the joining requests' slot state in ONE
        bucket-padded jitted join (loop thread only). A prefill/feed
        failure fails ONLY the joining futures."""
        if self.config.paged:
            return self._admit_paged(joins)
        cfg = self.config
        b = _buckets.pick_bucket(len(joins), cfg.admit_buckets)
        try:
            if self._prefill is not None:
                feeds = [r.feed for r in joins]
                feeds += [joins[-1].feed] * (b - len(joins))
                enc, src_len = self._prefill(feeds)
                enc = np.asarray(enc, np.float32)[:len(joins)]
                src_len = np.asarray(src_len, np.int32)[:len(joins)]
                # a short/misshapen prefill return must fail HERE, not
                # broadcast silently into the batch assembly below
                if enc.ndim != 3 or enc.shape[0] != len(joins):
                    raise ValueError(
                        'prefill returned enc of shape %r for %d '
                        'request(s) (want [n, S, %d])'
                        % (getattr(enc, 'shape', None), len(joins),
                           self._enc_dim))
                if src_len.shape != (len(joins),):
                    raise ValueError(
                        'prefill returned src_len of shape %r for %d '
                        'request(s)' % (src_len.shape, len(joins)))
                if enc.shape[1] > cfg.src_cap:
                    raise ValueError(
                        'prefill returned %d encoder rows > src_cap=%d'
                        % (enc.shape[1], cfg.src_cap))
            else:
                src_len = np.asarray([r.feed['enc'].shape[0]
                                      for r in joins], np.int32)
                enc = np.zeros((len(joins), int(src_len.max()),
                                self._enc_dim), np.float32)
                for i, r in enumerate(joins):
                    enc[i, :src_len[i]] = r.feed['enc']
            # bucket-padded batch ASSEMBLY stays inside the try: a
            # malformed prefill product failing here must resolve only
            # the joining futures, never reach the loop's crash guard
            pad = b - len(joins)
            valid = np.asarray([True] * len(joins) + [False] * pad)
            enc_b = np.zeros((b, cfg.src_cap, self._enc_dim), np.float32)
            enc_b[:len(joins), :enc.shape[1]] = enc
            mask_b = np.zeros((b, cfg.src_cap), np.float32)
            mask_b[:len(joins)] = (np.arange(cfg.src_cap)[None, :]
                                   < src_len[:, None])
            limit_b = np.zeros(b, np.int32)
            limit_b[:len(joins)] = [r.limit for r in joins]
        except Exception as e:  # noqa: BLE001 — the joiners' futures own it
            for r in joins:
                if not r.future.done():
                    r.future.set_exception(e)
            obs.event('decode.prefill.error',
                      requests=len(joins),
                      error='%s: %s' % (type(e).__name__, e))
            return

        free = [i for i, occ in enumerate(self._occupant) if occ is None]
        slot_idx = np.asarray(free[:len(joins)] + [0] * (b - len(joins)),
                              np.int32)
        self._scatter_join(slot_idx, valid, enc_b, mask_b, limit_b)
        now = time.monotonic()
        for i, req in enumerate(joins):
            slot = free[i]
            self._occupant[slot] = req
            self._slot_steps[slot] = 0
            req.t_join = now
            if req.resume is not None:
                self._apply_resume(slot, req)
            with self._lock:
                self._n['joins'] += 1
                self._win['joins'] += 1
            _C_JOINS.inc()
            obs.event('decode.join', slot=slot, limit=req.limit,
                      src_len=int(src_len[i]))
        occ_now = sum(o is not None for o in self._occupant)
        _G_SLOTS.set(occ_now)
        with self._lock:
            self._n['slots_high_water'] = max(
                self._n['slots_high_water'], occ_now)

    def _admit_paged(self, joins):
        """Paged admission (loop thread only): prefix-cache lookups
        FIRST (so a resident entry a batch-mate relies on cannot be
        evicted by this batch's own allocations), then prefill for the
        MISSES only — a prefix hit joins WITHOUT re-prefilling — then
        page claims, then one bucket-padded join scatter writing page
        tables + fresh page content. The admission gate
        (_pop_live_locked) already budgeted the worst case, so the
        claims cannot fail; a prefill/feed failure rolls every claim
        back and fails ONLY the joining futures."""
        cfg = self.config
        ps, NPE, NPH = (cfg.page_size, cfg.enc_table_width,
                        cfg.hist_table_width)
        S_pad = NPE * ps
        n = len(joins)
        # enc plan per join: ('hit', pages, src_len) | ('miss', j) with
        # j its row in the prefill batch | ('dup', i_first)
        plan = [None] * n
        first_by_key = {}
        miss_idx = []
        for i, r in enumerate(joins):
            if r.pkey is not None and self._prefix.peek(r.pkey):
                got = self._prefix.lookup(r.pkey)
                plan[i] = ('hit',) + tuple(got)
                continue
            if r.pkey is not None and r.pkey in first_by_key:
                plan[i] = ('dup', first_by_key[r.pkey])
                continue
            if r.pkey is not None:
                first_by_key[r.pkey] = i
                self._prefix.misses += 1   # cache-level miss
            plan[i] = ('miss', len(miss_idx))
            miss_idx.append(i)
        claimed_enc, claimed_hist = [], []    # rollback ledger
        try:
            # -- prefill / direct content for the misses only ----------
            if miss_idx and self._prefill is not None:
                b_pf = _buckets.pick_bucket(len(miss_idx),
                                            cfg.admit_buckets)
                feeds = [joins[i].feed for i in miss_idx]
                feeds += [joins[miss_idx[-1]].feed] \
                    * (b_pf - len(miss_idx))
                enc_m, len_m = self._prefill(feeds)
                enc_m = np.asarray(enc_m, np.float32)[:len(miss_idx)]
                len_m = np.asarray(len_m, np.int32)[:len(miss_idx)]
                if enc_m.ndim != 3 or enc_m.shape[0] != len(miss_idx):
                    raise ValueError(
                        'prefill returned enc of shape %r for %d '
                        'request(s) (want [n, S, %d])'
                        % (getattr(enc_m, 'shape', None), len(miss_idx),
                           self._enc_dim))
                if len_m.shape != (len(miss_idx),):
                    raise ValueError(
                        'prefill returned src_len of shape %r for %d '
                        'request(s)' % (len_m.shape, len(miss_idx)))
                if enc_m.shape[1] > cfg.src_cap:
                    raise ValueError(
                        'prefill returned %d encoder rows > src_cap=%d'
                        % (enc_m.shape[1], cfg.src_cap))
            elif miss_idx:
                len_m = np.asarray([joins[i].feed['enc'].shape[0]
                                    for i in miss_idx], np.int32)
                enc_m = np.zeros((len(miss_idx), int(len_m.max()),
                                  self._enc_dim), np.float32)
                for j, i in enumerate(miss_idx):
                    enc_m[j, :len_m[j]] = joins[i].feed['enc']
            # -- page claims (the pop gate guaranteed coverage; cache
            # insertion waits until the content is actually written) ---
            miss_pages = []
            for j, i in enumerate(miss_idx):
                need = _pages.pages_for(int(len_m[j]), ps)
                pages = self._enc_pool.alloc(need, self._prefix)
                if pages is None:       # gate bug — fail loudly
                    raise RuntimeError(
                        'encoder page pool exhausted mid-admit (%d '
                        'needed, %d free)' % (need,
                                              self._enc_pool.free_count))
                miss_pages.append(pages)
                claimed_enc.append(pages)
            hist_pages = []
            for r in joins:
                pages = self._hist_pool.alloc(r.hist_need)
                if pages is None:
                    raise RuntimeError(
                        'history page pool exhausted mid-admit (%d '
                        'needed, %d free)' % (r.hist_need,
                                              self._hist_pool.free_count))
                hist_pages.append(pages)
                claimed_hist.append(pages)
            # -- bucket-padded join arrays -----------------------------
            b = _buckets.pick_bucket(n, cfg.admit_buckets)
            pad = b - n
            valid = np.asarray([True] * n + [False] * pad)
            enc_b = np.zeros((b, S_pad, self._enc_dim), np.float32)
            mask_b = np.zeros((b, S_pad), np.float32)
            limit_b = np.zeros(b, np.int32)
            limit_b[:n] = [r.limit for r in joins]
            pt_hist_b = np.full((b, NPH), cfg.pages, np.int32)
            pt_enc_b = np.zeros((b, NPE), np.int32)   # tail: zero page
            wr_enc_b = np.full((b, NPE), cfg.enc_pages, np.int32)
            src_len = np.zeros(n, np.int32)
            enc_pages_of = [None] * n
            for i, r in enumerate(joins):
                kind = plan[i][0]
                if kind == 'hit':
                    pages, s_len = plan[i][1], plan[i][2]
                elif kind == 'dup':
                    j = plan[i][1]
                    jj = miss_idx.index(j)
                    pages, s_len = miss_pages[jj], int(len_m[jj])
                else:
                    j = plan[i][1]
                    pages, s_len = miss_pages[j], int(len_m[j])
                    enc_b[i, :enc_m.shape[1]] = enc_m[j]
                    mask_b[i, :cfg.src_cap] = (
                        np.arange(cfg.src_cap) < s_len)
                    wr_enc_b[i, :len(pages)] = pages
                src_len[i] = s_len
                enc_pages_of[i] = pages
                pt_enc_b[i, :len(pages)] = pages
                pt_hist_b[i, :len(hist_pages[i])] = hist_pages[i]
        except Exception as e:  # noqa: BLE001 — the joiners' futures own it
            for pages in claimed_enc:
                self._enc_pool.release(pages)
            for pages in claimed_hist:
                self._hist_pool.release(pages)
            for i, r in enumerate(joins):
                if plan[i] is not None and plan[i][0] == 'hit':
                    self._prefix.unref(r.pkey)
                if not r.future.done():
                    r.future.set_exception(e)
            obs.event('decode.prefill.error',
                      requests=len(joins),
                      error='%s: %s' % (type(e).__name__, e))
            return

        free = [i for i, occ in enumerate(self._occupant) if occ is None]
        slot_idx = np.asarray(free[:n] + [0] * (b - n), np.int32)
        self._scatter_join(slot_idx, valid, enc_b, mask_b, limit_b,
                           pt_hist_b, pt_enc_b, wr_enc_b)
        # the pages now hold real content: make the miss entries
        # resident (refs = every user in this batch — the first writer
        # plus its dups); a failure above instead released the claims,
        # so a half-written prefix can never be hit later
        for j, i in enumerate(miss_idx):
            key = joins[i].pkey
            if key is not None:
                users = 1 + sum(1 for p in plan
                                if p[0] == 'dup' and p[1] == i)
                self._prefix.insert(key, miss_pages[j], int(len_m[j]),
                                    refs=users)
        now = time.monotonic()
        pages_free = (self._hist_pool.free_count
                      + self._enc_pool.free_count)
        _G_PAGES_FREE.set(pages_free)
        for i, req in enumerate(joins):
            slot = free[i]
            self._occupant[slot] = req
            self._slot_steps[slot] = 0
            hit = plan[i][0] != 'miss'
            self._slot_pages[slot] = {
                'hist': hist_pages[i], 'enc': enc_pages_of[i],
                'pkey': req.pkey}
            req.t_join = now
            if req.resume is not None:
                self._apply_resume(slot, req)
            with self._lock:
                self._n['joins'] += 1
                self._win['joins'] += 1
                if hit:
                    self._n['prefix_hits'] += 1
                    self._win['prefix_hits'] += 1
                else:
                    self._n['prefix_misses'] += 1
                    self._win['prefix_misses'] += 1
            _C_JOINS.inc()
            (_C_PREFIX_HITS if hit else _C_PREFIX_MISSES).inc()
            obs.event('decode.join', slot=slot, limit=req.limit,
                      src_len=int(src_len[i]), prefix_hit=hit,
                      pages_hist=len(hist_pages[i]),
                      pages_enc=len(enc_pages_of[i]),
                      pages_free=pages_free)
        occ_now = sum(o is not None for o in self._occupant)
        _G_SLOTS.set(occ_now)
        with self._lock:
            self._n['slots_high_water'] = max(
                self._n['slots_high_water'], occ_now)

    def _apply_resume(self, slot, req):
        """Overwrite one JUST-JOINED slot's rows with checkpointed
        state — the decode-stream failover resume. The join scatter
        already installed the encoder rows / page tables from the
        retained original feed; this restores the generation state on
        top: carry, previous beam ids, scores, finish flags, step
        counter, and the token history written back into the slot's
        (freshly claimed) history buffer or pages. Everything lands
        EAGERLY through StepHandle.set_state under the handle lock —
        the push_rows seam — so no new jitted signature exists and a
        resumed stream performs zero compiles (loop thread only)."""
        import jax.numpy as jnp
        cfg = self.config
        st = req.resume
        t = int(st['step'])
        K = cfg.beam_size
        with self._handle_lock:
            handle = self._acquire()
            state = handle.state

            def put_row(name, rows):
                cur = jnp.asarray(state['cbd_' + name])
                handle.set_state(
                    'cbd_' + name,
                    cur.at[slot].set(jnp.asarray(np.asarray(rows),
                                                 cur.dtype)))

            for name in ('h', 'c', 'prev_ids', 'acc', 'fin'):
                put_row(name, st[name])
            for name in _DRAFT_STATE:
                if name in st and 'cbd_' + name in state:
                    put_row(name, st[name])
            put_row('step', t)
            ids = np.asarray(st['ids'], np.int32).reshape(t, K)
            par = np.asarray(st['par'], np.int32).reshape(t, K)
            if cfg.paged:
                pages = self._slot_pages[slot]['hist']
                ps = cfg.page_size
                idx = jnp.asarray(np.asarray(pages, np.int32))
                for pool_name, content in (('hist_ids', ids),
                                           ('hist_par', par)):
                    rows = np.zeros((len(pages) * ps, K), np.int32)
                    rows[:t] = content
                    cur = jnp.asarray(state['cbd_' + pool_name])
                    handle.set_state(
                        'cbd_' + pool_name,
                        cur.at[idx].set(jnp.asarray(
                            rows.reshape(len(pages), ps, K), cur.dtype)))
            else:
                for hist_name, content in (('ids_hist', ids),
                                           ('par_hist', par)):
                    cur = jnp.asarray(state['cbd_' + hist_name])
                    handle.set_state(
                        'cbd_' + hist_name,
                        cur.at[slot, :t].set(jnp.asarray(content,
                                                         cur.dtype)))
        self._slot_steps[slot] = t
        with self._lock:
            self._n['resumed'] += 1
            self._win['resumed'] += 1
        _C_RESUMED.inc()
        obs.event('decode.resume', slot=slot, step=t, limit=req.limit)

    def _snapshot_slot(self, slot, t, ids_np, par_np, acc_np):
        """One slot's decode state at token `t`, exactly the dict
        `submit(resume=...)` restores: carry + beam state rows read
        from the handle (one host copy per array, cadence-limited) and
        the token history sliced from this dispatch's fetched arrays
        (loop thread only)."""
        snap = {'step': np.asarray(t, np.int32),
                'acc': np.asarray(acc_np[slot])}
        with self._handle_lock:
            state = self._acquire().state
            for name in ('h', 'c', 'prev_ids', 'fin'):
                snap[name] = np.asarray(state['cbd_' + name])[slot]
            for name in _DRAFT_STATE:
                if 'cbd_' + name in state:
                    snap[name] = np.asarray(state['cbd_' + name])[slot]
        K = self.config.beam_size
        if self.config.paged:
            sp = self._slot_pages[slot]
            snap['ids'] = np.asarray(
                ids_np[sp['hist']].reshape(-1, K)[:t])
            snap['par'] = np.asarray(
                par_np[sp['hist']].reshape(-1, K)[:t])
        else:
            snap['ids'] = np.asarray(ids_np[slot, :t])
            snap['par'] = np.asarray(par_np[slot, :t])
        return snap

    def _token_row(self, slot, s, ids_np):
        """The [beam_size] raw beam column generated at step `s` (1-
        based) of `slot`, from this dispatch's fetched history."""
        if self.config.paged:
            sp = self._slot_pages[slot]
            ps = self.config.page_size
            return np.asarray(ids_np[sp['hist'][(s - 1) // ps],
                                     (s - 1) % ps])
        return np.asarray(ids_np[slot, s - 1])

    def _abort_slot(self, slot):
        """Free a slot whose stream consumer went away (loop thread
        only): deactivate the row eagerly (the push_rows seam — no new
        signature), return slot + pages to the pool, fail the future
        typed StreamCancelled. The remaining in-flight sequences never
        notice — the step's where-select masking isolates rows."""
        import jax.numpy as jnp
        req = self._occupant[slot]
        taken = self._slot_steps[slot]
        with self._handle_lock:
            handle = self._acquire()
            cur = jnp.asarray(handle.state['cbd_active'])
            handle.set_state('cbd_active', cur.at[slot].set(False))
        self._occupant[slot] = None
        sp = self._slot_pages[slot]
        self._slot_pages[slot] = None
        if sp is not None:
            self._hist_pool.release(sp['hist'])
            if sp['pkey'] is not None:
                self._prefix.unref(sp['pkey'])
            else:
                self._enc_pool.release(sp['enc'])
            _G_PAGES_FREE.set(self._hist_pool.free_count
                              + self._enc_pool.free_count)
        with self._lock:
            self._n['cancelled'] += 1
            self._win['cancelled'] += 1
            self._n['releases'] += 1
            self._win['releases'] += 1
        _C_CANCELLED.inc()
        _C_RELEASES.inc()
        _G_SLOTS.set(sum(o is not None for o in self._occupant))
        obs.event('decode.cancel', slot=slot, steps=taken)
        if req is not None and not req.future.done():
            try:
                req.future.set_exception(StreamCancelled(
                    'decode slot %d cancelled after %d token(s): the '
                    'stream consumer went away' % (slot, taken)))
            except Exception:  # noqa: BLE001 — racing cancel() is fine
                pass

    def _release(self, slot, poisoned, ids_np, par_np, acc_np):
        """Resolve the slot's future from the step's fetched token
        history (host arrays — no device traffic here; in paged mode
        ids_np/par_np are the page POOLS and the slot's history is
        gathered through its page table) and free it — pages return to
        the pool, the prefix entry stays resident with its ref count
        dropped (loop thread only)."""
        from ..fluid.ops_impl.lod_beam import backtrace_beams
        req = self._occupant[slot]
        self._occupant[slot] = None
        taken = self._slot_steps[slot]
        sp = self._slot_pages[slot]
        self._slot_pages[slot] = None
        if sp is not None:
            self._hist_pool.release(sp['hist'])
            if sp['pkey'] is not None:
                self._prefix.unref(sp['pkey'])
            else:
                self._enc_pool.release(sp['enc'])
            pages_free = (self._hist_pool.free_count
                          + self._enc_pool.free_count)
            _G_PAGES_FREE.set(pages_free)
        with self._lock:
            self._n['releases'] += 1
            self._win['releases'] += 1
        _C_RELEASES.inc()
        _G_SLOTS.set(sum(o is not None for o in self._occupant))
        if req is None:
            return
        if poisoned:
            with self._lock:
                self._n['poisoned'] += 1
                self._win['poisoned'] += 1
            _C_POISONED.inc()
            obs.event('decode.poisoned', slot=slot, steps=taken)
            req.future.set_exception(DecodeSlotPoisoned(
                'slot %d produced non-finite beam scores after %d '
                'step(s); the request was aborted (other in-flight '
                'sequences are unaffected)' % (slot, taken)))
            return
        acc = acc_np[slot]
        if self.config.paged:
            # gather the slot's history through its page table: the
            # fetched pools are host arrays, so this is a pure numpy
            # slice like the dense path
            K = self.config.beam_size
            ids_seq = ids_np[sp['hist']].reshape(-1, K)[:taken]
            par_seq = par_np[sp['hist']].reshape(-1, K)[:taken]
        else:
            ids_seq = ids_np[slot, :taken]
            par_seq = par_np[slot, :taken]
        toks = backtrace_beams(ids_seq, par_seq)        # [K, taken]
        if taken < req.limit:
            # the fused lockstep scan keeps emitting end_id with
            # identity parents once every beam finished — pad instead
            # of stepping (lod_beam.backtrace_beams documents why this
            # is bit-exact)
            pad = np.full((self.config.beam_size, req.limit - taken),
                          self.config.end_id, toks.dtype)
            toks = np.concatenate([toks, pad], axis=1)
        with self._lock:
            self._n['completed'] += 1
            self._win['completed'] += 1
            self._n['tokens'] += taken
            self._win['tokens'] += taken
        _H_REQ_TOKENS.observe(taken)
        obs.event('decode.release', slot=slot, steps=taken,
                  finished=taken < req.limit)
        req.future.set_result((toks.astype(np.int64), acc))

    def _loop(self):
        """Decode-loop thread wrapper: a loop bug must fail every
        in-flight and queued future loudly instead of stranding them
        (the serving batcher's last-resort guard, same rationale)."""
        try:
            self._loop_body()
        except BaseException as e:  # noqa: BLE001 — resolved into futures
            obs.event('decode.loop.error',
                      error='%s: %s' % (type(e).__name__, e))
            with self._lock:
                self._shutdown = True
                self._drain = False
                doomed = [r for r in self._queue]
                self._queue.clear()
                _G_QDEPTH.set(0)
            doomed += [occ for occ in self._occupant if occ is not None]
            self._occupant = [None] * self.config.slots
            _G_SLOTS.set(0)
            for r in doomed:
                try:
                    # queued futures are PENDING and must be claimed;
                    # in-flight ones are already RUNNING and raise here
                    r.future.set_running_or_notify_cancel()
                except RuntimeError:
                    pass
                if not r.future.done():
                    r.future.set_exception(e)

    def _loop_body(self):
        cfg = self.config
        while True:
            shed, joins, doomed = [], [], []
            with self._lock:
                free = sum(o is None for o in self._occupant)
                if free:
                    joins = self._pop_live_locked(time.monotonic(), shed,
                                                  free)
                pending = len(self._queue)
                closing = self._shutdown
                if closing and not self._drain:
                    # queued requests fail with ServerClosed; active
                    # slots still finish. Futures resolve OUTSIDE the
                    # lock (a done-callback may re-enter the engine)
                    while self._queue:
                        doomed.append(self._queue.popleft())
                    doomed += joins     # claimed but not yet admitted
                    joins = []
                    pending = 0
                    _G_QDEPTH.set(0)
            for r in doomed:
                try:
                    r.future.set_running_or_notify_cancel()
                except RuntimeError:
                    pass                # already claimed as a join
                if not r.future.done():
                    r.future.set_exception(ServerClosed(
                        'decode engine shut down without draining'))
            self._fail_shed(shed)
            # consumer-gone streams first: their slots (and pages) free
            # up BEFORE this round's admit and step
            for slot, occ in enumerate(self._occupant):
                if occ is not None and occ.aborted:
                    self._abort_slot(slot)
            if joins:
                self._admit(joins)
            n_active = sum(o is not None for o in self._occupant)
            if n_active == 0:
                if closing and pending == 0:
                    break
                with self._lock:
                    if not self._queue and not self._shutdown:
                        self._not_empty.wait(_POLL_S)
                continue
            handle = self._acquire()
            spec_k = self.config.spec_k
            occupied = [slot for slot, occ in enumerate(self._occupant)
                        if occ is not None]
            t0 = time.perf_counter()
            with self._handle_lock:   # vs warmup's join/step probes
                fetched = handle.step()
                (active_v, ids_v, par_v, acc_v, step_v) = fetched[:5]
                # fetch conversion stays INSIDE the lock: the fetched
                # arrays alias donated state, and a concurrent warmup
                # dispatch would delete the buffers under us
                active_np = np.asarray(active_v)
                steps_np = np.asarray(step_v)
                accepted_np = np.asarray(fetched[5]) if spec_k else None
                finished = [slot for slot, occ
                            in enumerate(self._occupant)
                            if occ is not None and not active_np[slot]]
                streaming = [slot for slot, occ
                             in enumerate(self._occupant)
                             if occ is not None and not occ.aborted
                             and (occ.on_token is not None
                                  or (occ.checkpoint is not None
                                      and occ.ckpt_every))]
                if finished or streaming:
                    # one host sync for every release/emission this
                    # bundle
                    ids_np = np.asarray(ids_v)
                    par_np = np.asarray(par_v)
                    acc_np = np.asarray(acc_v)
            dt = time.perf_counter() - t0
            _H_STEP.observe(dt)
            _C_STEPS.inc()
            with self._lock:
                self._n['steps'] += 1
                self._win['steps'] += 1
                if spec_k:
                    # accept-rate bookkeeping: every active slot saw
                    # spec_k proposals this dispatch; Accepted counts
                    # the ones the target verified
                    acc_n = int(sum(accepted_np[s] for s in occupied))
                    self._n['spec_proposed'] += spec_k * len(occupied)
                    self._n['spec_accepted'] += acc_n
                    self._win['spec_proposed'] += spec_k * len(occupied)
                    self._win['spec_accepted'] += acc_n
            if spec_k:
                _C_SPEC_PROPOSED.inc(spec_k * len(occupied))
                _C_SPEC_ACCEPTED.inc(acc_n)
            now = time.monotonic()
            for slot, occ in enumerate(self._occupant):
                if occ is None:
                    continue
                prev_steps = self._slot_steps[slot]
                self._slot_steps[slot] = int(steps_np[slot])
                cur = self._slot_steps[slot]
                _C_TOKENS.inc(cur - prev_steps)
                if prev_steps == 0 and cur > 0 \
                        and occ.t_join is not None:
                    _H_TTFT.observe(now - occ.t_submit)
                # stream every token this dispatch produced, IN ORDER —
                # the emission path is append-only (the wire's writer
                # queue), so a slow consumer backpressures its socket,
                # never this loop; a RAISING callback means the
                # consumer is gone and the slot is reaped next round
                if occ.on_token is not None and cur > prev_steps \
                        and not occ.aborted:
                    for s in range(prev_steps + 1, cur + 1):
                        try:
                            occ.on_token(
                                s, self._token_row(slot, s, ids_np))
                        except Exception as e:  # noqa: BLE001
                            occ.aborted = True
                            obs.event(
                                'decode.stream.abort', slot=slot,
                                token=s, error='%s: %s'
                                % (type(e).__name__, e))
                            break
                # checkpoint at every cadence crossing (not for a slot
                # finishing this dispatch — its result resolves anyway);
                # a failing sink degrades failover, it must not kill
                # the stream
                if occ.checkpoint is not None and occ.ckpt_every \
                        and not occ.aborted and slot not in finished \
                        and (cur // occ.ckpt_every
                             > prev_steps // occ.ckpt_every):
                    try:
                        occ.checkpoint(self._snapshot_slot(
                            slot, cur, ids_np, par_np, acc_np))
                    except Exception as e:  # noqa: BLE001
                        obs.event('decode.ckpt.error', slot=slot,
                                  step=cur, error='%s: %s'
                                  % (type(e).__name__, e))
                if slot in finished:
                    self._release(slot,
                                  bool(np.isnan(acc_np[slot]).any()),
                                  ids_np, par_np, acc_np)

    # -- lifecycle / stats -------------------------------------------------

    def request_shutdown(self):
        """Signal-safe: flag only (the Trainer preemption pattern)."""
        self._shutdown = True

    def shutdown(self, drain=True, timeout=None):
        """Stop admission; with drain=True every queued request still
        decodes, else queued futures fail with ServerClosed (in-flight
        sequences always finish). No future is ever lost."""
        with self._lock:
            self._drain = drain
            self._shutdown = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
        self._thread.join(timeout)
        done = not self._thread.is_alive()
        extra = {}
        if self.config.paged:
            extra.update(pages_total=(self._hist_pool.usable
                                      + self._enc_pool.usable),
                         prefix_hits=self._n['prefix_hits'],
                         prefix_misses=self._n['prefix_misses'],
                         prefix_evictions=(self._prefix.evictions
                                           if self._prefix else 0))
        if self.config.spec_k and self._n['spec_proposed']:
            extra['spec_accept_rate'] = round(
                self._n['spec_accepted'] / self._n['spec_proposed'], 4)
        obs.event('decode.shutdown', drained=drain, clean=done,
                  completed=self._n['completed'],
                  tokens=self._n['tokens'], **extra)
        return done

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False

    @property
    def stats(self):
        """Cumulative decode statistics + instantaneous depth/occupancy
        (the windowed signal the router balances on is stats_window())."""
        with self._lock:
            depth = len(self._queue)
        out = {k: self._n.get(k, 0) for k in
               ('submitted', 'completed', 'rejected', 'shed', 'poisoned',
                'joins', 'releases', 'steps', 'tokens', 'cancelled',
                'resumed', 'slots_high_water', 'delta_pushes',
                'delta_rows')}
        out['queue_depth'] = depth
        out['queue_high_water'] = self._q_high_water
        out['slots'] = self.config.slots
        out['slots_occupied'] = sum(o is not None for o in self._occupant)
        out['warm'] = self._warm
        if self.config.paged:
            out['pages_total'] = (self._hist_pool.usable
                                  + self._enc_pool.usable)
            out['pages_free'] = (self._hist_pool.free_count
                                 + self._enc_pool.free_count)
            out['prefix_hits'] = self._n['prefix_hits']
            out['prefix_misses'] = self._n['prefix_misses']
            out['prefix_evictions'] = (self._prefix.evictions
                                       if self._prefix else 0)
        if self.config.spec_k:
            out['spec_proposed'] = self._n['spec_proposed']
            out['spec_accepted'] = self._n['spec_accepted']
            out['spec_accept_rate'] = (
                self._n['spec_accepted'] / self._n['spec_proposed']
                if self._n['spec_proposed'] else None)
        return out

    def stats_window(self):
        """Admission-pressure counters SINCE THE LAST CALL — the
        windowed signal (queue high-water mark, shed/reject counts) the
        router's least-loaded policy needs; instantaneous depth alone
        reads zero between bursts (docs/serving.md). Reading resets the
        window."""
        with self._lock:
            win = dict(self._win)
            self._win.clear()
            depth = len(self._queue)
        for k in ('queue_high_water', 'shed', 'rejected', 'submitted',
                  'completed', 'tokens'):
            win.setdefault(k, 0)
        win['queue_depth'] = depth
        win['inflight'] = sum(o is not None for o in self._occupant)
        # 'capacity' is the ADMISSION queue capacity on every engine
        # kind (a consumer normalizing pressure by it must get the same
        # units from ServingEngine and DecodeEngine replicas); the slot
        # pool is reported separately
        win['capacity'] = self.config.queue_capacity
        win['slots'] = self.config.slots
        # page-pool occupancy + prefix hit rate feed the router's
        # windowed pressure sample (0/0 on a dense engine: no page
        # pressure term)
        if self.config.paged:
            win['pages_free'] = (self._hist_pool.free_count
                                 + self._enc_pool.free_count)
            win['pages_total'] = (self._hist_pool.usable
                                  + self._enc_pool.usable)
            seen = win.get('prefix_hits', 0) + win.get('prefix_misses', 0)
            win['prefix_hit_rate'] = (win.get('prefix_hits', 0) / seen
                                      if seen else None)
        else:
            win['pages_free'] = win['pages_total'] = 0
            win['prefix_hit_rate'] = None
        if self.config.spec_k:
            win['spec_accept_rate'] = (
                win.get('spec_accepted', 0) / win['spec_proposed']
                if win.get('spec_proposed') else None)
        return win

    def cache_stats(self):
        """The underlying executor's compile/cache counters (the
        zero-steady-state-compiles assertion reads misses before/after
        traffic)."""
        return self._exe.cache_stats

    def state_bytes(self):
        """Total bytes of the per-request decode STATE buffers (slot
        state + history/encoder storage — dense buffers or page pools +
        page tables; model weights excluded). The capacity bench's
        equal-HBM comparison is drawn at this number
        (tools/serve_bench.py --workload decode-paged)."""
        total = 0
        for shape, dtype in self._state_spec.values():
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(dtype).itemsize
        return total
