"""Paged decode-state memory: the block-pool allocator + prefix cache.

PR 8's DecodeEngine reserves dense per-slot state for the WORST case —
`max_len` token-history rows and `src_cap` encoder rows per slot, every
slot, up front. That is the memory wall between serving hundreds and
serving millions of concurrent decode streams: a slot decoding an
8-token reply holds a 256-token history buffer hostage. The paged
engine (``DecodeConfig(page_size=..., pages=...)``) replaces the dense
buffers with fixed-size PAGES drawn from two device-resident pools
(vLLM's PagedAttention block table, rebuilt TPU-native):

  * token-history pages ``[pages, page_size, beam]`` (ids + parents),
    indexed per slot through an int32 page table
    ``pt_hist [slots, ceil(max_len/page_size)]``;
  * encoder-row pages ``[enc_pages, page_size, enc_dim]`` (+ the
    attention mask rows), through ``pt_enc``.

Shapes are static throughout: the pools and page tables never change
shape, page lookup is an in-graph gather, history writes are in-graph
scatters at ``(page_table[slot, step // page_size], step % page_size)``
with invalid rows redirected to the out-of-range page index (XLA
``mode='drop'``), the same where-select discipline as slot masking. The
HOST side — this module — only decides WHICH physical page backs which
logical page, between dispatches:

  * :class:`PagePool` is the free-list allocator. Admission claims
    ``ceil(limit/page_size)`` history pages and ``ceil(src_len/
    page_size)`` encoder pages; release returns them. A join that
    cannot get pages BLOCKS in the admission queue (typed
    ``decode.reject`` with ``reason=pages`` when the queue then
    overflows) — never a crash, never a stranded future.
  * :class:`PrefixCache` keeps encoder pages RESIDENT after release,
    keyed by a content hash of the request's encoder prefix
    (:func:`content_key`). A request whose prefix is resident joins
    WITHOUT re-prefilling: its page table points at the shared pages
    (refcounted while any slot uses them). Under pool pressure,
    unreferenced resident entries are evicted least-recently-used —
    eviction is just pages returning to the free list.

Encoder page 0 is reserved as the permanent ZERO page: slots whose
source is shorter than ``src_cap`` point their tail page-table entries
at it, so the in-graph gather always reads finite zeros under the
masked-out attention rows (a garbage row would turn ``0 * NaN`` into a
poisoned softmax).

See docs/serving.md ("Paged decode memory") for the page-table diagram
and the eviction/refcount semantics; tests/test_decode.py's ``paged``
drill family pins the invariants (no page referenced by two live slots,
freed pages recycled, paged-vs-dense bit-exactness).
"""
import collections
import hashlib

import numpy as np

from ..utils.lru import RefCountedLRU

__all__ = ['PagePool', 'PrefixCache', 'content_key', 'pages_for']


def pages_for(rows, page_size):
    """Physical pages needed to back `rows` logical rows."""
    return -(-int(rows) // int(page_size))


def content_key(feed):
    """Stable content hash of a request feed (the prefix-cache key):
    sorted keys, each value's dtype/shape/bytes hashed. Two requests
    with bit-identical encoder inputs share resident pages."""
    h = hashlib.sha1()
    for k in sorted(feed):
        v = feed[k]
        h.update(str(k).encode())
        a = np.asarray(v)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class PagePool(object):
    """Free-list allocator over a fixed pool of device-resident pages.

    `reserved` pages (the encoder pool's zero page) are never handed
    out. All methods are called from the decode-loop thread only; the
    integer counters (`free_count`, `total`) are read lock-free by the
    stats surface.
    """

    def __init__(self, total, reserved=0):
        self.total = int(total)
        self.reserved = int(reserved)
        if self.total <= self.reserved:
            raise ValueError('page pool needs > %d page(s), got %d'
                             % (self.reserved, self.total))
        self._free = collections.deque(range(self.reserved, self.total))
        self.free_count = len(self._free)
        self.allocated = 0      # cumulative
        self.freed = 0          # cumulative

    @property
    def usable(self):
        """Pages the pool can ever hand out (total minus reserved)."""
        return self.total - self.reserved

    def alloc(self, n, cache=None):
        """Claim `n` pages; evicts LRU unreferenced prefix-cache entries
        through `cache` when the free list is short. Returns the page
        list, or None when the pool (plus everything evictable) cannot
        cover the request — the caller blocks, it never crashes."""
        n = int(n)
        while cache is not None and len(self._free) < n:
            if not cache.evict_one():
                break
        if len(self._free) < n:
            return None
        out = [self._free.popleft() for _ in range(n)]
        self.free_count = len(self._free)
        self.allocated += n
        return out

    def release(self, pages):
        """Return pages to the free list (slot release / cache evict)."""
        for p in pages:
            self._free.append(p)
        self.free_count = len(self._free)
        self.freed += len(pages)

    def available(self, cache=None):
        """Pages obtainable RIGHT NOW: free plus evictable residents."""
        n = len(self._free)
        if cache is not None:
            n += cache.evictable_pages()
        return n


class _Resident(object):
    __slots__ = ('pages', 'src_len')

    def __init__(self, pages, src_len):
        self.pages = pages
        self.src_len = src_len


class PrefixCache(object):
    """Content-hash -> resident encoder pages, refcounted, LRU-evicted
    through the owning :class:`PagePool`.

    A hit bumps the entry's ref count and its LRU position and returns
    the resident pages + src_len — the joining request points its page
    table at them and SKIPS prefill entirely. `unref` on slot release
    leaves the entry resident (refs may drop to 0); only pool pressure
    evicts it, least-recently-used first. `on_evict(key, pages)` lets
    the engine emit the eviction event. The refcount+recency bookkeeping
    is `utils.lru.RefCountedLRU` — the same structure the streaming
    vocab table pins in-flight embedding rows with (docs/embedding.md
    "streaming ids").
    """

    def __init__(self, pool, on_evict=None):
        self._pool = pool
        self._lru = RefCountedLRU()                 # key -> _Resident
        self._on_evict = on_evict
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self):
        return len(self._lru)

    def peek(self, key):
        """True when `key` is resident — the admission gate's page-need
        probe; no counter or ref-count side effects."""
        return key in self._lru

    def pinnable_pages(self, key):
        """Pages a hit on `key` would take OUT of the evictable budget:
        the entry's page count while it is unreferenced (a referenced
        entry was never evictable, so pinning it costs nothing). The
        admission gate charges this before admitting a hit, else a
        batch-mate's claim would count the same pages as evictable."""
        e = self._lru.get(key)
        return len(e.pages) if e is not None and self._lru.refs(key) == 0 \
            else 0

    def lookup(self, key):
        """(pages, src_len) on a hit (ref count bumped), else None."""
        e = self._lru.get(key)
        if e is None:
            self.misses += 1
            return None
        self._lru.ref(key)
        self._lru.touch(key)
        self.hits += 1
        return list(e.pages), e.src_len

    def insert(self, key, pages, src_len, refs=1):
        """Make freshly-written pages resident under `key`. The pages
        stay OUT of the pool's free list until evicted."""
        if key in self._lru:            # racing duplicate miss: keep
            for _ in range(int(refs)):  # the first copy, free ours
                self._lru.ref(key)
            self._pool.release(pages)
            return
        self._lru.insert(key, _Resident(list(pages), int(src_len)),
                         refs=int(refs))

    def unref(self, key):
        """One slot stopped using the entry; it STAYS resident (that is
        the whole point — the next request with this prefix hits)."""
        self._lru.unref(key)

    def evictable_pages(self):
        return self._lru.evictable(weigh=lambda e: len(e.pages))

    def evict_one(self):
        """Evict the least-recently-used unreferenced entry, returning
        its pages to the pool. False when nothing is evictable."""
        victim = self._lru.evict_one()
        if victim is None:
            return False
        key, e = victim
        self._pool.release(e.pages)
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(key, e.pages)
        return True

    def stats(self):
        return {'entries': len(self._lru), 'hits': self.hits,
                'misses': self.misses, 'evictions': self.evictions,
                'resident_pages': sum(len(e.pages)
                                      for _, e in self._lru.items())}
