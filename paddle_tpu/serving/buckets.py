"""Shape-bucket policy for the serving engine.

The executor compiles one XLA module per feed signature
(docs/architecture.md), so a serving layer that forwarded raw request
batch sizes would recompile on every novel size — a multi-second stall
on the hot path. Instead every micro-batch is padded UP to one of a
small closed set of batch-dimension buckets (powers of two by default),
so the jit/export cache sees a bounded signature set and `warmup()` can
pre-compile all of it before traffic arrives.

Host-side and stdlib+numpy only: padding happens on the request rows
BEFORE the feed crosses to the device, so the compiled step itself is
byte-identical to an ordinary fixed-batch run.
"""
import numpy as np

__all__ = ['default_buckets', 'pick_bucket', 'pad_rows']


def default_buckets(max_batch_size):
    """Powers of two up to max_batch_size, always including
    max_batch_size itself: 32 -> (1, 2, 4, 8, 16, 32); 24 -> (1, 2, 4,
    8, 16, 24). The smallest buckets keep single-request latency from
    paying a full max-batch worth of padded FLOPs under light load."""
    m = int(max_batch_size)
    if m < 1:
        raise ValueError('max_batch_size must be >= 1, got %r'
                         % (max_batch_size,))
    out = []
    b = 1
    while b < m:
        out.append(b)
        b *= 2
    out.append(m)
    return tuple(out)


def pick_bucket(n, buckets):
    """Smallest bucket >= n rows. ValueError when n exceeds every bucket
    (admission control should have split or rejected the batch first)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError('batch of %d rows exceeds the largest bucket %d'
                     % (n, max(buckets)))


def pad_rows(arr, bucket):
    """Pad `arr` along axis 0 up to `bucket` rows by repeating the last
    row (repeated real rows keep every dtype valid — e.g. embedding ids
    stay in-vocabulary, where zero-fill could not promise that). The
    padded rows are sliced off the outputs before results reach any
    caller, so their values only need to be *computable*, never
    correct."""
    arr = np.asarray(arr)
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError('cannot pad %d rows down to bucket %d'
                         % (n, bucket))
    pad = np.repeat(arr[-1:], bucket - n, axis=0)
    return np.concatenate([arr, pad], axis=0)
