"""Multi-replica serving router: least-loaded dispatch + hot swap.

One process, N model replicas (`ServingEngine` over Predictor/compiled
runners, or `DecodeEngine` for the continuous-batching decode path —
anything with submit()/stats_window()/shutdown()). The router is the
single front door:

  * LEAST-LOADED dispatch: each replica's admission pressure is sampled
    from its `stats_window()` — the queue high-water mark and shed/
    reject counts since the last sample, not just instantaneous depth
    (a bursty replica reads depth 0 between bursts; the window does
    not) — plus a same-window count of requests this router already
    sent it, so consecutive submits spread instead of dogpiling the
    replica that looked idle a moment ago;
  * PER-MODEL ADMISSION QUOTAS: a cap on outstanding (queued +
    in-flight) work per model id; exceeding it raises the typed
    `ModelOverloaded` BEFORE any replica queue is touched, and a
    replica's own `ServerOverloaded` is caught and retried on the next
    least-loaded replica — overload propagates to the caller typed, as
    `ModelOverloaded(model_id)`, only when every replica refused;
  * VERSIONED HOT SWAP (`swap`): load the incoming artifact via
    `inference.load_compiled`, `warmup()` it off to the side (every
    bucket pre-compiled), then cut traffic over atomically — requests
    route to the new replicas from one submit to the next — while the
    OLD replicas drain in the background (their queued and in-flight
    work completes; no future is lost). Zero downtime: admission never
    closes during a swap.

Observability: router.routed / router.overloaded counters (labeled by
model), router.swap events, and a replicas gauge; `obs_report` folds
them into the serving section (docs/serving.md).
"""
import concurrent.futures
import threading
import time

import numpy as np

from .. import obs
from ..obs import trace
from .engine import (DeadlineExceeded, ServerClosed, ServerOverloaded,
                     ServingEngine)

__all__ = ['Router', 'ModelOverloaded', 'TokenStream', 'UnknownModel',
           'estimate_state_bytes']


def estimate_state_bytes(artifact, mesh_axes=None, batch=1):
    """Static per-device byte footprint of a model NEVER loaded — the
    bin-packing twin of `DecodeEngine.state_bytes()` (ROADMAP item 4:
    a fleet scheduler placing N models x M replicas onto hosts needs
    the footprint of artifacts it has not paid to load).

    `artifact` is a model dir (containing `__model__.json`), a path to
    the `__model__.json` itself, or an already-built `fluid.Program`.
    Only the program JSON is read — weights are never touched, no
    device is involved. Returns `residency + peak-liveness temp` bytes
    per device from `fluid.analysis.cost_report` (the A/B'd-against-
    `compiled_memory_stats()` estimate, docs/analysis.md#pass-6);
    `mesh_axes` prices a deployment mesh the artifact was not
    annotated with (the program_lint --mesh posture)."""
    import json as _json
    import os as _os
    from ..fluid import analysis
    from ..fluid.framework import Program
    if isinstance(artifact, Program):
        program = artifact
    else:
        path = artifact
        if _os.path.isdir(path):
            path = _os.path.join(path, '__model__.json')
        with open(path) as f:
            meta = _json.load(f)
        program = Program._from_dict(
            meta['program'] if 'program' in meta else meta)
    rep = analysis.cost_report(program, mesh_axes=mesh_axes, batch=batch)
    return rep.residency_per_device + rep.peak_temp_bytes


class UnknownModel(KeyError):
    """submit()/swap() named a model id the router does not serve."""


class ModelOverloaded(ServerOverloaded):
    """The model's admission quota is exhausted, or every replica
    refused the request (typed overload propagation: callers catch
    ServerOverloaded and get the model id via .model_id)."""

    def __init__(self, model_id, message):
        super(ModelOverloaded, self).__init__(message)
        self.model_id = model_id


_C_ROUTED = obs.counter('router.routed')
_C_OVERLOADED = obs.counter('router.overloaded')
_G_REPLICAS = obs.gauge('router.replicas')
_G_POD_SIZE = obs.gauge('router.pod_size')
_C_STREAM_TOKENS = obs.counter('serving.stream.tokens')
# END-TO-END time to first token: stream() call (before admission,
# before any queueing) to the first token REACHING the client callback
# — the user-visible TTFT, not the engine-internal one
_H_STREAM_TTFT = obs.histogram('serving.stream.ttft.seconds')
# SERVER-SIDE time to first token: engine dispatch on the serving host
# to its first on_token call, carried back in the first token frame —
# ttft minus this is the wire + queueing share of the budget
_H_STREAM_STTFT = obs.histogram('serving.stream.server_ttft.seconds')

# process-wide replica-id sequence: ids stay unique across routers so a
# registry (serving/pod.py) can address any replica it ever handed out
_RID_LOCK = threading.Lock()
_RID = [0]


def _next_rid():
    with _RID_LOCK:
        _RID[0] += 1
        return _RID[0]


def _end_request_span(h, fut):
    """Close a serving.request trace span from its future's done
    callback. end() merges: a stream's _on_done adds ttft fields to the
    same record in whichever order the callbacks fire."""
    try:
        err = fut.exception()
    except concurrent.futures.CancelledError as e:
        err = e
    except Exception:
        err = None
    h.end(error=type(err).__name__ if err is not None else None)


class _Replica(object):
    """One replica slot of a model entry. The registration seam
    (docs/serving.md#pod): every replica — in-process engine or a
    cross-host proxy — carries a router-unique `rid` plus optional
    `host`/`key` registry coordinates, so the single-process Router and
    the pod registry share ONE replica abstraction (`add_replica`
    returns the rid; `remove_replica` addresses it; `swap()` and
    `push_deltas` run the same engine protocol against either kind)."""

    __slots__ = ('engine', 'window', 'routed_since', 'sampled_at',
                 'rid', 'host', 'key')

    def __init__(self, engine, host=None, key=None):
        self.engine = engine
        self.window = {}
        self.routed_since = 0
        self.sampled_at = None    # None = never sampled: refresh first
        self.rid = _next_rid()
        self.host = host          # pod host id (None = this process)
        self.key = key            # registry key (None = unregistered)

    def score(self):
        """Admission-pressure score (lower = less loaded): live queue
        depth + in-flight work + the windowed high-water mark, with
        shed/reject counts weighted heavily (a replica that had to
        refuse work is the last place to send more), plus requests this
        router routed to it since the sample. A paged decode replica
        additionally reports page-pool occupancy (pages_free /
        pages_total in the window): a nearly-exhausted pool blocks the
        next join even when slots look free, so it scores as slot-worth
        of pressure as it fills."""
        w = self.window
        pages_total = w.get('pages_total', 0)
        page_pressure = 0.0
        if pages_total:
            occupancy = 1.0 - w.get('pages_free', 0) / pages_total
            page_pressure = occupancy * w.get('slots', 1)
        return (w.get('queue_depth', 0) + w.get('inflight', 0)
                + w.get('queue_high_water', 0)
                + 4 * (w.get('shed', 0) + w.get('rejected', 0))
                + self.routed_since + page_pressure)

    def outstanding(self):
        return (self.window.get('queue_depth', 0)
                + self.window.get('inflight', 0) + self.routed_since)


class _ModelEntry(object):
    __slots__ = ('replicas', 'quota', 'version', 'path')

    def __init__(self, replicas, quota):
        self.replicas = replicas
        self.quota = quota
        self.version = 1
        self.path = None


class TokenStream(object):
    """Client handle for one per-token streamed decode request
    (`Router.stream`): iterate it for `(t, ids)` pairs — t the
    1-based generated-token index, ids the [beam_size] token row at
    that step — in strictly increasing t order, then call `result()`
    for the final (tokens, scores) exactly as a plain submit() future
    would return them.

    Ordering is the stream's contract, and it is enforced HERE, at the
    consumer edge, not assumed of the producers: `_on_token` drops any
    token with t <= the last t delivered. That one rule absorbs every
    duplicate source in the system — an rpc resend replayed after a
    reconnect, and the failover replay (serving/pod.py re-plays tokens
    1..ckpt from the checkpoint before the survivor resumes at
    ckpt+1) — so the consumer sees each index exactly once, in order,
    across any number of host losses.

    The producer (decode loop or rpc reader thread) never blocks on
    the consumer: tokens buffer here without bound (a decode stream is
    at most max_new_tokens rows — bounded by construction). Dropping
    the stream mid-iteration and calling `cancel()` frees the decode
    slot and its pages at the next loop tick (typed StreamCancelled on
    the future)."""

    def __init__(self, model_id=None):
        self.model_id = model_id
        self._cv = threading.Condition()
        self._buf = []
        self._last_t = 0
        self._future = None
        self._cancel_cb = None
        self._t_open = time.monotonic()
        self._ttft_s = None
        self._server_ttft_s = None
        self._tspan = None        # trace.SpanHandle of the request span

    # -- producer edge (decode loop / rpc reader thread) -------------------

    def _on_token(self, t, ids, server_ttft_s=None):
        # server_ttft_s rides ONLY the first token frame from an rpc
        # worker (engine dispatch -> first token on the serving host);
        # legacy 2-arg producers simply leave it None
        t = int(t)
        with self._cv:
            if t <= self._last_t:
                return            # failover replay / reconnect resend dup
            self._last_t = t
            first = self._ttft_s is None
            if first:
                self._ttft_s = time.monotonic() - self._t_open
                if server_ttft_s is not None:
                    self._server_ttft_s = float(server_ttft_s)
            self._buf.append((t, None if ids is None
                              else np.asarray(ids).copy()))
            self._cv.notify_all()
        _C_STREAM_TOKENS.inc()
        if first:
            _H_STREAM_TTFT.observe(self._ttft_s)
            if self._server_ttft_s is not None:
                _H_STREAM_STTFT.observe(self._server_ttft_s)
            h = self._tspan
            if h is not None:
                h.mark('trace.first_token', ttft_s=round(self._ttft_s, 6),
                       server_ttft_s=self._server_ttft_s)
            obs.event('serving.stream.first_token',
                      model=str(self.model_id),
                      ttft_s=round(self._ttft_s, 6),
                      server_ttft_s=self._server_ttft_s)

    def _attach(self, future):
        self._future = future
        future.add_done_callback(self._on_done)

    def _on_done(self, fut):
        with self._cv:
            self._cv.notify_all()
        try:
            err = fut.exception()
        except concurrent.futures.CancelledError as e:
            err = e
        h = self._tspan
        if h is not None:
            h.end(tokens=self._last_t, ttft_s=self._ttft_s,
                  server_ttft_s=self._server_ttft_s)
        obs.event('serving.stream.close', model=str(self.model_id),
                  tokens=self._last_t, ttft_s=self._ttft_s,
                  server_ttft_s=self._server_ttft_s,
                  error=type(err).__name__ if err is not None else None)

    # -- consumer edge -----------------------------------------------------

    @property
    def ttft_s(self):
        """End-to-end time to first token (None until it arrives):
        stream() call at the client to the token reaching the client,
        wire latency included."""
        return self._ttft_s

    @property
    def server_ttft_s(self):
        """Server-side time to first token: engine dispatch on the
        serving host to its first on_token call. None until the first
        token arrives, and None for in-process replicas (there is no
        wire to separate out)."""
        return self._server_ttft_s

    @property
    def last_t(self):
        """Highest token index delivered so far."""
        return self._last_t

    def __iter__(self):
        """Yield (t, ids) in order until the request completes; a
        failed request raises its typed error from `result()` AFTER
        the tokens that did arrive have been yielded."""
        while True:
            with self._cv:
                while not self._buf and not (self._future is not None
                                             and self._future.done()):
                    self._cv.wait(0.05)
                if self._buf:
                    t, ids = self._buf.pop(0)
                else:
                    return
            yield t, ids

    def result(self, timeout=None):
        """Final (tokens, scores) — blocks like a submit() future."""
        return self._future.result(timeout)

    def done(self):
        return self._future is not None and self._future.done()

    def cancel(self):
        """Stop the stream: a queued request is dropped, a decoding one
        is aborted at the next loop tick (slot and pages freed, typed
        StreamCancelled on the future). Returns True if a cancel was
        delivered."""
        if self._future is not None and self._future.done():
            return False
        cb = self._cancel_cb
        if cb is not None:
            try:
                return bool(cb())
            except Exception:
                pass
        return self._future.cancel() if self._future is not None else False


class Router(object):
    """Least-loaded request router over named models (module docstring).

    window_s: minimum seconds between stats_window() samples per
    replica — the windowed counters reset on read, so the router is
    their single consumer and rations the reads."""

    def __init__(self, window_s=0.25):
        self._lock = threading.Lock()
        self._swap_lock = threading.Lock()   # one swap at a time
        self._models = {}
        self._window_s = float(window_s)
        self._drainers = []

    # -- registry ----------------------------------------------------------

    def add_model(self, model_id, replicas, quota=None):
        """Register `model_id` served by `replicas` (a list of engines).
        `quota` caps outstanding (queued + in-flight) requests across
        the model's replicas; None = no cap."""
        if not replicas:
            raise ValueError('a model needs at least one replica')
        with self._lock:
            if model_id in self._models:
                raise ValueError('model %r is already registered; use '
                                 'swap() or add_replica()' % (model_id,))
            self._models[model_id] = _ModelEntry(
                [_Replica(e) for e in replicas],
                int(quota) if quota is not None else None)
            self._update_gauge_locked()
        return self

    def add_replica(self, model_id, engine, host=None, key=None):
        """Register one more replica for `model_id`; returns its rid —
        the registration handle `remove_replica` addresses. `host`/`key`
        are registry coordinates for cross-host replicas
        (serving/pod.py); in-process replicas leave them None."""
        r = _Replica(engine, host=host, key=key)
        with self._lock:
            self._entry(model_id).replicas.append(r)
            self._update_gauge_locked()
        if key is not None:
            obs.event('serving.replica.register', model=str(model_id),
                      rid=r.rid, host=host, key=str(key))
        return r.rid

    def remove_replica(self, model_id, rid, drain=True, timeout=None,
                       reason='removed'):
        """Deregister the replica `rid` of `model_id`. With drain=True
        (default) its engine drains in a background thread exactly like
        a swapped-out generation (queued + in-flight work completes, no
        future is lost); drain=False detaches without touching the
        engine — the pod registry's host-loss path, where the engine is
        gone and its pending work is re-routed by the caller. Returns
        the detached engine, or None when the rid is not registered."""
        with self._lock:
            entry = self._entry(model_id)
            match = [r for r in entry.replicas if r.rid == rid]
            if not match:
                return None
            entry.replicas = [r for r in entry.replicas if r.rid != rid]
            self._update_gauge_locked()
        r = match[0]
        obs.event('serving.replica.drain', model=str(model_id),
                  rid=r.rid, host=r.host, drain=bool(drain),
                  reason=str(reason))
        if drain:
            self._drain_async(r.engine)
        return r.engine

    def replicas(self, model_id):
        """Registry view: one dict per replica of `model_id` — rid,
        host, key, and the last-sampled window (no reset)."""
        with self._lock:
            return [{'rid': r.rid, 'host': r.host, 'key': r.key,
                     'window': dict(r.window),
                     'routed_since': r.routed_since}
                    for r in self._entry(model_id).replicas]

    def models(self):
        with self._lock:
            return {m: {'replicas': len(e.replicas), 'quota': e.quota,
                        'version': e.version, 'path': e.path}
                    for m, e in self._models.items()}

    def _entry(self, model_id):
        try:
            return self._models[model_id]
        except KeyError:
            raise UnknownModel(
                'no model %r (serving %r)'
                % (model_id, sorted(self._models)))

    def _update_gauge_locked(self):
        _G_REPLICAS.set(sum(len(e.replicas)
                            for e in self._models.values()))
        # pod size = distinct hosts serving at least one replica (a
        # replica with host=None lives in this process)
        hosts = {('local' if r.host is None else r.host)
                 for e in self._models.values() for r in e.replicas}
        _G_POD_SIZE.set(len(hosts))

    def _drain_async(self, engine):
        """Drain an outgoing engine in the background — the swap()
        cutover machinery, shared by remove_replica and autoscaling:
        queued + in-flight work completes, no future is lost."""
        t = threading.Thread(
            target=lambda e=engine: e.shutdown(drain=True),
            name='router-drain', daemon=True)
        t.start()
        self._drainers.append(t)
        return t

    # -- dispatch ----------------------------------------------------------

    def _refresh_locked(self, entry, now):
        for r in entry.replicas:
            if r.sampled_at is None or now - r.sampled_at >= self._window_s:
                try:
                    r.window = r.engine.stats_window()
                except Exception:
                    r.window = {}
                r.routed_since = 0
                r.sampled_at = now

    def sample_windows(self, model_id):
        """Refresh (rationed by window_s) and return each replica's
        admission-pressure sample: [{'rid', 'host', 'window',
        'routed_since'}]. The autoscaler's signal (serving/pod.py) —
        same windows the dispatch path balances on, same single-consumer
        rationing."""
        now = time.monotonic()
        with self._lock:
            entry = self._entry(model_id)
            self._refresh_locked(entry, now)
            return [{'rid': r.rid, 'host': r.host,
                     'window': dict(r.window),
                     'routed_since': r.routed_since}
                    for r in entry.replicas]

    def submit(self, model_id, feed, **kwargs):
        """Route one request to the least-loaded replica of `model_id`;
        extra keyword arguments (deadline_ms, timeout, max_new_tokens,
        ...) pass through to the replica's submit(). Raises UnknownModel
        for an unregistered id and ModelOverloaded when the model quota
        is exhausted or every replica refused.

        Every request is TRACED (docs/observability.md#distributed-tracing): the
        admission point opens the `serving.request` span under the
        caller's active trace context (or a wire-carried `_trace`
        stash, or a fresh trace), and dispatch runs with that span
        current — pod proxies forward it over the wire so the worker's
        serve span joins the same trace."""
        # `_trace` is the wire-header stash a failover reroute carries
        # (serving/pod.py); popped here so engine signatures never see it
        wire_ctx = kwargs.pop('_trace', None)
        ctx = trace.current()
        if ctx is None:
            ctx = trace.from_headers(wire_ctx) or trace.new_trace()
        h = trace.begin('serving.request', ctx=ctx, node='router',
                        model=str(model_id))
        try:
            with trace.activate(h.ctx):
                fut = self._dispatch(model_id, feed, kwargs)
        except Exception as e:
            h.end(error=type(e).__name__)
            raise
        fut.add_done_callback(lambda f, _h=h: _end_request_span(_h, f))
        try:
            fut._trace_span = h   # stream() picks the handle up here
        except Exception:
            pass
        return fut

    def _dispatch(self, model_id, feed, kwargs):
        last_err = None
        # one admission budget for the WHOLE dispatch: trying N blocking
        # replicas in sequence must not multiply the caller's timeout
        t_end = None
        if kwargs.get('timeout') is not None:
            t_end = time.monotonic() + kwargs['timeout']
        for attempt in (0, 1):
            now = time.monotonic()
            with self._lock:
                entry = self._entry(model_id)
                self._refresh_locked(entry, now)
                if entry.quota is not None:
                    outstanding = sum(r.outstanding()
                                      for r in entry.replicas)
                    if outstanding >= entry.quota:
                        _C_OVERLOADED.inc()
                        obs.event('router.overloaded',
                                  model=str(model_id),
                                  outstanding=outstanding,
                                  quota=entry.quota)
                        raise ModelOverloaded(
                            model_id,
                            'model %r admission quota exhausted (%d '
                            'outstanding >= quota %d)'
                            % (model_id, outstanding, entry.quota))
                order = sorted(entry.replicas, key=lambda r: r.score())
            all_closed = True
            fut = picked = bumped = None
            try:
                for r in order:
                    if t_end is not None:
                        kwargs['timeout'] = max(0.0,
                                                t_end - time.monotonic())
                    # bump ONLY the replica being attempted (bumping the
                    # whole order up front inflated outstanding() by N-1
                    # phantoms for the duration of a blocking submit,
                    # spuriously tripping the quota for other callers);
                    # a successful dispatch keeps its bump
                    with self._lock:
                        r.routed_since += 1
                    bumped = r
                    try:
                        fut = r.engine.submit(feed, **kwargs)
                    except (ServerOverloaded, ServerClosed) as e:
                        with self._lock:
                            # max(): a concurrent _refresh_locked may
                            # have reset the counter since the bump
                            r.routed_since = max(0, r.routed_since - 1)
                        bumped = None
                        last_err = e
                        all_closed = (all_closed
                                      and isinstance(e, ServerClosed))
                        continue
                    picked = r
                    break
            finally:
                # an UNEXPECTED submit error (bad feed ValueError, ...)
                # must not leave a phantom routed_since eating the quota
                if bumped is not None and picked is None:
                    with self._lock:
                        bumped.routed_since = max(
                            0, bumped.routed_since - 1)
            if picked is not None:
                _C_ROUTED.inc()
                return fut
            if attempt == 0 and last_err is not None and all_closed:
                # every replica in our snapshot raised ServerClosed: we
                # raced a swap() cutover and held the drained OLD
                # generation — re-resolve entry.replicas once and retry
                # against the warmed-up incoming generation (zero
                # downtime for callers)
                continue
            break
        if last_err is not None and all_closed:
            # still all closed after the re-resolve: the model is DOWN,
            # not overloaded — don't hand retry-forever clients a
            # transient-overload signal for a dead backend
            obs.event('router.closed', model=str(model_id),
                      replicas=len(order))
            raise ServerClosed(
                'every replica of model %r is shut down (last: %s)'
                % (model_id, last_err))
        _C_OVERLOADED.inc()
        obs.event('router.overloaded', model=str(model_id),
                  replicas=len(order))
        raise ModelOverloaded(
            model_id, 'every replica of model %r refused the request '
            '(last: %s)' % (model_id, last_err))

    def predict(self, model_id, feed, timeout=None, **kwargs):
        """Synchronous convenience: one wall-clock budget covering both
        admission and the result wait, with the engines' typed-timeout
        contract (DeadlineExceeded, never a raw TimeoutError; a still-
        queued request is cancelled so it stops holding quota)."""
        t0 = time.monotonic()
        fut = self.submit(model_id, feed, timeout=timeout, **kwargs)
        remaining = None if timeout is None else \
            max(0.0, timeout - (time.monotonic() - t0))
        try:
            return fut.result(remaining)
        except concurrent.futures.TimeoutError:
            if fut.done():
                return fut.result()
            if fut.cancel():
                raise DeadlineExceeded(
                    'no result within the %.3fs predict() timeout; the '
                    'queued request was cancelled' % timeout)
            raise DeadlineExceeded(
                'no result within the %.3fs predict() timeout; the '
                'request is already executing — it completes but the '
                'result is discarded' % timeout)

    def stream(self, model_id, feed, **kwargs):
        """Per-token streamed decode through the least-loaded replica:
        returns a `TokenStream` yielding (t, ids) as tokens are
        generated, with `result()` for the final (tokens, scores).
        Rides the ordinary submit() path — the stream's on_token
        callback travels in kwargs, so any replica that accepts
        on_token (in-process DecodeEngine, or an rpc pod proxy) can
        serve it, and admission/quota/overload-retry semantics are
        identical to submit(). TTFT is measured end-to-end: stream()
        call to first token at the client (`server_ttft_s` carries the
        worker-side dispatch-to-first-token share when the replica is
        an rpc proxy)."""
        s = TokenStream(model_id=model_id)
        kwargs['on_token'] = s._on_token
        ctx = trace.current()
        if ctx is None:
            ctx = trace.from_headers(kwargs.pop('_trace', None)) \
                or trace.new_trace()
        with trace.activate(ctx):
            fut = self.submit(model_id, feed, **kwargs)
            obs.event('serving.stream.open', model=str(model_id))
        s._tspan = getattr(fut, '_trace_span', None)
        s._cancel_cb = lambda: self._cancel_request(model_id, s._future)
        s._attach(fut)
        return s

    def _cancel_request(self, model_id, fut):
        """Best-effort cancel of an accepted request: ask each replica
        engine that knows the future (only its owner returns True)."""
        if fut is None:
            return False
        with self._lock:
            engines = [r.engine for r in self._entry(model_id).replicas]
        for e in engines:
            cancel = getattr(e, 'cancel', None)
            if cancel is None:
                continue
            try:
                if cancel(fut):
                    return True
            except Exception:
                pass
        return fut.cancel()

    # -- hot swap ----------------------------------------------------------

    def swap(self, model_id, path, config=None, warmup_feed=None,
             builder=None):
        """Zero-downtime versioned artifact hot-swap: build one NEW
        replica per current replica from the `load_compiled` artifact at
        `path`, run `warmup()` on each incoming replica (every bucket
        pre-compiled — the cutover never serves a cold compile), then
        atomically cut traffic over and drain the old replicas in the
        background (queued + in-flight work completes; no future is
        lost). Admission stays open throughout. Returns the new version
        number.

        `builder(path)` overrides replica construction (e.g. to swap a
        DecodeEngine); default: ServingEngine(load_compiled(path),
        config or the old replica's config). Swaps serialize on one
        router-wide lock (a second swap waits, it is not lost), and a
        replica added concurrently via add_replica survives the
        cutover."""
        with self._swap_lock:
            return self._swap_locked(model_id, path, config, warmup_feed,
                                     builder)

    def _swap_locked(self, model_id, path, config, warmup_feed, builder):
        with self._lock:
            entry = self._entry(model_id)
            n, old_replicas = len(entry.replicas), list(entry.replicas)
        if builder is None:
            from .. import inference

            def builder(p):
                cfg = config
                if cfg is None:
                    old_eng = old_replicas[0].engine
                    cfg = getattr(old_eng, 'config', None)
                return ServingEngine(inference.load_compiled(p), cfg)

        incoming = []
        try:
            for _ in range(n):
                eng = builder(path)
                with obs.span('router.swap.warmup', model=str(model_id)):
                    eng.warmup(warmup_feed)
                incoming.append(eng)
        except Exception:
            for eng in incoming:       # half-built generation: tear down
                try:
                    eng.shutdown(drain=False, timeout=5)
                except Exception:
                    pass
            raise
        with self._lock:
            # replace ONLY the snapshotted generation; replicas added
            # concurrently via add_replica keep serving (they are
            # neither drained below nor silently dropped)
            old_set = set(old_replicas)
            kept = [r for r in entry.replicas if r not in old_set]
            entry.replicas = [_Replica(e) for e in incoming] + kept
            entry.version += 1
            entry.path = path
            version = entry.version
            self._update_gauge_locked()
        obs.event('router.swap', model=str(model_id), version=version,
                  replicas=n, path=str(path))
        for old in old_replicas:
            self._drain_async(old.engine)
        return version

    # -- row-delta push ----------------------------------------------------

    def push_deltas(self, model_id, deltas):
        """Push trained row deltas into EVERY live replica of
        `model_id` — the streaming train->serve freshness path
        (docs/serving.md#delta-push): `deltas` maps a persistable name
        to `(row_ids, rows)`, applied through each engine's
        `push_rows` (per-table atomic reference swap on ServingEngine,
        StepHandle.set_state under the handle lock on DecodeEngine).

        Generation discipline: the push holds the router's SWAP lock,
        so it can never interleave a `swap()` cutover — a delta lands
        entirely on one generation, and a swap waits for an in-flight
        push (and vice versa). A push that raced just AHEAD of a swap
        is superseded by the incoming artifact; the publisher's next
        cadence re-freshens the new generation (its pending set only
        clears on success, docs/embedding.md "streaming ids"). A
        replica that is independently shut down raises ServerClosed: if
        every replica is closed the typed error propagates (the model
        is down, there is nothing to freshen); a partial failure
        freshens the survivors and reports the failures in the
        router.delta_push event. Typed errors (DeltaUnsupported,
        ValueError on malformed deltas) propagate immediately — they
        mean the push itself is wrong, not the replica.

        Returns the number of replicas updated."""
        from .engine import DeltaUnsupported
        with self._swap_lock:
            with self._lock:
                entry = self._entry(model_id)
                replicas = list(entry.replicas)
                version = entry.version
            pushed, rows, closed = 0, 0, []
            for r in replicas:
                try:
                    rows = r.engine.push_rows(deltas)
                    pushed += 1
                except ServerClosed as e:
                    closed.append(e)
                except (DeltaUnsupported, ValueError, KeyError):
                    raise
            obs.event('router.delta_push', model=str(model_id),
                      version=version, replicas=pushed,
                      closed=len(closed), rows=rows,
                      tables=sorted(str(k) for k in deltas))
            if closed and pushed == 0:
                raise ServerClosed(
                    'every replica of model %r is shut down — no live '
                    'generation to push deltas into (last: %s)'
                    % (model_id, closed[-1]))
            return pushed

    # -- lifecycle ---------------------------------------------------------

    def stats(self):
        """Per-model routing view: replica count, version, and each
        replica's last-sampled window (no reset — the dispatch path owns
        the sampling)."""
        with self._lock:
            return {m: {'version': e.version, 'quota': e.quota,
                        'replicas': [dict(r.window,
                                          routed_since=r.routed_since)
                                     for r in e.replicas]}
                    for m, e in self._models.items()}

    def shutdown(self, drain=True, timeout=None):
        """Shut every replica down (draining by default) and join the
        background drainers from past swaps."""
        with self._lock:
            engines = [r.engine for e in self._models.values()
                       for r in e.replicas]
            drainers = list(self._drainers)
        ok = True
        for e in engines:
            ok = bool(e.shutdown(drain=drain, timeout=timeout)) and ok
        for t in drainers:
            t.join(timeout)
            ok = ok and not t.is_alive()
        return ok

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
        return False
