"""Structured JSONL run log: one event per record, append-only, flushed.

Each record is a single JSON object on its own line:

    {"ts": <monotonic seconds, float>,
     "kind": "meta" | "event" | "span",
     "name": "<dotted event name>",
     "span": <enclosing span id or null>,
     "parent": <parent span id, span records only>,
     "dur_s": <wall seconds, span records only>,
     "fields": {...}}

`ts` is time.monotonic() so intervals are immune to wall-clock jumps; the
run_start meta record carries the wall-clock anchor ("time" ISO-8601) for
humans correlating against external logs. Writes are flushed per record so
a crash (or a driver timeout) loses at most the in-flight line, and
tools/obs_report.py can read a log while the run is still going.

stdlib-only (see metrics.py for why).
"""
import json
import os
import threading
import time

__all__ = ['RunLog', 'new_run_path']

_SEQ_LOCK = threading.Lock()
_SEQ = [0]


def _json_default(o):
    """Fields may carry numpy scalars / device-array leftovers; fall back
    to .item() (exact for numpy scalars) then str(). Never raises — a
    telemetry write must not take down the training step it observes."""
    item = getattr(o, 'item', None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(o)


def new_run_path(obs_dir):
    """A collision-free run-log path under obs_dir:
    run-<utc stamp>-p<pid>-<seq>.jsonl (seq disambiguates multiple runs
    started within one second of one process)."""
    with _SEQ_LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    stamp = time.strftime('%Y%m%dT%H%M%S', time.gmtime())
    return os.path.join(obs_dir,
                        'run-%s-p%d-%d.jsonl' % (stamp, os.getpid(), seq))


class RunLog(object):
    """Append-only JSONL writer. The file (and its directory) is created
    on construction; callers create RunLogs lazily so an enabled-but-idle
    process leaves no output file behind."""

    def __init__(self, path):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        is_new = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, 'a')
        if is_new:
            # several processes may share one pinned run file
            # (PADDLE_TPU_OBS_RUN_FILE); only the creator stamps run_start
            self.write({'ts': time.monotonic(), 'kind': 'meta',
                        'name': 'run_start', 'span': None,
                        'fields': {'pid': os.getpid(),
                                   'time': time.strftime(
                                       '%Y-%m-%dT%H:%M:%S%z')}})

    def write(self, record):
        try:
            line = json.dumps(record, separators=(',', ':'),
                              default=_json_default)
        except Exception:
            return  # telemetry must never crash the instrumented code
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + '\n')
                self._f.flush()
            except Exception as e:
                # disk full / fd revoked mid-run: the instrumented step
                # must survive. Disable THIS run log and say so once.
                try:
                    self._f.close()
                except Exception:
                    pass
                self._f = None
                import warnings
                warnings.warn(
                    'obs run log %r became unwritable (%s: %s); telemetry '
                    'file output disabled for the rest of this run'
                    % (self.path, type(e).__name__, e), RuntimeWarning)

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
