"""Structured JSONL run log: one event per record, append-only, flushed.

Each record is a single JSON object on its own line:

    {"ts": <monotonic seconds, float>,
     "kind": "meta" | "event" | "span",
     "name": "<dotted event name>",
     "span": <enclosing span id or null>,
     "parent": <parent span id, span records only>,
     "dur_s": <wall seconds, span records only>,
     "fields": {...}}

`ts` is time.monotonic() so intervals are immune to wall-clock jumps; the
run_start meta record carries the wall-clock anchor ("time" ISO-8601) for
humans correlating against external logs. Writes are flushed per record so
a crash (or a driver timeout) loses at most the in-flight line, and
tools/obs_report.py can read a log while the run is still going.

stdlib-only (see metrics.py for why).
"""
import json
import os
import threading
import time

from .metrics import REGISTRY

__all__ = ['RunLog', 'new_run_path']

_SEQ_LOCK = threading.Lock()
_SEQ = [0]


def _json_default(o):
    """Fields may carry numpy scalars / device-array leftovers; fall back
    to .item() (exact for numpy scalars) then str(). Never raises — a
    telemetry write must not take down the training step it observes."""
    item = getattr(o, 'item', None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(o)


def new_run_path(obs_dir):
    """A collision-free run-log path under obs_dir:
    run-<utc stamp>-p<pid>-<seq>.jsonl (seq disambiguates multiple runs
    started within one second of one process)."""
    with _SEQ_LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    stamp = time.strftime('%Y%m%dT%H%M%S', time.gmtime())
    return os.path.join(obs_dir,
                        'run-%s-p%d-%d.jsonl' % (stamp, os.getpid(), seq))


class RunLog(object):
    """Append-only JSONL writer. The file (and its directory) is created
    on construction; callers create RunLogs lazily so an enabled-but-idle
    process leaves no output file behind.

    RING-BUFFER MODE (`max_events=`): a week-long train_stream or decode
    soak must not grow the log without bound, so once the file exceeds
    max_events records (plus ~10% slack so compaction amortizes) it is
    rewritten in place — atomic tmp + os.replace, reopened for append —
    keeping the run_start meta line and the newest max_events records.
    Eviction is NEVER silent: every dropped record counts on the
    `obs.runlog.dropped` counter and the rewritten file leads with a
    `runlog.dropped` meta record carrying the cumulative total. Memory
    stays O(1) — the ring lives in the file, not in RAM. Do not use on a
    file shared by several live writers (the pinned
    PADDLE_TPU_OBS_RUN_FILE case): compaction would drop their racing
    appends — paddle_tpu.obs leaves pinned files unbounded by default."""

    def __init__(self, path, max_events=None):
        self.path = path
        self.max_events = int(max_events) if max_events else None
        self.dropped = 0
        self._lines = 0
        self._compact_failed = False
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        is_new = not os.path.exists(path) or os.path.getsize(path) == 0
        if not is_new and self.max_events:
            try:
                with open(path, 'rb') as f:
                    self._lines = sum(1 for _ in f)
            except Exception:
                self._lines = 0
        self._f = open(path, 'a')
        if is_new:
            # several processes may share one pinned run file
            # (PADDLE_TPU_OBS_RUN_FILE); only the creator stamps run_start
            self.write({'ts': time.monotonic(), 'kind': 'meta',
                        'name': 'run_start', 'span': None,
                        'fields': {'pid': os.getpid(),
                                   'time': time.strftime(
                                       '%Y-%m-%dT%H:%M:%S%z')}})

    def _compact_locked(self):
        """Rewrite the file keeping run_start + the newest max_events
        records; stale dropped-notices are superseded, not stacked."""
        with open(self.path, 'r') as f:
            lines = f.read().splitlines()
        head = [ln for ln in lines[:2] if '"name":"run_start"' in ln][:1]
        body = [ln for ln in lines if ln not in head
                and '"name":"runlog.dropped"' not in ln]
        keep = body[-self.max_events:]
        newly = len(body) - len(keep)
        if newly <= 0:
            self._lines = len(lines)
            return
        self.dropped += newly
        REGISTRY.counter('obs.runlog.dropped').inc(newly)
        notice = json.dumps(
            {'ts': time.monotonic(), 'kind': 'meta',
             'name': 'runlog.dropped', 'span': None,
             'fields': {'dropped': self.dropped,
                        'max_events': self.max_events}},
            separators=(',', ':'))
        tmp = '%s.tmp%d' % (self.path, os.getpid())
        out = head + [notice] + keep
        with open(tmp, 'w') as f:
            f.write('\n'.join(out) + '\n')
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, 'a')
        self._lines = len(out)

    def write(self, record):
        try:
            line = json.dumps(record, separators=(',', ':'),
                              default=_json_default)
        except Exception:
            return  # telemetry must never crash the instrumented code
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + '\n')
                self._f.flush()
                self._lines += 1
                if (self.max_events and not self._compact_failed
                        and self._lines > self.max_events
                        + max(32, self.max_events // 10)):
                    try:
                        self._compact_locked()
                    except Exception:
                        # unwritable tmp / torn file: stop trying, the
                        # log just stays append-only from here
                        self._compact_failed = True
            except Exception as e:
                # disk full / fd revoked mid-run: the instrumented step
                # must survive. Disable THIS run log and say so once.
                try:
                    self._f.close()
                except Exception:
                    pass
                self._f = None
                import warnings
                warnings.warn(
                    'obs run log %r became unwritable (%s: %s); telemetry '
                    'file output disabled for the rest of this run'
                    % (self.path, type(e).__name__, e), RuntimeWarning)

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
