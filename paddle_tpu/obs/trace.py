"""Request-scoped distributed tracing: one trace_id across the pod.

A request that enters at `Router.submit`/`Router.stream` gets a
TraceContext — `trace_id` naming the request, `span_id` naming the
span the next hop should parent under — propagated two ways:

  * IN-PROCESS via a contextvar: while a context is active
    (`activate()`), every `obs.span`/`obs.event` picks it up with no
    signature change — span records gain `trace`/`tspan`/`tparent`
    keys in the run log AND a completed-span record in the trace
    buffer below;
  * ACROSS PROCESSES via `headers()` -> `from_headers()`: a plain
    JSON-safe dict carried in the rpc frame header and in the
    file-mailbox request meta (serving/pod.py), in heal control
    commands, and in delta-push frames, so the worker re-enters the
    SAME trace before serving (docs/observability.md#distributed-tracing).

Span records are buffered per process (bounded; overflow counted on
`obs.trace.dropped`, never silent) and spilled by each host into
`<pod_dir>/traces/spans.p<pid>.json` with the registry's
atomic-replace posture. Open spans spill with `t1: null` — a host
that dies mid-request leaves its serve span open in its last spill,
which is exactly how `TraceCollector` flags ORPHANS instead of
dropping them. Timestamps are wall-clock (`time.time()`), not
monotonic: cross-host stitching needs one clock domain (same-box
pods are exact; real multi-host pods are as good as their NTP).

stdlib-only (see metrics.py for why); the obs package loads
standalone without jax.
"""
import collections
import contextvars
import itertools
import json
import os
import threading
import time
import uuid

from .metrics import REGISTRY
from .runlog import _json_default

__all__ = ['TraceContext', 'SpanHandle', 'TraceCollector', 'new_trace',
           'current', 'node', 'activate', 'headers', 'from_headers',
           'begin', 'mark', 'spill', 'set_capacity', 'TRACE_DIR']

# subdirectory of a pod dir that collects per-host span spills
TRACE_DIR = 'traces'
_DEFAULT_CAPACITY = 4096

_ctx = contextvars.ContextVar('paddle_tpu_trace', default=None)
_node = contextvars.ContextVar('paddle_tpu_trace_node', default=None)

_lock = threading.Lock()
_buf = collections.deque()       # completed span/mark records
_open = {}                       # span_id -> still-open span record
_capacity = [_DEFAULT_CAPACITY]
_span_seq = itertools.count(1)
_spill_warned = [False]


class TraceContext(object):
    """(trace_id, span_id) — span_id is the span a child created under
    this context parents to (None at the root)."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id, span_id=None):
        self.trace_id = str(trace_id)
        self.span_id = span_id

    def __repr__(self):
        return 'TraceContext(%r, %r)' % (self.trace_id, self.span_id)


def new_trace():
    """A fresh root context. Nothing becomes current — pair with
    `activate()` (or pass ctx= to `begin()`/`mark()`)."""
    return TraceContext(uuid.uuid4().hex[:16], None)


def current():
    """The active TraceContext of this thread/task, or None."""
    return _ctx.get()


def node():
    """The active node label (host attribution in spilled spans)."""
    return _node.get()


class _Activation(object):
    """Context manager installing `ctx` (and optionally a node label)
    into the contextvars; a None ctx is a clean no-op so call sites
    need no 'was a trace carried?' branches."""

    __slots__ = ('ctx', '_node', '_tok', '_ntok')

    def __init__(self, ctx, node_label):
        self.ctx = ctx
        self._node = node_label
        self._tok = None
        self._ntok = None

    def __enter__(self):
        if self.ctx is not None:
            self._tok = _ctx.set(self.ctx)
            if self._node is not None:
                self._ntok = _node.set(str(self._node))
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        for var, tok in ((_node, self._ntok), (_ctx, self._tok)):
            if tok is not None:
                try:
                    var.reset(tok)
                except Exception:
                    pass
        self._tok = self._ntok = None
        return False


def activate(ctx, node=None):
    """`with activate(ctx, node='h0'): ...` — make `ctx` current so
    obs.span/obs.event (and nested submits) pick it up. ctx=None is a
    no-op."""
    return _Activation(ctx, node)


def headers(ctx=None):
    """The wire form of `ctx` (default: the current context): a
    JSON-safe dict for an rpc frame header / request meta / control
    command. None when there is no trace to carry."""
    if ctx is None:
        ctx = _ctx.get()
    if ctx is None:
        return None
    return {'trace_id': ctx.trace_id, 'parent_id': ctx.span_id}


def from_headers(d):
    """Re-enter a wire-carried context; None for absent/malformed
    headers (an untraced request stays untraced, never crashes)."""
    if not isinstance(d, dict) or not d.get('trace_id'):
        return None
    return TraceContext(d['trace_id'], d.get('parent_id'))


def _new_span_id():
    # unique across processes within a trace: pid-qualified sequence
    return '%x.%x' % (os.getpid(), next(_span_seq))


def _append_locked(rec):
    _buf.append(rec)
    cap = _capacity[0]
    dropped = 0
    while len(_buf) > cap:
        _buf.popleft()
        dropped += 1
    if dropped:
        REGISTRY.counter('obs.trace.dropped').inc(dropped)


def set_capacity(n):
    """Bound of the in-memory span buffer (oldest evicted, counted on
    obs.trace.dropped)."""
    with _lock:
        _capacity[0] = max(1, int(n))
        while len(_buf) > _capacity[0]:
            _buf.popleft()
            REGISTRY.counter('obs.trace.dropped').inc()


def _clean_fields(fields):
    return dict((k, v) for k, v in fields.items() if v is not None)


class SpanHandle(object):
    """An explicitly-ended span for request lifetimes that cross
    threads (a worker opens the serve span on the rpc reader thread
    and ends it from the engine's done callback). `end()` is
    idempotent on t1 but always merges fields, so a late
    'tokens=' merge and an early 'error=' merge both land."""

    __slots__ = ('_rec', 'ctx')

    def __init__(self, rec):
        self._rec = rec
        self.ctx = TraceContext(rec['trace'], rec['span'])

    def mark(self, name, **fields):
        """A zero-duration milestone under this span (thread-safe:
        carries its own context, no contextvar needed)."""
        return _mark_rec(name, self.ctx, self._rec.get('node'), fields)

    def end(self, **fields):
        with _lock:
            self._rec['fields'].update(_clean_fields(fields))
            if self._rec['t1'] is None:
                self._rec['t1'] = time.time()
                _open.pop(self._rec['span'], None)
                _append_locked(self._rec)
        return self


def begin(name, ctx=None, node=None, **fields):
    """Open a request-lifetime span under `ctx` (default: the current
    context). Returns a SpanHandle, or None when there is no trace to
    attach to — callers guard with `if h is not None`. The span sits
    in the OPEN set until `end()`, so a spill that happens first
    records it with t1=None (the orphan flag's raw material)."""
    if ctx is None:
        ctx = _ctx.get()
    if ctx is None:
        return None
    rec = {'trace': ctx.trace_id, 'span': _new_span_id(),
           'parent': ctx.span_id, 'name': str(name),
           'node': str(node) if node is not None else _node.get(),
           'pid': os.getpid(), 't0': time.time(), 't1': None,
           'fields': _clean_fields(fields)}
    with _lock:
        _open[rec['span']] = rec
    return SpanHandle(rec)


def _mark_rec(name, ctx, node_label, fields):
    t = time.time()
    rec = {'trace': ctx.trace_id, 'span': _new_span_id(),
           'parent': ctx.span_id, 'name': str(name), 'node': node_label,
           'pid': os.getpid(), 't0': t, 't1': t, 'mark': True,
           'fields': _clean_fields(fields)}
    with _lock:
        _append_locked(rec)
    return rec


def mark(name, ctx=None, **fields):
    """Record a point-in-time milestone (e.g. trace.first_token) under
    `ctx` or the current context; None when no trace is active."""
    if ctx is None:
        ctx = _ctx.get()
    if ctx is None:
        return None
    return _mark_rec(name, ctx, _node.get(), fields)


# -- obs.Span integration (called by paddle_tpu.obs.span) -------------------

def _span_begin(name):
    """Hook for obs.Span.__enter__: when a trace is active, open a
    trace span for it and make it the current parent. Returns the
    (record, contextvar token) pair __exit__ hands back, or None."""
    ctx = _ctx.get()
    if ctx is None:
        return None
    rec = {'trace': ctx.trace_id, 'span': _new_span_id(),
           'parent': ctx.span_id, 'name': str(name),
           'node': _node.get(), 'pid': os.getpid(),
           't0': time.time(), 't1': None, 'fields': {}}
    with _lock:
        _open[rec['span']] = rec
    token = _ctx.set(TraceContext(rec['trace'], rec['span']))
    return (rec, token)


def _span_end(info, fields=None, error=None):
    """Hook for obs.Span.__exit__: complete the trace span and restore
    the parent context. Returns the completed record (its trace ids
    are merged into the run-log span record)."""
    rec, token = info
    try:
        _ctx.reset(token)
    except Exception:
        pass
    if fields:
        rec['fields'].update(_clean_fields(fields))
    if error is not None:
        rec['fields']['error'] = error
    with _lock:
        if rec['t1'] is None:
            rec['t1'] = time.time()
            _open.pop(rec['span'], None)
            _append_locked(rec)
    return rec


def _ids():
    """Additive run-log keys for the current context (obs.event): the
    `span` key stays the process-local integer id — trace identity
    rides separate keys so report.validate_record still holds."""
    ctx = _ctx.get()
    if ctx is None:
        return None
    out = {'trace': ctx.trace_id}
    if ctx.span_id is not None:
        out['tspan'] = ctx.span_id
    return out


def _reset():
    """Tests: drop buffered spans and restore the default capacity."""
    with _lock:
        _buf.clear()
        _open.clear()
        _capacity[0] = _DEFAULT_CAPACITY
    _spill_warned[0] = False


# -- spill + stitch ----------------------------------------------------------

def spill(dir_path):
    """Atomic-replace dump of this process's buffer — completed spans
    AND still-open ones (t1=None) — into
    `dir_path/spans.p<pid>.json`. Idempotent per cadence: the file is
    REPLACED, so it always holds the current bounded window. Returns
    the path, or None when there is nothing to spill or the write
    failed (warned once; telemetry never crashes the serving path)."""
    with _lock:
        recs = [dict(r, fields=dict(r['fields'])) for r in _buf]
        recs += [dict(r, fields=dict(r['fields']))
                 for r in sorted(_open.values(), key=lambda r: r['t0'])]
    if not recs:
        return None
    path = os.path.join(str(dir_path), 'spans.p%d.json' % os.getpid())
    tmp = '%s.tmp%d' % (path, os.getpid())
    try:
        os.makedirs(str(dir_path), exist_ok=True)
        with open(tmp, 'w') as f:
            json.dump({'pid': os.getpid(), 'spans': recs}, f,
                      default=_json_default)
        os.replace(tmp, path)
    except Exception as e:
        if not _spill_warned[0]:
            _spill_warned[0] = True
            import warnings
            warnings.warn('trace spill into %r failed (%s: %s); tracing '
                          'continues in memory only'
                          % (str(dir_path), type(e).__name__, e),
                          RuntimeWarning)
        return None
    return path


# canonical request milestones, in causal order; the timeline's stages
# are the deltas between whichever of them the trace actually has
_MILESTONES = ('admit', 'serve', 'dispatch', 'first_token', 'done')


class TraceCollector(object):
    """Stitch per-host spills from a `<pod_dir>/traces/` directory into
    end-to-end request timelines. Spans whose t1 is still None belong
    to hosts that died (or have not spilled their completion yet):
    they are FLAGGED as orphans in the timeline, never dropped."""

    def __init__(self, traces_dir):
        self.traces_dir = str(traces_dir)

    def load(self):
        """Every span record across every host spill (skips torn or
        half-written files; the writers atomic-replace, so a retry
        sees a whole file)."""
        spans = []
        try:
            names = sorted(os.listdir(self.traces_dir))
        except OSError:
            return spans
        for fname in names:
            if not (fname.startswith('spans.')
                    and fname.endswith('.json')):
                continue
            try:
                with open(os.path.join(self.traces_dir, fname)) as f:
                    d = json.load(f)
            except (OSError, ValueError):
                continue
            for rec in (d.get('spans') or []) \
                    if isinstance(d, dict) else []:
                if isinstance(rec, dict) and rec.get('trace'):
                    spans.append(rec)
        return spans

    def traces(self):
        """{trace_id: [span records sorted by t0]}."""
        out = {}
        for rec in self.load():
            out.setdefault(str(rec['trace']), []).append(rec)
        for recs in out.values():
            recs.sort(key=lambda r: (r.get('t0') or 0.0,
                                     str(r.get('span'))))
        return out

    def timeline(self, trace_id=None):
        """One stitched end-to-end timeline: ordered spans across every
        host, the request milestones that were recorded (router admit
        -> worker serve -> engine dispatch -> first token -> done),
        per-stage durations between consecutive milestones, and the
        orphan spans. trace_id may be omitted when the directory holds
        exactly one trace."""
        traces = self.traces()
        if trace_id is None:
            if len(traces) != 1:
                raise ValueError(
                    '%d traces under %r — pass trace_id (have: %s)'
                    % (len(traces), self.traces_dir,
                       sorted(traces)[:8]))
            trace_id = next(iter(traces))
        spans = traces.get(str(trace_id))
        if not spans:
            raise KeyError('no spans for trace %r under %r'
                           % (trace_id, self.traces_dir))
        orphans = [s for s in spans
                   if s.get('t1') is None and not s.get('mark')]

        def first_t0(name):
            ts = [s['t0'] for s in spans
                  if s.get('name') == name and s.get('t0') is not None]
            return min(ts) if ts else None

        def last_t1(name):
            ts = [s['t1'] for s in spans
                  if s.get('name') == name and s.get('t1') is not None]
            return max(ts) if ts else None

        points = {'admit': first_t0('serving.request'),
                  'serve': first_t0('serving.pod.serve'),
                  'dispatch': first_t0('trace.dispatch'),
                  'first_token': first_t0('trace.first_token'),
                  'done': last_t1('serving.request')}
        milestones = [{'name': n, 't': points[n]} for n in _MILESTONES
                      if points[n] is not None]
        stages = []
        for a, b in zip(milestones, milestones[1:]):
            stages.append({'stage': '%s->%s' % (a['name'], b['name']),
                           'seconds': b['t'] - a['t']})
        nodes = sorted({str(s.get('node') or 'p%s' % s.get('pid'))
                        for s in spans})
        start = milestones[0]['t'] if milestones else spans[0].get('t0')
        return {'trace': str(trace_id), 'start': start, 'spans': spans,
                'orphans': orphans, 'milestones': milestones,
                'stages': stages, 'nodes': nodes}
