"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Prometheus-shaped, host-side, stdlib-only. Instruments are identified by
(name, labels) — labels are how one logical series fans out per call site
(`retry.attempts{site=...}`) or per executor (`executor.cache.hits{exe=...}`)
while reports aggregate across them by name. Everything is thread-safe and
cheap enough to stay armed unconditionally: an increment is one lock plus
one add, so the registry keeps counting even when the run-log side of the
observability layer (PADDLE_TPU_OBS_DIR) is disabled. File IO and trace
forwarding — the costly parts — live in paddle_tpu.obs and are gated there.

This module must not import jax (or anything outside the stdlib): the
disabled-mode contract of the obs layer is "no file, no jax import", and
tests load the package standalone to prove it.
"""
import bisect
import re
import threading

__all__ = ['Counter', 'Gauge', 'Histogram', 'Registry', 'REGISTRY',
           'counter', 'gauge', 'histogram', 'render_prom',
           'DEFAULT_TIME_BUCKETS']

# Exponential seconds buckets spanning sub-ms op dispatch to multi-minute
# compiles. The +Inf overflow bucket is implicit (the last counts slot).
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class _Instrument(object):
    kind = None

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = dict(labels)
        # reentrant: an instrument update may allocate, allocation may
        # trigger GC, and a destructor (executor.FetchHandle.__del__)
        # may re-enter instrument code on the SAME thread — a plain Lock
        # would self-deadlock there
        self._lock = threading.RLock()

    def _base_snapshot(self):
        return {'kind': self.kind, 'name': self.name,
                'labels': dict(self.labels)}


class Counter(_Instrument):
    """Monotonically increasing count (or sum — inc() takes a float)."""
    kind = 'counter'

    def __init__(self, name, labels=()):
        super(Counter, self).__init__(name, labels)
        self._value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError('counters only go up; got inc(%r)' % (n,))
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        s = self._base_snapshot()
        s['value'] = self._value
        return s


class Gauge(_Instrument):
    """Last-written value (None until first set)."""
    kind = 'gauge'

    def __init__(self, name, labels=()):
        super(Gauge, self).__init__(name, labels)
        self._value = None

    def set(self, v):
        with self._lock:
            self._value = float(v)

    @property
    def value(self):
        return self._value

    def snapshot(self):
        s = self._base_snapshot()
        s['value'] = self._value
        return s


class Histogram(_Instrument):
    """Fixed-bucket histogram with percentile estimation.

    Buckets are upper bounds; observations above the last bound land in an
    implicit +Inf bucket. Exact min/max/sum/count are tracked alongside, so
    percentile() can clamp its bucket interpolation to values that were
    actually seen (a p95 above the observed max would be a lie)."""
    kind = 'histogram'

    def __init__(self, name, labels=(), buckets=DEFAULT_TIME_BUCKETS):
        super(Histogram, self).__init__(name, labels)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError('histogram needs at least one bucket bound')
        self._counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    def percentile(self, p):
        """Estimated p-th percentile (0..100) by linear interpolation
        inside the bucket holding the target rank; None when empty."""
        if not 0 <= p <= 100:
            raise ValueError('percentile must be in [0, 100], got %r' % p)
        with self._lock:
            if self.count == 0:
                return None
            target = max(1, int(round(p / 100.0 * self.count)))
            cum = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    cum += c
                    continue
                if cum + c >= target:
                    lo = self.bounds[i - 1] if i > 0 else \
                        (self.min if self.min is not None else 0.0)
                    hi = self.bounds[i] if i < len(self.bounds) else self.max
                    frac = (target - cum) / float(c)
                    est = lo + (hi - lo) * frac
                    if self.min is not None:
                        est = max(est, self.min)
                    if self.max is not None:
                        est = min(est, self.max)
                    return est
                cum += c
            return self.max

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def percentile_window(self, before, after, p):
        """Estimated p-th percentile over ONLY the observations recorded
        between two snapshot()s — a windowed view of this cumulative
        histogram (serve_bench isolates one benchmark rep's TTFT this
        way). None when the window is empty. Per-window min/max are not
        tracked, so a rank landing in the overflow (+Inf) bucket reports
        the last finite bound — a conservative floor — rather than
        interpolating toward a lifetime max that may belong to an
        observation OUTSIDE the window."""
        if not 0 <= p <= 100:
            raise ValueError('percentile must be in [0, 100], got %r' % p)
        counts = [a[1] - b[1] for b, a in zip(before['buckets'],
                                              after['buckets'])]
        n = sum(counts)
        if n <= 0:
            return None
        target = max(1, int(round(p / 100.0 * n)))
        cum = 0
        for i, c in enumerate(counts):
            if c > 0 and cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if i >= len(self.bounds):      # overflow bucket
                    return self.bounds[-1]
                hi = self.bounds[i]
                return lo + (hi - lo) * ((target - cum) / float(c))
            cum += c
        return self.bounds[-1]

    def snapshot(self):
        with self._lock:
            s = self._base_snapshot()
            s.update(count=self.count, sum=self.sum, min=self.min,
                     max=self.max,
                     buckets=[[b, c] for b, c in
                              zip(self.bounds + ('+Inf',), self._counts)])
        s['p50'] = self.percentile(50)
        s['p95'] = self.percentile(95)
        return s


class Registry(object):
    """Name+labels -> instrument store. Getter calls are idempotent: the
    same (name, labels) always returns the SAME instrument, so call sites
    can re-resolve per call instead of caching handles."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments = {}

    def _get(self, cls, name, labels, **kw):
        key = (name, tuple(sorted((str(k), str(v))
                                  for k, v in labels.items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, key[1], **kw)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    'metric %r is already registered as a %s, not a %s'
                    % (name, inst.kind, cls.kind))
        return inst

    def counter(self, name, **labels):
        return self._get(Counter, name, labels)

    def gauge(self, name, **labels):
        return self._get(Gauge, name, labels)

    def histogram(self, name, buckets=None, **labels):
        kw = {} if buckets is None else {'buckets': buckets}
        return self._get(Histogram, name, labels, **kw)

    def total(self, name):
        """Sum of counter values across every label set of `name`
        (0.0 when the name was never registered)."""
        with self._lock:
            insts = [i for (n, _), i in self._instruments.items()
                     if n == name and isinstance(i, Counter)]
        return sum(i.value for i in insts)

    def find(self, name):
        """Every instrument registered under `name`, any labels, in
        stable label order ([] when never registered) — how the SLO
        evaluator reaches a histogram's percentile() (snapshots only
        pre-compute p50/p95)."""
        with self._lock:
            return [inst for (n, _), inst in sorted(self._instruments
                                                    .items()) if n == name]

    def snapshot(self):
        """Point-in-time list of every instrument's snapshot dict, sorted
        by (name, labels) for stable diffing."""
        with self._lock:
            insts = sorted(self._instruments.items())
        return [inst.snapshot() for _, inst in insts]

    def reset(self):
        """Drop every instrument (tests only — live handles held by call
        sites keep counting into detached objects)."""
        with self._lock:
            self._instruments.clear()


REGISTRY = Registry()


def counter(name, **labels):
    return REGISTRY.counter(name, **labels)


def gauge(name, **labels):
    return REGISTRY.gauge(name, **labels)


def histogram(name, buckets=None, **labels):
    return REGISTRY.histogram(name, buckets=buckets, **labels)


def _prom_name(name):
    n = re.sub(r'[^a-zA-Z0-9_:]', '_', str(name))
    if not n or not re.match(r'[a-zA-Z_:]', n[0]):
        n = '_' + n
    return n


def _prom_esc(v):
    return str(v).replace('\\', '\\\\').replace('"', '\\"') \
                 .replace('\n', '\\n')


def _prom_labels(labels, extra=()):
    items = sorted(labels.items()) + list(extra)
    if not items:
        return ''
    return '{%s}' % ','.join('%s="%s"' % (_prom_name(k), _prom_esc(v))
                             for k, v in items)


def _prom_num(v):
    return repr(float(v))


def render_prom(registry=None):
    """The whole registry in Prometheus text exposition format (v0.0.4):
    counters as `<name>_total`, gauges as-is (unset gauges skipped),
    histograms as CUMULATIVE `_bucket{le=...}` series plus `_sum` and
    `_count` — our per-bucket counts are accumulated here because that
    is what the wire format specifies. Dotted metric names are
    sanitized (`.` -> `_`); one HELP/TYPE header per metric name. The
    pod serves this on the rpc `metrics` frame and drops it into
    `metrics.h<host>.prom` files on the stats cadence, so a scrape
    needs no run-log parsing."""
    reg = registry if registry is not None else REGISTRY
    lines = []
    headed = set()

    def _head(mname, mtype):
        if mname not in headed:
            headed.add(mname)
            lines.append('# HELP %s paddle_tpu metric' % mname)
            lines.append('# TYPE %s %s' % (mname, mtype))

    for s in reg.snapshot():
        base = _prom_name(s['name'])
        kind = s['kind']
        if kind == 'counter':
            mname = base + '_total'
            _head(mname, 'counter')
            lines.append('%s%s %s' % (mname, _prom_labels(s['labels']),
                                      _prom_num(s['value'])))
        elif kind == 'gauge':
            if s['value'] is None:
                continue
            _head(base, 'gauge')
            lines.append('%s%s %s' % (base, _prom_labels(s['labels']),
                                      _prom_num(s['value'])))
        elif kind == 'histogram':
            _head(base, 'histogram')
            cum = 0
            for bound, c in s['buckets']:
                cum += c
                le = '+Inf' if bound == '+Inf' else _prom_num(bound)
                lines.append('%s_bucket%s %d'
                             % (base, _prom_labels(s['labels'],
                                                   [('le', le)]), cum))
            lines.append('%s_sum%s %s' % (base, _prom_labels(s['labels']),
                                          _prom_num(s['sum'])))
            lines.append('%s_count%s %d' % (base,
                                            _prom_labels(s['labels']),
                                            s['count']))
    return '\n'.join(lines) + '\n' if lines else ''
