"""Declarative SLO budgets graded against live telemetry.

A budget file is plain JSON mapping budget keys to numeric ceilings
(docs/observability.md#slo-budgets has the schema):

    {"_comment": "ignored",
     "budgets": {"ttft_p50_s": 2.5, "ttft_p99_s": 6.0, "dropped": 0}}

`SloBudget.evaluate()` measures each key from the metrics registry
(histogram percentiles, gauge values, counter totals) and, when a
run-log event list is supplied, from events too (recovery_s comes from
heal drills, which only events record). Every key resolves to exactly
one of three TYPED outcomes:

  * ok        — measured <= limit
  * violation — SloViolation(budget, limit, measured); result.passed
                is False and renderers name the violated percentile
  * missing   — SloMissing(budget, limit): the budget was declared but
                nothing measured it (e.g. recovery_s in a run with no
                heal drill). Reported loudly, but NOT a failure —
                otherwise every budget file would need a per-workload
                variant; pass `strict_missing=True` to make it one.

Consumed by tools/serve_bench.py --slo (exit nonzero on violation),
tools/slo_report.py, and tools/bench_sentinel.sh (hard gate).
stdlib-only (see metrics.py for why).
"""
import json

from .metrics import REGISTRY, Counter, Gauge, Histogram
from .report import percentile_exact

__all__ = ['SloBudget', 'SloResult', 'SloViolation', 'SloMissing',
           'measure', 'KNOWN_BUDGETS']

# budget key -> how it is measured (the docs table mirrors this)
KNOWN_BUDGETS = {
    'ttft_p50_s': 'p50 of serving.stream.ttft.seconds (client-side)',
    'ttft_p99_s': 'p99 of serving.stream.ttft.seconds (client-side)',
    'server_ttft_p99_s':
        'p99 of serving.stream.server_ttft.seconds (dispatch->token 1)',
    'per_token_p99_s': 'p99 of decode.step.seconds',
    'recovery_s': 'slowest heal: serving.replica.reshard heal_s / '
                  'bench.metric *recovery_s|*resume_s events',
    'freshness_lag_s': 'streaming.freshness_lag_s gauge',
    'dropped': 'serving/decode shed+rejected totals plus stream '
               'failovers that never resumed',
}


def _hist_pct(reg, name, p):
    for inst in reg.find(name):
        if isinstance(inst, Histogram) and inst.count:
            return inst.percentile(p)
    return None


def _gauge(reg, name):
    for inst in reg.find(name):
        if isinstance(inst, Gauge) and inst.value is not None:
            return inst.value
    return None


def _counters_seen(reg, names):
    return any(isinstance(i, Counter)
               for n in names for i in reg.find(n))


def measure(registry=None, events=None):
    """Best-effort {budget_key: measured value}. Keys nothing measured
    are ABSENT (evaluate() types them as missing). Events, when given,
    fill what the registry cannot (recovery_s) and back-fill TTFT
    percentiles for offline runs whose registry is empty."""
    reg = registry if registry is not None else REGISTRY
    out = {}
    for key, name, p in (('ttft_p50_s', 'serving.stream.ttft.seconds', 50),
                         ('ttft_p99_s', 'serving.stream.ttft.seconds', 99),
                         ('server_ttft_p99_s',
                          'serving.stream.server_ttft.seconds', 99),
                         ('per_token_p99_s', 'decode.step.seconds', 99)):
        v = _hist_pct(reg, name, p)
        if v is not None:
            out[key] = v
    v = _gauge(reg, 'streaming.freshness_lag_s')
    if v is not None:
        out['freshness_lag_s'] = v
    # dropped is only meaningful once some admission/stream path ran;
    # an empty registry must report it MISSING, not a vacuous 0
    drop_names = ('serving.shed', 'serving.rejected', 'decode.shed',
                  'decode.rejected', 'serving.stream.failovers',
                  'serving.stream.resumes', 'serving.stream.tokens',
                  'serving.requests', 'decode.requests')
    if _counters_seen(reg, drop_names):
        unresumed = max(0.0, reg.total('serving.stream.failovers')
                        - reg.total('serving.stream.resumes'))
        out['dropped'] = (reg.total('serving.shed')
                          + reg.total('serving.rejected')
                          + reg.total('decode.shed')
                          + reg.total('decode.rejected') + unresumed)
    if events:
        recov = []
        ttft, sttft = [], []
        for ev in events:
            name = ev.get('name')
            fields = ev.get('fields') or {}
            if name == 'serving.replica.reshard' and \
                    fields.get('heal_s') is not None:
                recov.append(float(fields['heal_s']))
            elif name == 'bench.metric' and \
                    (str(fields.get('metric', '')).endswith('recovery_s')
                     or str(fields.get('metric', '')).endswith('resume_s')) \
                    and fields.get('value') is not None:
                # a SIGKILL drill's stream-resume time IS its recovery
                recov.append(float(fields['value']))
            elif name == 'serving.stream.first_token':
                if fields.get('ttft_s') is not None:
                    ttft.append(float(fields['ttft_s']))
                if fields.get('server_ttft_s') is not None:
                    sttft.append(float(fields['server_ttft_s']))
        if recov:
            out['recovery_s'] = max(recov)
        if ttft:
            out.setdefault('ttft_p50_s', percentile_exact(ttft, 50))
            out.setdefault('ttft_p99_s', percentile_exact(ttft, 99))
        if sttft:
            out.setdefault('server_ttft_p99_s',
                           percentile_exact(sttft, 99))
    return out


class SloViolation(object):
    """measured > limit for one budget key."""
    __slots__ = ('budget', 'limit', 'measured')

    def __init__(self, budget, limit, measured):
        self.budget = str(budget)
        self.limit = float(limit)
        self.measured = float(measured)

    def describe(self):
        return ('SLO VIOLATION: %s measured %.6g exceeds budget %.6g'
                % (self.budget, self.measured, self.limit))

    def __repr__(self):
        return 'SloViolation(%s: %.6g > %.6g)' % (
            self.budget, self.measured, self.limit)


class SloMissing(object):
    """A declared budget nothing in this run measured."""
    __slots__ = ('budget', 'limit')

    def __init__(self, budget, limit):
        self.budget = str(budget)
        self.limit = float(limit)

    def describe(self):
        return ('SLO MISSING: %s has budget %.6g but no measurement '
                'in this run' % (self.budget, self.limit))

    def __repr__(self):
        return 'SloMissing(%s: budget %.6g)' % (self.budget, self.limit)


class SloResult(object):
    """Outcome of one evaluation: `ok` [(budget, limit, measured)],
    `violations` [SloViolation], `missing` [SloMissing]."""

    def __init__(self, ok, violations, missing, strict_missing=False):
        self.ok = list(ok)
        self.violations = list(violations)
        self.missing = list(missing)
        self.strict_missing = bool(strict_missing)

    @property
    def passed(self):
        if self.violations:
            return False
        if self.strict_missing and self.missing:
            return False
        return True

    def lines(self):
        out = []
        for budget, limit, measured in self.ok:
            out.append('SLO OK: %s = %.6g (budget %.6g)'
                       % (budget, measured, limit))
        for v in self.violations:
            out.append(v.describe())
        for m in self.missing:
            out.append(m.describe())
        out.append('SLO: %d ok, %d violated, %d missing -> %s'
                   % (len(self.ok), len(self.violations),
                      len(self.missing),
                      'PASS' if self.passed else 'FAIL'))
        return out

    def __repr__(self):
        return 'SloResult(passed=%s, ok=%d, violations=%r, missing=%r)' \
            % (self.passed, len(self.ok), self.violations, self.missing)


class SloBudget(object):
    """The declared ceilings. Unknown keys are legal (they evaluate as
    missing — a budget written for a future metric fails loudly as
    MISSING instead of silently passing); '_'-prefixed keys are
    comments."""

    def __init__(self, budgets):
        self.budgets = {}
        for k, v in dict(budgets).items():
            if str(k).startswith('_'):
                continue
            self.budgets[str(k)] = float(v)

    @classmethod
    def from_dict(cls, d):
        if not isinstance(d, dict):
            raise ValueError('SLO budget must be a JSON object, got %s'
                             % type(d).__name__)
        inner = d.get('budgets')
        return cls(inner if isinstance(inner, dict) else d)

    @classmethod
    def from_file(cls, path):
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def evaluate(self, registry=None, events=None, measured=None,
                 strict_missing=False):
        """Grade every declared budget. `measured` (a dict) overrides /
        extends what measure() finds — tests and bench reps inject
        windowed percentiles this way."""
        vals = measure(registry=registry, events=events)
        if measured:
            vals.update(measured)
        ok, violations, missing = [], [], []
        for budget in sorted(self.budgets):
            limit = self.budgets[budget]
            m = vals.get(budget)
            if m is None:
                missing.append(SloMissing(budget, limit))
            elif float(m) > limit:
                violations.append(SloViolation(budget, limit, m))
            else:
                ok.append((budget, limit, float(m)))
        return SloResult(ok, violations, missing,
                         strict_missing=strict_missing)
