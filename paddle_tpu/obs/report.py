"""Run-log analysis: load/validate JSONL event records and print the
diagnosis summary tools/obs_report.py serves (step-time percentiles,
compile breakdown, cache hit ratio, anomaly skips, retries, reader
degradation, checkpoint timeline) — a run is explainable without
TensorBoard or a Perfetto trace.

stdlib-only (see metrics.py for why).
"""
import json
import os

__all__ = ['validate_record', 'load_events', 'collect_events',
           'summarize', 'latest_run', 'percentile_exact']

_KINDS = ('meta', 'event', 'span')


def validate_record(obj):
    """None when `obj` is a well-formed event record, else a short reason
    string (the --check contract)."""
    if not isinstance(obj, dict):
        return 'record is not a JSON object'
    ts = obj.get('ts')
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        return 'missing/non-numeric "ts"'
    name = obj.get('name')
    if not isinstance(name, str) or not name:
        return 'missing/empty "name"'
    kind = obj.get('kind')
    if kind not in _KINDS:
        return 'bad "kind" %r (want one of %s)' % (kind, '/'.join(_KINDS))
    if 'fields' in obj and not isinstance(obj['fields'], dict):
        return '"fields" is not an object'
    if kind == 'span':
        dur = obj.get('dur_s')
        if not isinstance(dur, (int, float)) or isinstance(dur, bool):
            return 'span record missing numeric "dur_s"'
    sp = obj.get('span')
    if sp is not None and not isinstance(sp, int):
        return '"span" is neither null nor an integer id'
    return None


def load_events(path):
    """Parse one JSONL file -> (events, errors) where errors is a list of
    (line_number, reason, raw_line) for malformed records. Blank lines are
    ignored; nothing raises on bad input — that is what errors is for."""
    events, errors = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                errors.append((i, 'not JSON: %s' % e, line[:120]))
                continue
            reason = validate_record(obj)
            if reason is not None:
                errors.append((i, reason, line[:120]))
                continue
            events.append(obj)
    return events, errors


def latest_run(obs_dir):
    """Newest run-*.jsonl under obs_dir, or None."""
    cands = [os.path.join(obs_dir, d) for d in os.listdir(obs_dir)
             if d.endswith('.jsonl')] if os.path.isdir(obs_dir) else []
    return max(cands, key=os.path.getmtime) if cands else None


def collect_events(path, merge_dir=False):
    """Load events from a .jsonl file, or from a directory (newest run
    only unless merge_dir=True, which concatenates every run file).
    Returns (events, errors, files_read)."""
    if os.path.isdir(path):
        files = sorted(os.path.join(path, d) for d in os.listdir(path)
                       if d.endswith('.jsonl'))
        if not merge_dir:
            latest = latest_run(path)
            files = [latest] if latest else []
    else:
        files = [path]
    events, errors = [], []
    for f in files:
        ev, er = load_events(f)
        events.extend(ev)
        errors.extend((('%s:%d' % (os.path.basename(f), ln)), why, raw)
                      for ln, why, raw in er)
    return events, errors, files


def percentile_exact(values, p):
    """Exact percentile of a small list (nearest-rank with interpolation);
    None on empty input."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    idx = (p / 100.0) * (len(vs) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (idx - lo)


def _spans(events, name):
    return [e for e in events if e.get('kind') == 'span'
            and e.get('name') == name]


def _events(events, name):
    return [e for e in events if e.get('kind') == 'event'
            and e.get('name') == name]


def _fmt_s(v):
    if v is None:
        return '-'
    if v >= 1.0:
        return '%.3fs' % v
    return '%.1fms' % (v * 1e3)


def summarize(events):
    """Human-readable summary string for one run's event list."""
    lines = ['================ obs report ================']
    meta = [e for e in events if e.get('kind') == 'meta'
            and e.get('name') == 'run_start']
    if meta:
        f = meta[0].get('fields', {})
        lines.append('run started %s (pid %s); %d records'
                     % (f.get('time', '?'), f.get('pid', '?'), len(events)))
    else:
        lines.append('%d records (no run_start meta — partial log?)'
                     % len(events))

    # -- steps ----------------------------------------------------------
    steps = _spans(events, 'executor.step')
    compiled_steps = [s for s in steps
                      if s.get('fields', {}).get('compiled')]
    steady = [s['dur_s'] for s in steps
              if not s.get('fields', {}).get('compiled')]
    lines.append('')
    lines.append('-- steps --')
    if steps:
        lines.append('executor steps: %d total, %d carried a compile'
                     % (len(steps), len(compiled_steps)))
        if steady:
            lines.append(
                'steady-state step time: p50 %s  p95 %s  max %s  (n=%d)'
                % (_fmt_s(percentile_exact(steady, 50)),
                   _fmt_s(percentile_exact(steady, 95)),
                   _fmt_s(max(steady)), len(steady)))
        alldur = [s['dur_s'] for s in steps]
        lines.append('all-step time:          p50 %s  p95 %s  max %s'
                     % (_fmt_s(percentile_exact(alldur, 50)),
                        _fmt_s(percentile_exact(alldur, 95)),
                        _fmt_s(max(alldur))))
    else:
        lines.append('no executor.step spans recorded')

    # -- compile / lowering breakdown -----------------------------------
    lowering = _spans(events, 'executor.lowering')
    compiles = _spans(events, 'executor.compile')
    lines.append('')
    lines.append('-- compile --')
    if lowering or compiles:
        per_key = {}
        for s in lowering:
            k = s.get('fields', {}).get('key', '?')
            per_key.setdefault(k, [0.0, 0.0])[0] += s['dur_s']
        for s in compiles:
            k = s.get('fields', {}).get('key', '?')
            per_key.setdefault(k, [0.0, 0.0])[1] += s['dur_s']
        tot_low = sum(v[0] for v in per_key.values())
        tot_cmp = sum(v[1] for v in per_key.values())
        lines.append('lowering %s + compile(+first step) %s over %d '
                     'cache key(s)'
                     % (_fmt_s(tot_low), _fmt_s(tot_cmp), len(per_key)))
        for k, (lo, cm) in sorted(per_key.items(),
                                  key=lambda kv: -(kv[1][0] + kv[1][1])):
            lines.append('  key %-10s lowering %-9s compile %s'
                         % (k, _fmt_s(lo), _fmt_s(cm)))
        steady_total = sum(steady) if steady else 0.0
        denom = steady_total + tot_low + tot_cmp
        if denom > 0:
            lines.append('compile share of instrumented wall time: %.1f%%'
                         % (100.0 * (tot_low + tot_cmp) / denom))
    else:
        lines.append('no lowering/compile spans (every lookup hit the '
                     'cache, or the run predates instrumentation)')

    # -- cache ----------------------------------------------------------
    hits = sum(1 for s in steps if s.get('fields', {}).get('cache') == 'hit')
    misses = sum(1 for s in steps
                 if s.get('fields', {}).get('cache') == 'miss')
    lines.append('')
    lines.append('-- compile cache --')
    if hits + misses:
        lines.append('lookups: %d hits / %d misses (hit ratio %.1f%%)'
                     % (hits, misses, 100.0 * hits / (hits + misses)))
    else:
        lines.append('no cache lookups recorded')
    # persistent (on-disk, cross-process) cache: a first jitted call that
    # DESERIALIZED instead of compiling emits this event and NO
    # executor.compile span — on a warm restart the compile section above
    # should be empty and this line nonzero (docs/perf.md)
    phits = _events(events, 'executor.compile.persistent_hit')
    if phits:
        lines.append('persistent cache: %d executable(s) deserialized '
                     '(zero cold compiles for those keys)' % len(phits))

    # -- bundling --------------------------------------------------------
    bundles = _spans(events, 'executor.bundle')
    if bundles:
        bsteps = sum(int(s.get('fields', {}).get('steps', 0))
                     for s in bundles)
        bdur = [s['dur_s'] for s in bundles]
        lines.append('')
        lines.append('-- bundling --')
        lines.append('%d bundle dispatch(es) covering %d steps '
                     '(p50 %s p95 %s per bundle)'
                     % (len(bundles), bsteps,
                        _fmt_s(percentile_exact(bdur, 50)),
                        _fmt_s(percentile_exact(bdur, 95))))
    stalls = _spans(events, 'executor.host_stall')
    if stalls:
        sdur = [s['dur_s'] for s in stalls]
        lines.append('async fetch: %d host stall(s), total %s '
                     '(p95 %s) — time the host actually blocked on the '
                     'device' % (len(stalls), _fmt_s(sum(sdur)),
                                 _fmt_s(percentile_exact(sdur, 95))))

    # -- step artifact ---------------------------------------------------
    # the compiled-step artifact + pipeline-overlap story (docs/perf.md):
    # one executor.artifact event per artifact build, one first-call
    # record per compiled signature (executor.compile span = online
    # compile; executor.compile.persistent_hit / .aot_hit events =
    # deserialized), trainer.input_stage spans for the input-overlap
    # ratio, and checkpoint.snapshot / checkpoint.commit /
    # trainer.checkpoint.async_wait spans for the async-checkpoint
    # latencies.
    artifacts = _events(events, 'executor.artifact')
    aot_hits = _events(events, 'executor.compile.aot_hit')
    aot_stale = _events(events, 'executor.aot.stale')
    aot_loaded = _events(events, 'executor.aot.loaded')
    aot_exported = _events(events, 'executor.aot.exported')
    input_stage = _spans(events, 'trainer.input_stage')
    snaps = _spans(events, 'checkpoint.snapshot')
    awaits = _spans(events, 'trainer.checkpoint.async_wait')
    if artifacts or aot_hits or aot_loaded or aot_exported or input_stage \
            or snaps or awaits:
        lines.append('')
        lines.append('-- step artifact --')
        if artifacts:
            # per-artifact signature count: every first-call record
            # (compile span OR persistent/aot-hit event) under the
            # artifact's cache key is one compiled entry point (the
            # unbundled step, each bundle length)
            sig_per_key = {}
            for rec in (compiles + phits + aot_hits):
                k = rec.get('fields', {}).get('key', '?')
                sig_per_key[k] = sig_per_key.get(k, 0) + 1
            per_art = [sig_per_key.get(
                e.get('fields', {}).get('key', '?'), 0)
                for e in artifacts]
            lines.append('%d artifact(s) built; signatures per artifact: '
                         '%s (total %d)'
                         % (len(artifacts),
                            '/'.join(str(n) for n in per_art) or '0',
                            sum(sig_per_key.values())))
        split = ('first calls: %d compiled online, %d persistent-hit, '
                 '%d AOT-hit' % (len(compiles), len(phits),
                                 len(aot_hits)))
        if aot_stale:
            split += ', %d STALE (AOT-claimed but compiled)' \
                % len(aot_stale)
        lines.append(split)
        for e in aot_loaded:
            f = e.get('fields', {})
            lines.append('AOT blob loaded: %s signature(s), %s cache '
                         'entr(ies) imported'
                         % (f.get('signatures', '?'),
                            f.get('cache_entries_imported', '?')))
        for e in aot_exported:
            f = e.get('fields', {})
            lines.append('AOT blob exported: %s signature(s), %s cache '
                         'entr(ies)' % (f.get('signatures', '?'),
                                        f.get('cache_entries', '?')))
        if input_stage:
            wait_s = sum(s['dur_s'] for s in input_stage)
            staged = sum(1 for s in input_stage
                         if s.get('fields', {}).get('staged'))
            step_s = sum(s['dur_s'] for s in
                         _spans(events, 'trainer.step'))
            line = ('input stage: %s over %d batch(es) (%d staged '
                    'off-thread)' % (_fmt_s(wait_s), len(input_stage),
                                     staged))
            if step_s > 0:
                line += (' — overlap ratio %.1f%% '
                         '(1 - input wait / step time)'
                         % (100.0 * (1.0 - min(1.0, wait_s / step_s))))
            lines.append(line)
        if snaps:
            sd = [s['dur_s'] for s in snaps]
            lines.append('async checkpoint snapshots: %d (p50 %s  max %s)'
                         % (len(snaps),
                            _fmt_s(percentile_exact(sd, 50)),
                            _fmt_s(max(sd))))
        commits = _spans(events, 'checkpoint.commit')
        if snaps and commits:
            cd = [s['dur_s'] for s in commits]
            lines.append('commit latency: p50 %s  max %s (%d commit '
                         'span(s))' % (_fmt_s(percentile_exact(cd, 50)),
                                       _fmt_s(max(cd)), len(commits)))
        if awaits:
            ad = [s['dur_s'] for s in awaits]
            stalls_n = sum(1 for s in awaits
                           if not s.get('fields', {}).get('ready'))
            lines.append('async-save waits at step boundary: %d (total '
                         '%s, %d not yet done when waited)'
                         % (len(awaits), _fmt_s(sum(ad)), stalls_n))

    # -- optimizer passes ------------------------------------------------
    # passes.optimize spans carry ops_before/ops_after + per-pass sums
    # (docs/passes.md): the attribution trail for op-count wins
    opt_spans = _spans(events, 'passes.optimize')
    if opt_spans:
        before = sum(int(s.get('fields', {}).get('ops_before', 0))
                     for s in opt_spans)
        after = sum(int(s.get('fields', {}).get('ops_after', 0))
                    for s in opt_spans)
        lines.append('')
        lines.append('-- optimizer passes --')
        lines.append('%d program(s) optimized: %d -> %d top-level op(s)'
                     % (len(opt_spans), before, after))
        per = {}
        for name in ('dce', 'fold', 'cse', 'amp', 'quant'):
            tot = sum(int(s.get('fields', {}).get(name, 0))
                      for s in opt_spans)
            if tot:
                per[name] = tot
        if per:
            lines.append('per pass: ' + ', '.join(
                '%s=%d' % kv for kv in sorted(per.items())))
        errs = _events(events, 'passes.error')
        if errs:
            lines.append('%d optimizer failure(s) fell back to the '
                         'unoptimized lowering' % len(errs))

    # -- analysis ---------------------------------------------------------
    # the build-time verifier gate (analysis.verify — one span per
    # (program, context) key PADDLE_TPU_VERIFY judged) and the static
    # cost model (analysis.cost — one span per cost_report() pricing;
    # docs/analysis.md#pass-6)
    ver_spans = _spans(events, 'analysis.verify')
    cost_spans = _spans(events, 'analysis.cost')
    if ver_spans or cost_spans:
        lines.append('')
        lines.append('-- analysis --')
        if ver_spans:
            nf = sum(int(s.get('fields', {}).get('findings', 0))
                     for s in ver_spans)
            ne = sum(int(s.get('fields', {}).get('errors', 0))
                     for s in ver_spans)
            lines.append('%d program(s) verified: %d finding(s), '
                         '%d error-severity' % (len(ver_spans), nf, ne))
        if cost_spans:
            res = max(int(s.get('fields', {})
                          .get('residency_per_device', 0))
                      for s in cost_spans)
            comm = max(int(s.get('fields', {})
                           .get('comm_bytes_per_step', 0))
                       for s in cost_spans)
            lines.append('cost model: %d report(s); max residency '
                         '%d bytes/device, max wire %d bytes/step'
                         % (len(cost_spans), res, comm))

    # -- kernels ----------------------------------------------------------
    # pallas kernel layer (docs/perf.md#kernel-layer): one
    # kernels.dispatch event per TRACE-time routing decision — mode
    # 'kernel' means the pallas body was baked into the compiled module,
    # 'fallback' the pure-XLA lowering. These count compiled modules,
    # not steady-state steps (which re-trace nothing).
    kdisp = _events(events, 'kernels.dispatch')
    if kdisp:
        lines.append('')
        lines.append('-- kernels --')
        per = {}
        for e in kdisp:
            f = e.get('fields', {})
            key = (str(f.get('kernel', '?')), str(f.get('mode', '?')))
            per[key] = per.get(key, 0) + 1
        n_k = sum(v for (_, m), v in per.items() if m == 'kernel')
        n_f = sum(v for (_, m), v in per.items() if m == 'fallback')
        lines.append('trace-time dispatches: %d kernel, %d fallback'
                     % (n_k, n_f))
        for (k, m), v in sorted(per.items()):
            lines.append('  %s: %d %s trace(s)' % (k, v, m))

    # -- sharding / GSPMD ------------------------------------------------
    # executor.remat_detected: XLA's SPMD partitioner fell back to
    # replicate-then-repartition during a compile (an all-gather per step
    # the program never asked for). Zero is the contract on the shipped
    # compositions (docs/parallel.md); any nonzero here is a sharding
    # regression that previously only lived in dryrun stderr tails.
    remat = _events(events, 'executor.remat_detected')
    if remat:
        n = sum(int(e.get('fields', {}).get('count', 1)) for e in remat)
        keys = sorted({str(e.get('fields', {}).get('key', '?'))
                       for e in remat})
        lines.append('')
        lines.append('-- sharding / GSPMD --')
        lines.append('involuntary rematerialization: %d detection(s) '
                     'across compile key(s) %s — a sharding transition '
                     'XLA could only satisfy by replicating the tensor'
                     % (n, ', '.join(keys)))

    # -- embedding -------------------------------------------------------
    # sharded-embedding subsystem (docs/embedding.md): one
    # embedding.lookup event per compiled lookup wire (its geometry) and
    # one embedding.update_rows event per sparse-plan compile (which
    # tables update touched-rows-only, at what per-step bound)
    lookups = _events(events, 'embedding.lookup')
    updates = _events(events, 'embedding.update_rows')
    if lookups or updates:
        lines.append('')
        lines.append('-- embedding --')
        for e in lookups:
            f = e.get('fields', {})
            lines.append('lookup wire: %s ids over axis %s=%s '
                         '(vocab %s, dim %s; %s query slots/shard, '
                         '%s row B/device per exchange)'
                         % (f.get('ids', '?'), f.get('axis', '?'),
                            f.get('axis_size', '?'), f.get('vocab', '?'),
                            f.get('dim', '?'),
                            f.get('query_capacity', '?'),
                            f.get('row_bytes_per_device', '?')))
        for e in updates:
            f = e.get('fields', {})
            lines.append('sparse updates: tables %s, <= %s rows/step '
                         'touched%s (key %s)'
                         % (','.join(f.get('tables', []) or ['?']),
                            f.get('rows_per_step', '?'),
                            ' [sharded]' if f.get('sharded') else '',
                            f.get('key', '?')))

    # -- streaming --------------------------------------------------------
    # streaming-ids online training (docs/embedding.md "streaming ids"):
    # vocab drift (admit/evict events from the VocabTable), and the
    # train->serve delta pushes with their freshness lag
    admits = _events(events, 'streaming.admit')
    evicts = _events(events, 'streaming.evict')
    pushes = _events(events, 'streaming.delta_push')
    rpushes = _events(events, 'router.delta_push')
    if admits or evicts or pushes or rpushes:
        lines.append('')
        lines.append('-- streaming --')
        n_adm = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                    for e in admits)
        n_ev = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                   for e in evicts)
        # the LAST drift event in file order (admits and evicts each
        # carry the post-event resident count; concatenating the lists
        # would wrongly prefer the last evict over a later admit)
        drift = [e for e in events
                 if e.get('name') in ('streaming.admit',
                                      'streaming.evict')]
        resident = drift[-1].get('fields', {}).get('resident', '?') \
            if drift else '?'
        lines.append('vocab drift: %d row(s) admitted, %d evicted '
                     '(resident now: %s)' % (n_adm, n_ev, resident))
        ok = [e for e in pushes if e.get('fields', {}).get('ok')]
        failed = len(pushes) - len(ok)
        if pushes:
            n_rows = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                         for e in ok)
            last = ok[-1].get('fields', {}) if ok else {}
            lines.append('delta pushes: %d ok / %d failed, %d row(s) '
                         'pushed (last: %s ms push, %s s freshness lag)'
                         % (len(ok), failed, n_rows,
                            last.get('push_ms', '?'),
                            last.get('freshness_lag_s', '?')))
        for e in rpushes[-3:]:
            f = e.get('fields', {})
            lines.append('  router push: model %s v%s -> %s replica(s)'
                         ' (%s closed), tables %s'
                         % (f.get('model', '?'), f.get('version', '?'),
                            f.get('replicas', '?'), f.get('closed', 0),
                            ','.join(f.get('tables', []) or ['?'])))

    # -- tiers ------------------------------------------------------------
    # the host-RAM spill tier behind the HBM table (docs/embedding.md
    # #tiers): spill/restore traffic, the warm-restore prefetch leg,
    # and the two LOUD fallbacks (arena full, CRC-failed slot)
    t_spills = _events(events, 'streaming.tier.spill')
    t_restores = _events(events, 'streaming.tier.restore')
    t_prefetch = _events(events, 'streaming.tier.prefetch')
    t_full = _events(events, 'streaming.tier.arena_full')
    t_corrupt = _events(events, 'streaming.tier.corrupt')
    if t_spills or t_restores or t_prefetch or t_full or t_corrupt:
        lines.append('')
        lines.append('-- tiers --')
        n_sp = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                   for e in t_spills)
        n_re = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                   for e in t_restores)
        n_pf = sum(int(e.get('fields', {}).get('rows', 0) or 0)
                   for e in t_prefetch)
        lines.append('spill tier: %d row(s) spilled to host, %d '
                     'restored warm (%d prefetched on the worker)'
                     % (n_sp, n_re, n_pf))
        if t_spills:
            f = t_spills[-1].get('fields', {})
            lines.append('arena: %s/%s slots used (last spill %s ms)'
                         % (f.get('arena_used', '?'),
                            f.get('arena_slots', '?'),
                            f.get('spill_ms', '?')))
        if t_restores:
            f = t_restores[-1].get('fields', {})
            lines.append('last restore: %s row(s) in %s ms'
                         % (f.get('rows', '?'), f.get('restore_ms', '?')))
        if t_full:
            n_drop = sum(int(e.get('fields', {}).get('dropped', 0) or 0)
                         for e in t_full)
            lines.append('ARENA FULL: %d evicted id(s) fell back to '
                         'zeroing (cold re-admit) — provision slots'
                         % n_drop)
        if t_corrupt:
            lines.append('CORRUPT SLOTS: %d spilled row(s) failed CRC '
                         'and were dropped (cold re-admit)'
                         % len(t_corrupt))

    # -- anomaly guard ---------------------------------------------------
    skips = _events(events, 'anomaly.skip')
    lines.append('')
    lines.append('-- anomaly guard --')
    if skips:
        last = skips[-1].get('fields', {})
        lines.append('skipped steps: %d (last: run=%s grad_norm=%s '
                     'loss_finite=%s grads_finite=%s)'
                     % (len(skips), last.get('run', '?'),
                        last.get('grad_norm', '?'),
                        last.get('loss_finite', '?'),
                        last.get('grads_finite', '?')))
    else:
        lines.append('skipped steps: 0')

    # -- retries ---------------------------------------------------------
    retries = _events(events, 'retry.attempt')
    deadline = _events(events, 'retry.deadline_exceeded')
    exhausted = _events(events, 'retry.exhausted')
    lines.append('')
    lines.append('-- retries --')
    if retries or deadline or exhausted:
        by_site = {}
        for e in retries:
            f = e.get('fields', {})
            s = by_site.setdefault(f.get('site', '?'), [0, 0.0])
            s[0] += 1
            s[1] += float(f.get('delay_s', 0.0) or 0.0)
        for site, (n, backoff) in sorted(by_site.items()):
            lines.append('  %-32s %3d retr%s, %s backoff'
                         % (site, n, 'y' if n == 1 else 'ies',
                            _fmt_s(backoff)))
        if deadline:
            lines.append('  deadline exceeded: %d' % len(deadline))
        if exhausted:
            lines.append('  attempts exhausted: %d' % len(exhausted))
    else:
        lines.append('no retries')

    # -- reader ----------------------------------------------------------
    r_retries = _events(events, 'reader.retry')
    degrades = _events(events, 'reader.degrade')
    lines.append('')
    lines.append('-- reader --')
    if r_retries or degrades:
        lines.append('source re-opens: %d; degraded-to-skip streams: %d'
                     % (len(r_retries), len(degrades)))
        for e in degrades:
            f = e.get('fields', {})
            lines.append('  degrade after %s sample(s): %s'
                         % (f.get('emitted', '?'),
                            str(f.get('error', ''))[:80]))
    else:
        lines.append('no reader faults')

    # -- checkpoints ------------------------------------------------------
    ck = [e for e in events
          if e.get('name', '').startswith(('trainer.checkpoint.',
                                           'checkpoint.',
                                           'trainer.resume.',
                                           'trainer.preempted'))]
    lines.append('')
    lines.append('-- checkpoint timeline --')
    if ck:
        t0 = min(e['ts'] for e in events)
        for e in sorted(ck, key=lambda e: e['ts']):
            f = e.get('fields', {})
            extra = ' '.join('%s=%s' % (k, f[k]) for k in sorted(f)
                             if k not in ('error',))
            err = (' ERROR: %s' % str(f['error'])[:60]) if 'error' in f \
                else ''
            dur = (' [%s]' % _fmt_s(e['dur_s'])) if 'dur_s' in e else ''
            lines.append('  +%8.3fs %-34s%s %s%s'
                         % (e['ts'] - t0, e['name'], dur, extra, err))
    else:
        lines.append('no checkpoint activity')

    # -- elastic ----------------------------------------------------------
    # elastic pod training (docs/robustness.md#elastic): sharded-
    # checkpoint commits, reshard-on-restore, topology-change resumes,
    # heartbeat staleness and host-loss verdicts — the decisions that
    # keep a pod job restartable, one line each
    # commits counted from the checkpoint.committed EVENT (fires only
    # after the rename) — the checkpoint.commit span also covers
    # staged-role peers and timed-out attempts, which are not commits
    el_commits = _events(events, 'checkpoint.committed')
    el_reshard = _spans(events, 'checkpoint.reshard')
    el_resume = _events(events, 'elastic.resume')
    el_lost = _events(events, 'elastic.host_lost')
    el_stale = _events(events, 'parallel.heartbeat.stale')
    el_skip = _events(events, 'checkpoint.uncommitted_skipped')
    el_cto = _events(events, 'checkpoint.commit.timeout')
    if el_commits or el_reshard or el_resume or el_lost or el_stale \
            or el_skip or el_cto:
        lines.append('')
        lines.append('-- elastic --')
        if el_commits:
            steps = [e.get('fields', {}).get('step') for e in el_commits]
            lines.append('checkpoint commits: %d (last step %s)'
                         % (len(el_commits), steps[-1]))
        for e in el_cto:
            f = e.get('fields', {})
            lines.append('commit TIMED OUT: step %s waiting for peer '
                         'process(es) %s — left uncommitted'
                         % (f.get('step', '?'), f.get('missing', '?')))
        for e in el_skip:
            lines.append('uncommitted (torn) staging dir(s) skipped on '
                         'restore: %s' % e.get('fields', {}).get('dirs'))
        for s in el_reshard:
            f = s.get('fields', {})
            lines.append('reshard-on-restore: %s array(s), mesh %s -> %s'
                         % (f.get('arrays', '?'), f.get('from_mesh', '?'),
                            f.get('to_mesh', '?')))
        for e in el_resume:
            f = e.get('fields', {})
            lines.append('elastic resume: serial %s at epoch %s step %s, '
                         'mesh %s -> %s'
                         % (f.get('serial', '?'), f.get('epoch', '?'),
                            f.get('step', '?'), f.get('from_mesh', '?'),
                            f.get('to_mesh', '?')))
        if el_stale:
            peers = sorted({e.get('fields', {}).get('peer')
                            for e in el_stale})
            lines.append('stale heartbeats: %d detection(s), peer(s) %s'
                         % (len(el_stale), peers))
        for e in el_lost:
            f = e.get('fields', {})
            lines.append('HOST LOST: peer(s) %s at epoch %s step %s'
                         % (f.get('stale', '?'), f.get('epoch', '?'),
                            f.get('step', '?')))

    # -- serving ----------------------------------------------------------
    sv_batches = _spans(events, 'serving.batch')
    sv_warm = _spans(events, 'serving.warmup')
    sv_rejects = _events(events, 'serving.reject')
    sv_sheds = _events(events, 'serving.shed')
    sv_errors = _events(events, 'serving.batch.error')
    sv_down = _events(events, 'serving.shutdown')
    if sv_batches or sv_warm or sv_rejects or sv_sheds or sv_errors \
            or sv_down:
        lines.append('')
        lines.append('-- serving --')
        if sv_warm:
            per_bucket = ', '.join(
                'b%s %s' % (s.get('fields', {}).get('bucket', '?'),
                            _fmt_s(s['dur_s']))
                for s in sorted(sv_warm, key=lambda s: s.get(
                    'fields', {}).get('bucket', 0)))
            lines.append('warmup: %d bucket(s) pre-compiled (%s)'
                         % (len(sv_warm), per_bucket))
        if sv_batches:
            sizes = [s.get('fields', {}).get('batch_size', 0)
                     for s in sv_batches]
            pads = [s.get('fields', {}).get('padded', 0)
                    for s in sv_batches]
            waits = [s.get('fields', {}).get('wait_max_s')
                     for s in sv_batches]
            waits = [w for w in waits if isinstance(w, (int, float))]
            execs = [s['dur_s'] for s in sv_batches]
            rows = sum(sizes)
            lines.append('batches: %d (%d row(s); batch size p50 %s max %s; '
                         'padding overhead %.1f%%)'
                         % (len(sv_batches), rows,
                            percentile_exact(sizes, 50), max(sizes),
                            100.0 * sum(pads) / max(rows + sum(pads), 1)))
            lines.append('exec latency: p50 %s  p95 %s  max %s'
                         % (_fmt_s(percentile_exact(execs, 50)),
                            _fmt_s(percentile_exact(execs, 95)),
                            _fmt_s(max(execs))))
            if waits:
                lines.append('queue wait (batch max): p50 %s  max %s'
                             % (_fmt_s(percentile_exact(waits, 50)),
                                _fmt_s(max(waits))))
        if sv_rejects or sv_sheds:
            lines.append('overload: %d rejected, %d shed past deadline'
                         % (len(sv_rejects), len(sv_sheds)))
        for e in sv_errors:
            f = e.get('fields', {})
            lines.append('  batch ERROR (%s request(s)): %s'
                         % (f.get('requests', '?'),
                            str(f.get('error', ''))[:80]))
        for e in sv_down:
            f = e.get('fields', {})
            lines.append('shutdown: drained=%s clean=%s completed=%s '
                         'shed=%s' % (f.get('drained', '?'),
                                      f.get('clean', '?'),
                                      f.get('completed', '?'),
                                      f.get('shed', '?')))

    # -- continuous-batching decode + router ------------------------------
    dc_joins = _events(events, 'decode.join')
    dc_rel = _events(events, 'decode.release')
    dc_poison = _events(events, 'decode.poisoned')
    dc_shed = _events(events, 'decode.shed')
    dc_rej = _events(events, 'decode.reject')
    dc_pferr = _events(events, 'decode.prefill.error')
    dc_warm = _spans(events, 'decode.warmup')
    dc_down = _events(events, 'decode.shutdown')
    rt_swap = _events(events, 'router.swap')
    rt_over = _events(events, 'router.overloaded')
    if dc_joins or dc_rel or dc_poison or dc_shed or dc_rej or dc_down \
            or dc_warm or dc_pferr:
        lines.append('')
        lines.append('-- decode --')
        if dc_warm:
            kinds = {}
            for s in dc_warm:
                k = s.get('fields', {}).get('kind', 'join')
                kinds[k] = kinds.get(k, 0) + 1
            lines.append('warmup: %s signature(s) pre-compiled'
                         % ', '.join('%d %s' % (c, k)
                                     for k, c in sorted(kinds.items())))
        toks = [e.get('fields', {}).get('steps', 0) for e in dc_rel]
        lines.append('slot lifecycle: joins: %d  released: %d  '
                     'poisoned: %d' % (len(dc_joins), len(dc_rel),
                                       len(dc_poison)))
        if toks:
            lines.append('tokens per released request: p50 %s  max %s  '
                         '(total %d)'
                         % (percentile_exact(toks, 50), max(toks),
                            sum(toks)))
        # paged state memory: page-pool occupancy from the per-join
        # pages_free samples, prefix-cache counters + speculative
        # accept rate from the shutdown summary (docs/serving.md)
        pg_free = [e['fields']['pages_free'] for e in dc_joins
                   if 'pages_free' in e.get('fields', {})]
        pg_total = next((e['fields']['pages_total'] for e in dc_down
                         if 'pages_total' in e.get('fields', {})), None)
        if pg_free:
            line = ('page pool: min free %d (peak occupancy)'
                    % min(pg_free))
            if pg_total is not None:
                line += ' of %d total' % pg_total
            lines.append(line)
        dc_evict = _events(events, 'decode.prefix.evict')
        pf_hits = sum(1 for e in dc_joins
                      if e.get('fields', {}).get('prefix_hit') is True)
        pf_miss = sum(1 for e in dc_joins
                      if e.get('fields', {}).get('prefix_hit') is False)
        if pf_hits or pf_miss or dc_evict:
            lines.append('prefix cache: %d hit(s), %d miss(es), %d '
                         'evicted (hit rate %s)'
                         % (pf_hits, pf_miss, len(dc_evict),
                            '%.2f' % (pf_hits / (pf_hits + pf_miss))
                            if pf_hits + pf_miss else 'n/a'))
        for e in dc_down:
            rate = e.get('fields', {}).get('spec_accept_rate')
            if rate is not None:
                lines.append('speculative decode: accept rate %.2f'
                             % rate)
        if dc_shed or dc_rej:
            by_reason = {}
            for e in dc_rej:
                r = e.get('fields', {}).get('reason', 'queue')
                by_reason[r] = by_reason.get(r, 0) + 1
            detail = ''
            if by_reason.get('pages'):
                detail = ' (%d blocked on the page pool)' \
                    % by_reason['pages']
            lines.append('overload: %d rejected%s, %d shed past deadline'
                         % (len(dc_rej), detail, len(dc_shed)))
        for e in dc_pferr:
            f = e.get('fields', {})
            lines.append('  prefill ERROR (%s request(s)): %s'
                         % (f.get('requests', '?'),
                            str(f.get('error', ''))[:80]))
        for e in dc_down:
            f = e.get('fields', {})
            lines.append('shutdown: drained=%s clean=%s completed=%s '
                         'tokens=%s' % (f.get('drained', '?'),
                                        f.get('clean', '?'),
                                        f.get('completed', '?'),
                                        f.get('tokens', '?')))
    # -- pod serving: registry, host loss, heal, autoscale -----------------
    pd_reg = _events(events, 'serving.replica.register')
    pd_drain = _events(events, 'serving.replica.drain')
    pd_lost = _events(events, 'serving.replica.lost')
    pd_resh = _events(events, 'serving.replica.reshard')
    pd_heal = _events(events, 'serving.pod.heal_requested')
    pd_hfail = (_events(events, 'serving.pod.heal_failed')
                + _events(events, 'serving.pod.heal_unroutable'))
    pd_scale = _events(events, 'serving.autoscale')
    pd_hlost = _events(events, 'router.host_lost')
    if pd_reg or pd_lost or pd_resh or pd_drain or pd_scale:
        lines.append('')
        lines.append('-- pod serving --')
        hosts = sorted({e.get('fields', {}).get('host')
                        for e in pd_reg
                        if e.get('fields', {}).get('host') is not None})
        lines.append('replicas: %d registered across %d host(s), '
                     '%d drained, %d lost'
                     % (len(pd_reg), len(hosts), len(pd_drain),
                        len(pd_lost)))
        for e in pd_hlost:
            f = e.get('fields', {})
            lines.append('host LOST: h%s — %s replica(s) detached, %s '
                         'future(s) re-routed, %s heal(s) requested'
                         % (f.get('host', '?'), f.get('replicas', '?'),
                            f.get('rerouted', '?'), f.get('heals', '?')))
        for e in pd_resh:
            f = e.get('fields', {})
            line = ('reshard: model=%s -> h%s (%s)'
                    % (f.get('model', '?'), f.get('host', '?'),
                       f.get('key', '?')))
            if f.get('heal_s') is not None:
                line += ' healed in %s' % _fmt_s(f['heal_s'])
            lines.append(line)
        if pd_heal or pd_hfail:
            lines.append('heals: %d requested, %d failed/unroutable'
                         % (len(pd_heal), len(pd_hfail)))
        if pd_scale:
            ups = sum(1 for e in pd_scale
                      if e.get('fields', {}).get('direction') == 'up')
            lines.append('autoscale: %d up, %d down'
                         % (ups, len(pd_scale) - ups))

    # -- rpc transport + per-token streams ---------------------------------
    tr_conn = _events(events, 'serving.transport.connect')
    tr_reco = _events(events, 'serving.transport.reconnect')
    tr_err = _events(events, 'serving.transport.error')
    tr_rej = _events(events, 'serving.transport.reject')
    st_open = _events(events, 'serving.stream.open')
    st_first = _events(events, 'serving.stream.first_token')
    st_res = _events(events, 'serving.stream.resume')
    st_fail = _events(events, 'serving.stream.failover')
    st_close = _events(events, 'serving.stream.close')
    if tr_conn or tr_reco or tr_err or st_open or st_close:
        lines.append('')
        lines.append('-- transport / streams --')
        if tr_conn or tr_reco or tr_err or tr_rej:
            lines.append('rpc wire: %d connect(s), %d reconnect(s), '
                         '%d wire error(s), %d admission reject(s)'
                         % (len(tr_conn), len(tr_reco), len(tr_err),
                            len(tr_rej)))
        if st_open or st_close:
            failed = [e for e in st_close
                      if e.get('fields', {}).get('error')]
            lines.append('streams: %d opened, %d closed (%d failed)'
                         % (len(st_open), len(st_close), len(failed)))
        if st_first:
            ttfts = sorted(e['fields']['ttft_s'] for e in st_first
                           if e.get('fields', {}).get('ttft_s')
                           is not None)
            if ttfts:
                lines.append('ttft: min=%s p50=%s max=%s over %d '
                             'stream(s)'
                             % (_fmt_s(ttfts[0]),
                                _fmt_s(ttfts[len(ttfts) // 2]),
                                _fmt_s(ttfts[-1]), len(ttfts)))
        if st_res or st_fail:
            replayed = sum(int(e.get('fields', {}).get('replayed') or 0)
                           for e in st_res)
            lines.append('failover: %d stream(s) lost a host, %d '
                         'resumed token-exact (%d token(s) replayed)'
                         % (len(st_fail) + len(st_res), len(st_res),
                            replayed))
            for e in st_fail:
                f = e.get('fields', {})
                if not f.get('resumed', True):
                    lines.append('  NOT resumed (ckpt_every=0): sid=%s '
                                 'at t=%s' % (f.get('sid', '-'),
                                              f.get('seen_t', '?')))

    if rt_swap or rt_over:
        lines.append('')
        lines.append('-- router --')
        for e in rt_swap:
            f = e.get('fields', {})
            lines.append('swap: model=%s -> version %s (%s replica(s))'
                         % (f.get('model', '?'), f.get('version', '?'),
                            f.get('replicas', '?')))
        if rt_over:
            by_model = {}
            for e in rt_over:
                m = e.get('fields', {}).get('model', '?')
                by_model[m] = by_model.get(m, 0) + 1
            lines.append('overloaded: %s'
                         % ', '.join('%s x%d' % kv
                                     for kv in sorted(by_model.items())))

    # -- bench ------------------------------------------------------------
    bench = _events(events, 'bench.metric') \
        + _events(events, 'bench.sweep.cmd')
    if bench:
        lines.append('')
        lines.append('-- bench --')
        for e in bench:
            f = e.get('fields', {})
            if e['name'] == 'bench.metric':
                lines.append('  %-52s %s %s'
                             % (f.get('metric', '?'), f.get('value', '-'),
                                f.get('unit', '')))
            else:
                lines.append('  sweep cmd rc=%s %s: %s'
                             % (f.get('rc', '?'),
                                _fmt_s(f.get('dur_s')),
                                str(f.get('cmd', ''))[:70]))
    lines.append('============================================')
    return '\n'.join(lines)
