"""paddle_tpu.obs — the runtime telemetry layer.

Three pieces (docs/observability.md has the full catalog):

  * a process-wide METRICS REGISTRY (obs.metrics): counters, gauges,
    fixed-bucket histograms. Always armed — an increment is a lock and an
    add, cheap enough for the executor hot path — so `exe.cache_stats`
    and the fault-drill assertions work with no environment set up.
  * a STRUCTURED RUN LOG: JSONL, one event per record, written under
    $PADDLE_TPU_OBS_DIR (or obs.enable(dir)). Created lazily on the first
    record; when observability is disabled there is NO file IO at all.
  * a SPAN API: `with obs.span("executor.step"): ...` nests via a
    thread-local stack, records wall time into the registry histogram
    `<name>.seconds`, appends a span record to the run log, and forwards
    to jax.profiler.TraceAnnotation (StepTraceAnnotation when step_num is
    given) so the same names appear in Perfetto/XLA traces.

Disabled-mode contract (the default): spans still time into the in-memory
registry, but no file is written, no event is recorded, and jax is never
imported — this module is stdlib-only and only *reuses* jax.profiler when
the host program already imported jax AND observability is on. Tests load
the package standalone (importlib, no paddle_tpu parent) to enforce that.
"""
import itertools
import os
import sys
import threading
import time

from . import metrics  # noqa: F401
from . import report  # noqa: F401
from . import runlog as _runlog
from . import slo  # noqa: F401
from . import trace  # noqa: F401
from .metrics import REGISTRY, counter, gauge, histogram  # noqa: F401

__all__ = ['metrics', 'report', 'slo', 'trace', 'REGISTRY', 'counter',
           'gauge', 'histogram', 'enabled', 'obs_dir', 'enable', 'disable',
           'event', 'span', 'span_record', 'run_log_path', 'ENV_DIR']

ENV_DIR = 'PADDLE_TPU_OBS_DIR'
# Optional: pin the run-log to an EXACT file path instead of a fresh
# run-<stamp>-<pid>.jsonl — how tools/perf_sweep.sh collects one sweep's
# events (its own + every child bench's) into a single run file.
ENV_RUN_FILE = 'PADDLE_TPU_OBS_RUN_FILE'
# Ring-buffer bound of the run log (see runlog.RunLog); applies to fresh
# per-run files. A pinned shared file (ENV_RUN_FILE) stays unbounded by
# default because compaction would drop other writers' appends.
ENV_MAX_EVENTS = 'PADDLE_TPU_OBS_MAX_EVENTS'
DEFAULT_MAX_EVENTS = 500000

_state = {
    'override': None,      # None = follow env; (True, dir) / (False, None)
    'runlog': None,
    'runlog_dir': None,
    'failed_dir': None,    # dir whose run-log creation failed (warn once)
    'lock': threading.RLock(),
}
_span_ids = itertools.count(1)
_local = threading.local()
# span-name -> registry histogram, so the per-span fast path skips the
# registry's label-normalizing lookup (hot: 3 spans per executor step)
_span_hists = {}


def obs_dir():
    """The active observability directory, or None when disabled.
    obs.enable()/disable() override the PADDLE_TPU_OBS_DIR environment."""
    ov = _state['override']
    if ov is not None:
        return ov[1] if ov[0] else None
    return os.environ.get(ENV_DIR) or None


def enabled():
    return obs_dir() is not None


def enable(dir_path):
    """Force observability on, writing a fresh run log under dir_path
    (tests and notebooks; production uses the environment variable)."""
    with _state['lock']:
        _close_runlog_locked()
        _state['override'] = (True, str(dir_path))


def disable():
    """Force observability off regardless of the environment; closes the
    current run log. Call enable()/disable(None-reset) via _reset() in
    tests to return to env-driven behavior."""
    with _state['lock']:
        _close_runlog_locked()
        _state['override'] = (False, None)


def _reset():
    """Back to environment-driven state with no open run log (tests)."""
    with _state['lock']:
        _close_runlog_locked()
        _state['override'] = None
        _span_hists.clear()   # drop handles detached by REGISTRY.reset()
    trace._reset()


def _close_runlog_locked():
    rl = _state['runlog']
    if rl is not None:
        rl.close()
    _state['runlog'] = None
    _state['runlog_dir'] = None
    _state['failed_dir'] = None


def _run_log():
    """The current run's RunLog, created lazily; None when disabled. A
    change of directory (enable() with a new path, env flip) starts a new
    run file. A directory whose run log cannot be created (unwritable
    path, full disk) is warned about ONCE and then skipped — telemetry
    must never take down the step it observes."""
    d = obs_dir()
    if d is None:
        return None
    rl = _state['runlog']
    if rl is not None and _state['runlog_dir'] == d:
        return rl
    if _state['failed_dir'] == d:
        return None
    with _state['lock']:
        rl = _state['runlog']
        if rl is None or _state['runlog_dir'] != d:
            _close_runlog_locked()
            # the env pin only applies in env-driven mode: an explicit
            # obs.enable(dir) (tests isolating a run) must not be
            # silently redirected into a leaked shared run file
            pinned = (os.environ.get(ENV_RUN_FILE)
                      if _state['override'] is None else None)
            path = pinned or _runlog.new_run_path(d)
            max_events = None if pinned else DEFAULT_MAX_EVENTS
            raw = os.environ.get(ENV_MAX_EVENTS)
            if raw:
                try:
                    max_events = int(raw) or None
                except ValueError:
                    pass
            try:
                rl = _runlog.RunLog(path, max_events=max_events)
            except Exception as e:
                _state['failed_dir'] = d
                import warnings
                warnings.warn(
                    'obs run log unavailable under %r (%s: %s); telemetry '
                    'file output disabled until the directory changes'
                    % (d, type(e).__name__, e), RuntimeWarning)
                return None
            _state['runlog'] = rl
            _state['runlog_dir'] = d
    return rl


def run_log_path():
    """Path of the current run's JSONL file (None when disabled or when
    nothing has been recorded yet — the file is created lazily)."""
    rl = _state['runlog']
    return rl.path if rl is not None and _state['runlog_dir'] == obs_dir() \
        else None


def _span_stack():
    st = getattr(_local, 'stack', None)
    if st is None:
        st = _local.stack = []
    return st


def current_span_id():
    st = getattr(_local, 'stack', None)
    return st[-1].id if st else None


def event(name, **fields):
    """Record a one-shot event (no-op when disabled). Returns the record
    dict when written, else None — handy for tests."""
    rl = _run_log()
    if rl is None:
        return None
    rec = {'ts': time.monotonic(), 'kind': 'event', 'name': name,
           'span': current_span_id(), 'fields': fields}
    tids = trace._ids()
    if tids:
        rec.update(tids)
    rl.write(rec)
    return rec


class Span(object):
    """Context manager created by obs.span(). After __exit__, `.seconds`
    holds the wall time. `.fields` may be mutated inside the span — the
    run-log record is emitted at exit."""
    __slots__ = ('name', 'fields', 'step_num', 'id', 'parent', 't0',
                 'seconds', '_trace', '_tinfo', '_entered')

    def __init__(self, name, step_num=None, **fields):
        self.name = name
        self.fields = fields
        self.step_num = step_num
        self.id = None
        self.parent = None
        self.t0 = None
        self.seconds = None
        self._trace = None
        self._tinfo = None
        self._entered = False

    def __enter__(self):
        st = _span_stack()
        self.parent = st[-1].id if st else None
        self.id = next(_span_ids)
        st.append(self)
        self._entered = True
        # when a distributed trace is active this span joins it (and
        # becomes the parent of anything opened inside) — no-op otherwise
        self._tinfo = trace._span_begin(self.name)
        if enabled():
            self._enter_trace()
        self.t0 = time.perf_counter()
        return self

    def _enter_trace(self):
        # Forward to the XLA trace ONLY via an already-imported jax: the
        # disabled-mode (and jax-less) contract is "no jax import", and
        # sys.modules.get never triggers one.
        jaxmod = sys.modules.get('jax')
        if jaxmod is None:
            return
        try:
            prof = jaxmod.profiler
            if self.step_num is not None:
                self._trace = prof.StepTraceAnnotation(
                    self.name, step_num=int(self.step_num))
            else:
                self._trace = prof.TraceAnnotation(self.name)
            self._trace.__enter__()
        except Exception:
            self._trace = None

    def __exit__(self, exc_type, exc, tb):
        self.seconds = time.perf_counter() - self.t0
        if self._trace is not None:
            try:
                self._trace.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._trace = None
        st = _span_stack()
        if self._entered and st and st[-1] is self:
            st.pop()
        elif self._entered and self in st:   # mis-nested exit; stay sane
            st.remove(self)
        self._entered = False
        h = _span_hists.get(self.name)
        if h is None:
            h = REGISTRY.histogram(self.name + '.seconds')
            _span_hists[self.name] = h
        h.observe(self.seconds)
        err = '%s: %s' % (exc_type.__name__, exc) if exc_type is not None \
            else None
        tids = None
        if self._tinfo is not None:
            trec = trace._span_end(self._tinfo, fields=dict(self.fields),
                                   error=err)
            self._tinfo = None
            tids = {'trace': trec['trace'], 'tspan': trec['span']}
            if trec.get('parent') is not None:
                tids['tparent'] = trec['parent']
        rl = _run_log()
        if rl is not None:
            fields = dict(self.fields)
            if err is not None:
                fields['error'] = err
            if self.step_num is not None:
                fields.setdefault('step_num', self.step_num)
            rec = {'ts': time.monotonic(), 'kind': 'span',
                   'name': self.name, 'span': self.id,
                   'parent': self.parent,
                   'dur_s': self.seconds, 'fields': fields}
            if tids:
                rec.update(tids)
            rl.write(rec)
        return False


def span(name, step_num=None, **fields):
    """Open a nested wall-time span. Always records `<name>.seconds` into
    the registry histogram; when observability is enabled it also appends
    a span record to the run log and brackets the region with
    jax.profiler.TraceAnnotation (StepTraceAnnotation when `step_num` is
    given), so Perfetto shows the same names the run log does."""
    return Span(name, step_num=step_num, **fields)


def span_record(name, seconds, **fields):
    """Record a span POST-HOC: the caller timed the region itself and only
    afterwards knows whether (and under which name) it should be recorded.
    The executor needs this for `executor.compile` — a first jitted call
    is timed, then classified as a real cold compile (span recorded) or a
    persistent-cache hit (an `executor.compile.persistent_hit` event
    instead), so a warm-cache restart shows ZERO compile spans. Feeds the
    same registry histogram and run-log span schema as span(); no trace
    annotation (the region is already over). Returns the record dict when
    written to the run log, else None."""
    seconds = float(seconds)
    h = _span_hists.get(name)
    if h is None:
        h = REGISTRY.histogram(name + '.seconds')
        _span_hists[name] = h
    h.observe(seconds)
    rl = _run_log()
    if rl is None:
        return None
    rec = {'ts': time.monotonic(), 'kind': 'span', 'name': name,
           'span': next(_span_ids), 'parent': current_span_id(),
           'dur_s': seconds, 'fields': dict(fields)}
    tids = trace._ids()
    if tids:
        rec.update(tids)
    rl.write(rec)
    return rec
