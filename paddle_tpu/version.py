"""Version metadata.

Parity: the reference generates python/paddle/version.py at build time
(python/setup.py.in) with full_version / major / minor / patch / rc /
istaged / commit / with_mkl; paddle/__init__.py imports full_version and
commit from it. Static here — there is no cmake build stamping.
"""
major = 0
minor = 14
patch = '0'
rc = 0
version = '0.14.0'
full_version = '0.14.0+tpu.r2'
commit = 'tpu-native-rebuild'
istaged = True
with_mkl = 'OFF'  # XLA:TPU is the backend; MKL-DNN paths do not exist


def show():
    if istaged:
        print('full_version:', full_version)
        print('major:', major)
        print('minor:', minor)
        print('patch:', patch)
        print('rc:', rc)
    else:
        print('commit:', commit)


def mkl():
    return with_mkl
