"""Datasets. Parity: reference python/paddle/dataset/.

Zero-egress environment: when the real files are absent locally
(~/.cache/paddle_tpu/dataset), each dataset falls back to a deterministic
synthetic generator with the same schema/shape/vocab so models and tests
run anywhere. Drop the official files into the cache dir to train on real
data.
"""
from . import common
from . import uci_housing
from . import mnist
from . import cifar
from . import imdb
from . import imikolov
from . import movielens
from . import wmt14
from . import wmt16
from . import conll05
from . import sentiment
from . import flowers
from . import voc2012
from . import mq2007

__all__ = ['common', 'uci_housing', 'mnist', 'cifar', 'imdb', 'imikolov',
           'movielens', 'wmt14', 'wmt16', 'conll05', 'sentiment', 'flowers',
           'voc2012', 'mq2007']
