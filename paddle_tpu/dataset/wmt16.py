"""WMT16 en-de (used by Transformer). Parity: reference python/paddle/dataset/wmt16.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'validation', 'get_dict', 'fetch',
           'convert']


def get_dict(lang, dict_size, reverse=False):
    d = {('w%d' % i): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic(n, tag, src_dict_size, trg_dict_size):
    rng = common.synthetic_rng('wmt16_' + tag)
    for _ in range(n):
        slen = int(rng.randint(4, 50))
        src = [int(w) for w in rng.randint(3, src_dict_size, size=slen)]
        trg = [max(3, (w * 3 + 11) % trg_dict_size) for w in src]
        yield src, [0] + trg, trg + [1]


def train(src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    def reader():
        for s in _synthetic(2048, 'train', src_dict_size, trg_dict_size):
            yield s
    return reader


def test(src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    def reader():
        for s in _synthetic(256, 'test', src_dict_size, trg_dict_size):
            yield s
    return reader


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    """reference wmt16.py:validation (held-out split)."""
    def reader():
        for s in _synthetic(256, 'valid', src_dict_size, trg_dict_size):
            yield s
    return reader


def fetch():
    """Zero-egress environment: nothing to download; synthetic data is
    generated on the fly (reference wmt16.py:fetch pre-downloads)."""
    return None


def convert(path, src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    """Serialize splits to recordio (reference wmt16.py:convert)."""
    common.convert(path, train(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_train")
    common.convert(path, test(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_test")
    common.convert(path, validation(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_validation")
