"""WMT16 en-de (used by Transformer). Parity: reference python/paddle/dataset/wmt16.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'get_dict']


def get_dict(lang, dict_size, reverse=False):
    d = {('w%d' % i): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic(n, tag, src_dict_size, trg_dict_size):
    rng = common.synthetic_rng('wmt16_' + tag)
    for _ in range(n):
        slen = int(rng.randint(4, 50))
        src = [int(w) for w in rng.randint(3, src_dict_size, size=slen)]
        trg = [max(3, (w * 3 + 11) % trg_dict_size) for w in src]
        yield src, [0] + trg, trg + [1]


def train(src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    def reader():
        for s in _synthetic(2048, 'train', src_dict_size, trg_dict_size):
            yield s
    return reader


def test(src_dict_size=10000, trg_dict_size=10000, src_lang='en'):
    def reader():
        for s in _synthetic(256, 'test', src_dict_size, trg_dict_size):
            yield s
    return reader
