"""PASCAL VOC2012 segmentation. Parity: reference python/paddle/dataset/voc2012.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'val']


def _reader(tag, n):
    def reader():
        rng = common.synthetic_rng('voc2012_' + tag)
        for _ in range(n):
            img = rng.rand(3, 128, 128).astype('float32')
            label = rng.randint(0, 21, size=(128, 128)).astype('int32')
            yield img, label
    return reader


def train():
    return _reader('train', 128)


def test():
    return _reader('test', 32)


def val():
    return _reader('val', 32)
