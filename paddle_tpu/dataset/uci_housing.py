"""UCI housing. Parity: reference python/paddle/dataset/uci_housing.py
(13 features -> price regression)."""
import os

import numpy as np

from . import common

__all__ = ['train', 'test', 'feature_range', 'convert']

URL = 'https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data'
MD5 = 'd4accdce7a25600298819f8e28e8d593'
feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None


def feature_range(maximums, minimums):
    pass


def _load():
    global UCI_TRAIN_DATA, UCI_TEST_DATA
    if UCI_TRAIN_DATA is not None:
        return
    path = common.download(URL, 'uci_housing', MD5)
    if path is not None and os.path.exists(path):
        data = np.fromfile(path, sep=' ')
        data = data.reshape(data.shape[0] // 14, 14)
    else:
        # synthetic: linear ground truth + noise, same shape/scale
        rng = common.synthetic_rng('uci_housing')
        n = 506
        x = rng.uniform(-1, 1, size=(n, 13))
        w = rng.uniform(-2, 2, size=(13,))
        y = x @ w + 0.1 * rng.randn(n) + 22.0
        data = np.concatenate([x, y[:, None]], axis=1)
    maximums, minimums, avgs = data.max(axis=0), data.min(axis=0), \
        data.sum(axis=0) / data.shape[0]
    for i in range(13):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * 0.8)
    UCI_TRAIN_DATA = data[:offset].astype('float32')
    UCI_TEST_DATA = data[offset:].astype('float32')


def train():
    _load()

    def reader():
        for d in UCI_TRAIN_DATA:
            yield d[:-1], d[-1:]
    return reader


def test():
    _load()

    def reader():
        for d in UCI_TEST_DATA:
            yield d[:-1], d[-1:]
    return reader


def convert(path):
    """Serialize train/test to recordio (reference uci_housing.py:convert,
    including its 'uci_houseing_test' prefix typo for name parity)."""
    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_houseing_test")
