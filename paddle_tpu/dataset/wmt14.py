"""WMT14 en-fr. Parity: reference python/paddle/dataset/wmt14.py
(src ids, trg ids, trg_next ids)."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'get_dict', 'convert', 'N']

N = 30000  # vocab size in reference's pruned dict


def _synthetic(n, tag, dict_size):
    rng = common.synthetic_rng('wmt14_' + tag)
    for _ in range(n):
        slen = int(rng.randint(4, 30))
        src = [int(w) for w in rng.randint(3, dict_size, size=slen)]
        # target = noisy "translation": shifted copy
        trg = [(w + 7) % dict_size for w in src[:max(2, slen - 2)]]
        trg = [max(3, w) for w in trg]
        yield src, [0] + trg, trg + [1]  # <s> trg, trg </s>


def train(dict_size=N):
    def reader():
        for s in _synthetic(2048, 'train', dict_size):
            yield s
    return reader


def test(dict_size=N):
    def reader():
        for s in _synthetic(256, 'test', dict_size):
            yield s
    return reader


def get_dict(dict_size, reverse=True):
    """reference wmt14.py:get_dict -> (src_dict, trg_dict); id->word when
    reverse (the reference default)."""
    d = {('w%d' % i): i for i in range(dict_size)}
    if reverse:
        d = {v: k for k, v in d.items()}
    return d, dict(d)


def convert(path):
    """Serialize train/test to recordio (reference wmt14.py:convert)."""
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
