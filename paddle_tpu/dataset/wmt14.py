"""WMT14 en-fr. Parity: reference python/paddle/dataset/wmt14.py
(src ids, trg ids, trg_next ids)."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'N']

N = 30000  # vocab size in reference's pruned dict


def _synthetic(n, tag, dict_size):
    rng = common.synthetic_rng('wmt14_' + tag)
    for _ in range(n):
        slen = int(rng.randint(4, 30))
        src = [int(w) for w in rng.randint(3, dict_size, size=slen)]
        # target = noisy "translation": shifted copy
        trg = [(w + 7) % dict_size for w in src[:max(2, slen - 2)]]
        trg = [max(3, w) for w in trg]
        yield src, [0] + trg, trg + [1]  # <s> trg, trg </s>


def train(dict_size=N):
    def reader():
        for s in _synthetic(2048, 'train', dict_size):
            yield s
    return reader


def test(dict_size=N):
    def reader():
        for s in _synthetic(256, 'test', dict_size):
            yield s
    return reader
