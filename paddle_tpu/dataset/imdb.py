"""IMDB sentiment. Parity: reference python/paddle/dataset/imdb.py
(word-id sequence, 0/1 label)."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'word_dict', 'build_dict', 'convert']

_VOCAB = 5147


def word_dict():
    return {('w%d' % i): i for i in range(_VOCAB)}


def _synthetic(n, tag):
    rng = common.synthetic_rng('imdb_' + tag)
    pos_words = np.arange(0, _VOCAB // 2)
    neg_words = np.arange(_VOCAB // 2, _VOCAB)
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 100))
        pool = pos_words if label else neg_words
        mix = rng.randint(0, _VOCAB, size=length)
        bias = pool[rng.randint(0, len(pool), size=length)]
        take = rng.rand(length) < 0.7
        seq = np.where(take, bias, mix).astype('int64')
        yield list(seq), label


def train(word_idx=None):
    def reader():
        for s in _synthetic(2048, 'train'):
            yield s
    return reader


def test(word_idx=None):
    def reader():
        for s in _synthetic(256, 'test'):
            yield s
    return reader


def build_dict(pattern=None, cutoff=None):
    """reference imdb.py:build_dict (word -> id); synthetic vocab here."""
    return word_dict()


def convert(path):
    """Serialize train/test to recordio (reference imdb.py:convert)."""
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
