"""MovieLens ratings. Parity: reference python/paddle/dataset/movielens.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
           'age_table', 'movie_categories', 'get_movie_title_dict']

age_table = [1, 18, 25, 35, 45, 50, 56]

_CATEGORIES = ['Action', 'Adventure', 'Animation', "Children's", 'Comedy',
               'Crime', 'Documentary', 'Drama', 'Fantasy', 'Film-Noir',
               'Horror', 'Musical', 'Mystery', 'Romance', 'Sci-Fi',
               'Thriller', 'War', 'Western']
_TITLE_WORDS = 5175


def movie_categories():
    return list(_CATEGORIES)


def get_movie_title_dict():
    return {('t%d' % i): i for i in range(_TITLE_WORDS)}


def max_user_id():
    return 6040


def max_movie_id():
    return 3952


def max_job_id():
    return 20


def _synthetic(n, tag):
    rng = common.synthetic_rng('movielens_' + tag)
    for _ in range(n):
        uid = int(rng.randint(1, 6041))
        gender = int(rng.randint(0, 2))
        age = int(rng.randint(0, 7))
        job = int(rng.randint(0, 21))
        mid = int(rng.randint(1, 3953))
        category = [int(rng.randint(0, len(_CATEGORIES)))]
        title = [int(rng.randint(0, _TITLE_WORDS)) for _ in range(3)]
        # learnable: rating is a (noisy) user-movie affinity, not pure noise
        base = 1 + (uid * 7 + mid * 13 + gender * 3) % 5
        score = float(np.clip(base + rng.randint(-1, 2), 1, 5))
        yield [uid, gender, age, job, mid, category, title, score]


def train():
    def reader():
        for s in _synthetic(4096, 'train'):
            yield s
    return reader


def test():
    def reader():
        for s in _synthetic(512, 'test'):
            yield s
    return reader
