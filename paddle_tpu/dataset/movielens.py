"""MovieLens ratings. Parity: reference python/paddle/dataset/movielens.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
           'age_table', 'movie_categories', 'get_movie_title_dict',
           'movie_info', 'user_info', 'MovieInfo', 'UserInfo', 'convert']

age_table = [1, 18, 25, 35, 45, 50, 56]

_CATEGORIES = ['Action', 'Adventure', 'Animation', "Children's", 'Comedy',
               'Crime', 'Documentary', 'Drama', 'Fantasy', 'Film-Noir',
               'Horror', 'Musical', 'Mystery', 'Romance', 'Sci-Fi',
               'Thriller', 'War', 'Western']
_TITLE_WORDS = 5175


def movie_categories():
    return list(_CATEGORIES)


def get_movie_title_dict():
    return {('t%d' % i): i for i in range(_TITLE_WORDS)}


def max_user_id():
    return 6040


def max_movie_id():
    return 3952


def max_job_id():
    return 20


def _synthetic(n, tag):
    rng = common.synthetic_rng('movielens_' + tag)
    for _ in range(n):
        uid = int(rng.randint(1, 6041))
        gender = int(rng.randint(0, 2))
        age = int(rng.randint(0, 7))
        job = int(rng.randint(0, 21))
        mid = int(rng.randint(1, 3953))
        category = [int(rng.randint(0, len(_CATEGORIES)))]
        title = [int(rng.randint(0, _TITLE_WORDS)) for _ in range(3)]
        # learnable: rating is a (noisy) user-movie affinity, not pure noise
        base = 1 + (uid * 7 + mid * 13 + gender * 3) % 5
        score = float(np.clip(base + rng.randint(-1, 2), 1, 5))
        yield [uid, gender, age, job, mid, category, title, score]


def train():
    def reader():
        for s in _synthetic(4096, 'train'):
            yield s
    return reader


def test():
    def reader():
        for s in _synthetic(512, 'test'):
            yield s
    return reader


class MovieInfo(object):
    """reference movielens.py:MovieInfo."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index, [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo(object):
    """reference movielens.py:UserInfo."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


CATEGORIES_DICT = {c: i for i, c in enumerate(movie_categories())}
MOVIE_TITLE_DICT = get_movie_title_dict()


def movie_info():
    """id -> MovieInfo for the synthetic catalog (reference
    movielens.py:movie_info)."""
    rng = common.synthetic_rng('movielens_catalog')
    out = {}
    for mid in range(1, max_movie_id() + 1):
        cats = [_CATEGORIES[int(rng.randint(0, len(_CATEGORIES)))]]
        title = ' '.join('t%d' % int(t)
                         for t in rng.randint(0, _TITLE_WORDS, size=3))
        out[mid] = MovieInfo(mid, cats, title)
    return out


def user_info():
    """id -> UserInfo for the synthetic users (reference
    movielens.py:user_info)."""
    rng = common.synthetic_rng('movielens_users')
    out = {}
    for uid in range(1, max_user_id() + 1):
        out[uid] = UserInfo(uid, 'M' if rng.rand() < 0.5 else 'F',
                            age_table[int(rng.randint(0, len(age_table)))],
                            int(rng.randint(0, max_job_id() + 1)))
    return out


def convert(path):
    """Serialize train/test to recordio (reference movielens.py:convert)."""
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
