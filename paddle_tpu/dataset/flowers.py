"""Oxford-102 flowers. Parity: reference python/paddle/dataset/flowers.py
(readers yield (3x224x224 float32 CHW image, int label); train applies the
random-crop/flip augmentation, test/valid the center-crop path, optionally
through the multiprocess-style xmap pipeline). Synthetic offline fallback:
raw samples are deterministic uint8 HWC 'photos' sized like real inputs so
the image.simple_transform augmentation is genuinely exercised."""
import functools

import numpy as np

from . import common, image
from .. import reader as paddle_reader

__all__ = ['train', 'test', 'valid']

_RAW_H, _RAW_W = 256, 320  # larger than crop so resize/crop paths do work


def default_mapper(is_train, sample):
    img, label = sample
    img = image.simple_transform(
        img, 256, 224, is_train, mean=[103.94, 116.78, 123.68])
    return img.flatten().astype('float32'), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def _raw_reader(tag, n):
    def reader():
        rng = common.synthetic_rng('flowers_' + tag)
        for _ in range(n):
            label = int(rng.randint(0, 102))
            img = (rng.rand(_RAW_H, _RAW_W, 3) * 255).astype('uint8')
            yield img, label
    return reader


def _reader_creator(tag, n, mapper, use_xmap, buffered_size):
    raw = _raw_reader(tag, n)
    if use_xmap:
        return paddle_reader.xmap_readers(mapper, raw, 4, buffered_size)
    return paddle_reader.map_readers(mapper, raw)


def train(use_xmap=True, mapper=train_mapper, buffered_size=1024,
          cycle=False):
    return _reader_creator('train', 512, mapper, use_xmap, buffered_size)


def test(use_xmap=True, mapper=test_mapper, buffered_size=1024, cycle=False):
    return _reader_creator('test', 64, mapper, use_xmap, buffered_size)


def valid(use_xmap=True, mapper=test_mapper, buffered_size=1024):
    return _reader_creator('valid', 64, mapper, use_xmap, buffered_size)
