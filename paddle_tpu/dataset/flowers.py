"""Oxford-102 flowers. Parity: reference python/paddle/dataset/flowers.py
(3x224x224 image, int label)."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'valid']


def _reader(tag, n, use_xmap=True):
    def reader():
        rng = common.synthetic_rng('flowers_' + tag)
        for _ in range(n):
            label = int(rng.randint(0, 102))
            img = rng.rand(3, 224, 224).astype('float32')
            yield img, label
    return reader


def train(use_xmap=True, mapper=None, buffered_size=1024, cycle=False):
    return _reader('train', 512)


def test(use_xmap=True, mapper=None, buffered_size=1024, cycle=False):
    return _reader('test', 64)


def valid(use_xmap=True, mapper=None, buffered_size=1024):
    return _reader('valid', 64)
