"""MNIST. Parity: reference python/paddle/dataset/mnist.py
(784-float image in [-1,1], int label)."""
import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ['train', 'test', 'convert']

TRAIN_IMAGE_URL = 'http://yann.lecun.com/exdb/mnist/train-images-idx3-ubyte.gz'
TRAIN_LABEL_URL = 'http://yann.lecun.com/exdb/mnist/train-labels-idx1-ubyte.gz'
TEST_IMAGE_URL = 'http://yann.lecun.com/exdb/mnist/t10k-images-idx3-ubyte.gz'
TEST_LABEL_URL = 'http://yann.lecun.com/exdb/mnist/t10k-labels-idx1-ubyte.gz'


def _parse_idx(img_path, lbl_path):
    with gzip.open(lbl_path, 'rb') as f:
        magic, n = struct.unpack('>II', f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img_path, 'rb') as f:
        magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return images, labels


def _synthetic(n, tag):
    """Class-conditional blobs: 10 fixed prototype digits + noise, so simple
    models genuinely learn separable structure."""
    rng = common.synthetic_rng('mnist_' + tag)
    protos = common.synthetic_rng('mnist_protos').uniform(
        -1, 1, size=(10, 784)).astype('float32')
    labels = rng.randint(0, 10, size=n).astype('int64')
    images = protos[labels] + 0.35 * rng.randn(n, 784).astype('float32')
    return np.clip(images, -1, 1).astype('float32'), labels


def _reader_creator(image_url, label_url, tag, n_synth):
    def reader():
        img_path = common.download(image_url, 'mnist', None)
        lbl_path = common.download(label_url, 'mnist', None)
        if img_path and lbl_path:
            images, labels = _parse_idx(img_path, lbl_path)
            images = images.astype('float32') / 127.5 - 1.0
            labels = labels.astype('int64')
        else:
            images, labels = _synthetic(n_synth, tag)
        for i in range(len(labels)):
            yield images[i], int(labels[i])
    return reader


def train():
    return _reader_creator(TRAIN_IMAGE_URL, TRAIN_LABEL_URL, 'train', 8192)


def test():
    return _reader_creator(TEST_IMAGE_URL, TEST_LABEL_URL, 'test', 1024)


def convert(path):
    """Serialize train/test to recordio (reference mnist.py:convert)."""
    common.convert(path, train(), 1000, "minist_train")
    common.convert(path, test(), 1000, "minist_test")
