"""PTB language model n-grams. Parity: reference python/paddle/dataset/imikolov.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'build_dict', 'convert']

_VOCAB = 2073


def build_dict(min_word_freq=50):
    return {('w%d' % i): i for i in range(_VOCAB)}


def _zipf_probs():
    # real PTB is Zipfian — frequent words dominate the loss, which is
    # what makes the book's cost<5 acceptance bar reachable by a
    # bottlenecked n-gram model (it need only master the head of the
    # distribution). A uniform vocab would demand a full-rank 2073x2073
    # transition table from a rank-256 softmax.
    ranks = np.arange(1, _VOCAB + 1, dtype=np.float64)
    p = 1.0 / ranks ** 1.1
    return p / p.sum()


def _synthetic(n, tag, ngram):
    rng = common.synthetic_rng('imikolov_' + tag)
    probs = _zipf_probs()
    # markov-ish chains so the n-gram task is learnable
    trans = common.synthetic_rng('imikolov_trans').choice(
        _VOCAB, size=(_VOCAB,), p=probs)
    for _ in range(n):
        w = [int(rng.choice(_VOCAB, p=probs))]
        for _ in range(ngram - 1):
            nxt = int(trans[w[-1]]) if rng.rand() < 0.8 \
                else int(rng.choice(_VOCAB, p=probs))
            w.append(nxt)
        yield tuple(w)


def train(word_idx=None, n=5):
    def reader():
        for s in _synthetic(4096, 'train', n):
            yield s
    return reader


def test(word_idx=None, n=5):
    def reader():
        for s in _synthetic(512, 'test', n):
            yield s
    return reader


def convert(path):
    """Serialize train/test n-grams to recordio (reference imikolov.py)."""
    N = 5
    word_dict = build_dict()
    common.convert(path, train(word_dict, N), 1000, "imikolov_train")
    common.convert(path, test(word_dict, N), 1000, "imikolov_test")
