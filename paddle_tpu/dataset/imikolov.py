"""PTB language model n-grams. Parity: reference python/paddle/dataset/imikolov.py."""
import numpy as np
from . import common

__all__ = ['train', 'test', 'build_dict', 'convert']

_VOCAB = 2073


def build_dict(min_word_freq=50):
    return {('w%d' % i): i for i in range(_VOCAB)}


def _synthetic(n, tag, ngram):
    rng = common.synthetic_rng('imikolov_' + tag)
    # markov-ish chains so the n-gram task is learnable
    trans = common.synthetic_rng('imikolov_trans').randint(
        0, _VOCAB, size=(_VOCAB,))
    for _ in range(n):
        w = [int(rng.randint(0, _VOCAB))]
        for _ in range(ngram - 1):
            nxt = int(trans[w[-1]]) if rng.rand() < 0.8 else int(rng.randint(0, _VOCAB))
            w.append(nxt)
        yield tuple(w)


def train(word_idx=None, n=5):
    def reader():
        for s in _synthetic(4096, 'train', n):
            yield s
    return reader


def test(word_idx=None, n=5):
    def reader():
        for s in _synthetic(512, 'test', n):
            yield s
    return reader


def convert(path):
    """Serialize train/test n-grams to recordio (reference imikolov.py)."""
    N = 5
    word_dict = build_dict()
    common.convert(path, train(word_dict, N), 1000, "imikolov_train")
    common.convert(path, test(word_dict, N), 1000, "imikolov_test")
