"""Movie-review sentiment. Parity: reference python/paddle/dataset/sentiment.py."""
from . import imdb

__all__ = ['train', 'test', 'get_word_dict']


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb.train()


def test():
    return imdb.test()
