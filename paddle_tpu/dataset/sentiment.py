"""Movie-review sentiment. Parity: reference python/paddle/dataset/sentiment.py."""
from . import imdb

__all__ = ['train', 'test', 'get_word_dict', 'convert']


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb.train()


def test():
    return imdb.test()


def convert(path):
    """Serialize train/test to recordio (reference sentiment.py:convert)."""
    from . import common  # sentiment has no top-level common import
    common.convert(path, train(), 1000, "sentiment_train")
    common.convert(path, test(), 1000, "sentiment_test")
