"""CoNLL-2005 SRL. Parity: reference python/paddle/dataset/conll05.py."""
import numpy as np
from . import common

__all__ = ['get_dict', 'get_embedding', 'train', 'test', 'convert']

_WORD, _VERB, _LABEL = 44068, 3162, 59


def get_dict():
    word_dict = {('w%d' % i): i for i in range(_WORD)}
    verb_dict = {('v%d' % i): i for i in range(_VERB)}
    label_dict = {('l%d' % i): i for i in range(_LABEL)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = common.synthetic_rng('conll05_emb')
    return rng.uniform(-1, 1, size=(_WORD, 32)).astype('float32')


def _synthetic(n, tag):
    """9 slots like the real corpus sample layout (word, ctx_n2, ctx_n1,
    ctx_0, ctx_p1, ctx_p2, verb, mark, target). The target is a noisy
    function of (word, mark) so the SRL tagger has signal to learn."""
    rng = common.synthetic_rng('conll05_' + tag)
    for _ in range(n):
        slen = int(rng.randint(5, 40))
        word = rng.randint(0, _WORD, size=slen)
        ctxs = [np.roll(word, k) for k in (2, 1, 0, -1, -2)]
        verb = [int(rng.randint(0, _VERB))] * slen
        mark = rng.randint(0, 2, size=slen)
        noise = rng.randint(0, _LABEL, size=slen)
        label = np.where(rng.rand(slen) < 0.8,
                         (word % (_LABEL // 2)) + mark * (_LABEL // 2),
                         noise)
        yield tuple([[int(v) for v in word]]
                    + [[int(v) for v in c] for c in ctxs]
                    + [verb, [int(v) for v in mark],
                       [int(v) for v in label]])


def train():
    def reader():
        for s in _synthetic(1024, 'train'):
            yield s
    return reader


def test():
    def reader():
        for s in _synthetic(256, 'test'):
            yield s
    return reader


def convert(path):
    """Serialize the test split to recordio (reference conll05.py:convert)."""
    common.convert(path, test(), 1000, "conl105_test")
