"""CoNLL-2005 SRL. Parity: reference python/paddle/dataset/conll05.py."""
import numpy as np
from . import common

__all__ = ['get_dict', 'get_embedding', 'train', 'test', 'convert']

_WORD, _VERB, _LABEL = 44068, 3162, 59


def get_dict():
    word_dict = {('w%d' % i): i for i in range(_WORD)}
    verb_dict = {('v%d' % i): i for i in range(_VERB)}
    label_dict = {('l%d' % i): i for i in range(_LABEL)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Path to the pretrained word-embedding FILE (reference
    conll05.get_embedding downloads one and returns its path; book code
    opens it with a 16-byte header then raw float32 — test_label_
    semantic_roles.py load_parameter). Synthetic equivalent: written once
    to the dataset cache dir in the same binary layout."""
    import os
    path = os.path.join(common.DATA_HOME, 'conll05_emb.bin')
    if not os.path.exists(path):
        rng = common.synthetic_rng('conll05_emb')
        emb = rng.uniform(-1, 1, size=(_WORD, 32)).astype('float32')
        tmp = path + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(b'\0' * 16)  # header, skipped by readers
            emb.tofile(f)
        os.replace(tmp, path)
    return path


def _synthetic(n, tag):
    """9 slots like the real corpus sample layout (word, ctx_n2, ctx_n1,
    ctx_0, ctx_p1, ctx_p2, verb, mark, target). The target is a noisy
    function of (word, mark) so the SRL tagger has signal to learn."""
    rng = common.synthetic_rng('conll05_' + tag)
    for _ in range(n):
        # 5..20 tokens: the book's acceptance bar is an ABSOLUTE batch
        # cost (<60) and CRF NLL scales with sequence length — a wide
        # length range makes the per-batch cost so variable that crossing
        # the bar depends on shuffle luck rather than learning
        slen = int(rng.randint(5, 21))
        word = rng.randint(0, _WORD, size=slen)
        ctxs = [np.roll(word, k) for k in (2, 1, 0, -1, -2)]
        verb = [int(rng.randint(0, _VERB))] * slen
        mark = rng.randint(0, 2, size=slen)
        # low-entropy target, 3% noise: the reference book trains to a CI
        # bar of batch cost < 60 (~2.7 nats/token) within ~260 SGD
        # batches (test_label_semantic_roles.py) — the synthetic task
        # must be reachable in that budget. 6 effective labels from
        # (word % 3, mark) keep the NLL floor ~0.25 nats/token while
        # still exercising the full 59-label CRF machinery.
        noise = rng.randint(0, _LABEL, size=slen)
        label = np.where(rng.rand(slen) < 0.97,
                         (word % 3) + mark * 3,
                         noise)
        yield tuple([[int(v) for v in word]]
                    + [[int(v) for v in c] for c in ctxs]
                    + [verb, [int(v) for v in mark],
                       [int(v) for v in label]])


def train():
    def reader():
        for s in _synthetic(1024, 'train'):
            yield s
    return reader


def test():
    def reader():
        # 768 samples: the reference book trains its CRF on THIS set
        # (test_label_semantic_roles.py train_data uses conll05.test())
        # for up to 10 passes — the sample count bounds its SGD budget
        for s in _synthetic(768, 'test'):
            yield s
    return reader


def convert(path):
    """Serialize the test split to recordio (reference conll05.py:convert)."""
    common.convert(path, test(), 1000, "conl105_test")
