"""CoNLL-2005 SRL. Parity: reference python/paddle/dataset/conll05.py."""
import numpy as np
from . import common

__all__ = ['get_dict', 'get_embedding', 'test']

_WORD, _VERB, _LABEL = 44068, 3162, 59


def get_dict():
    word_dict = {('w%d' % i): i for i in range(_WORD)}
    verb_dict = {('v%d' % i): i for i in range(_VERB)}
    label_dict = {('l%d' % i): i for i in range(_LABEL)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = common.synthetic_rng('conll05_emb')
    return rng.uniform(-1, 1, size=(_WORD, 32)).astype('float32')


def _synthetic(n, tag):
    rng = common.synthetic_rng('conll05_' + tag)
    for _ in range(n):
        slen = int(rng.randint(5, 40))
        word = [int(w) for w in rng.randint(0, _WORD, size=slen)]
        ctx = [int(w) for w in rng.randint(0, _WORD, size=slen)]
        verb = [int(rng.randint(0, _VERB))] * slen
        mark = [int(m) for m in rng.randint(0, 2, size=slen)]
        label = [int(l) for l in rng.randint(0, _LABEL, size=slen)]
        yield word, ctx, ctx, ctx, ctx, verb, mark, label


def test():
    def reader():
        for s in _synthetic(256, 'test'):
            yield s
    return reader
