"""CIFAR-10/100. Parity: reference python/paddle/dataset/cifar.py
(3072-float image in [0,1], int label)."""
import numpy as np
from . import common

__all__ = ['train10', 'test10', 'train100', 'test100', 'convert']


def _synthetic(n, num_classes, tag):
    rng = common.synthetic_rng('cifar_' + tag + str(num_classes))
    protos = common.synthetic_rng('cifar_protos' + str(num_classes)).uniform(
        0, 1, size=(num_classes, 3072)).astype('float32')
    labels = rng.randint(0, num_classes, size=n).astype('int64')
    images = protos[labels] + 0.15 * rng.randn(n, 3072).astype('float32')
    return np.clip(images, 0, 1).astype('float32'), labels


def _reader_creator(tag, num_classes, n):
    def reader():
        images, labels = _synthetic(n, num_classes, tag)
        for i in range(len(labels)):
            yield images[i], int(labels[i])
    return reader


def train10():
    return _reader_creator('train', 10, 4096)


def test10():
    return _reader_creator('test', 10, 512)


def train100():
    return _reader_creator('train', 100, 4096)


def test100():
    return _reader_creator('test', 100, 512)


def convert(path):
    """Serialize all four splits to recordio (reference cifar.py:convert,
    same shard prefixes)."""
    common.convert(path, train100(), 1000, "cifar_train100")
    common.convert(path, test100(), 1000, "cifar_test100")
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
