"""Image loading + augmentation pipeline (resize / crop / flip / normalize).

Parity: reference ``python/paddle/dataset/image.py`` (load_image,
resize_short, to_chw, center_crop, random_crop, left_right_flip,
simple_transform, load_and_transform, batch_images_from_tar). The reference
is cv2-backed; this build decodes with PIL when present and performs all
array transforms in pure vectorized numpy, so the augmentation path has no
hard native-image dependency and a fixed output dtype/layout suitable for
feeding the TPU input pipeline (CHW float32, optionally mean-subtracted).
"""
import os
import tarfile

import numpy as np

try:  # decode-only dependency; array math below never needs it
    from PIL import Image as _PILImage
except Exception:  # pragma: no cover - PIL is present in this image
    _PILImage = None

__all__ = [
    'load_image_bytes', 'load_image', 'resize_short', 'to_chw', 'center_crop',
    'random_crop', 'left_right_flip', 'simple_transform',
    'simple_transform_batch', 'load_and_transform', 'batch_images_from_tar'
]


def _require_pil():
    if _PILImage is None:
        raise ImportError(
            'PIL is required to decode image files; array-based transforms '
            '(resize_short/center_crop/...) work without it.')


def load_image_bytes(data, is_color=True):
    """Decode an encoded image byte string to an HWC (color) or HW (gray)
    uint8 ndarray."""
    import io
    _require_pil()
    img = _PILImage.open(io.BytesIO(data))
    img = img.convert('RGB' if is_color else 'L')
    return np.asarray(img)


def load_image(file, is_color=True):
    """Load an image file into an HWC uint8 ndarray (HW when gray)."""
    with open(file, 'rb') as f:
        return load_image_bytes(f.read(), is_color=is_color)


def _bilinear_resize(im, out_h, out_w):
    """Vectorized numpy bilinear resize of an HW[C] array (align_corners
    false / half-pixel centers, matching common image-library semantics)."""
    h, w = im.shape[:2]
    if (h, w) == (out_h, out_w):
        return im
    squeeze = im.ndim == 2
    arr = im[:, :, None].astype(np.float32) if squeeze else im.astype(np.float32)

    ys = (np.arange(out_h, dtype=np.float32) + 0.5) * (h / out_h) - 0.5
    xs = (np.arange(out_w, dtype=np.float32) + 0.5) * (w / out_w) - 0.5
    y0 = np.clip(np.floor(ys), 0, h - 1).astype(np.int64)
    x0 = np.clip(np.floor(xs), 0, w - 1).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]

    top = arr[y0][:, x0] * (1 - wx) + arr[y0][:, x1] * wx
    bot = arr[y1][:, x0] * (1 - wx) + arr[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(im.dtype, np.integer):
        out = np.clip(np.rint(out), np.iinfo(im.dtype).min,
                      np.iinfo(im.dtype).max)
    out = out.astype(im.dtype)
    return out[:, :, 0] if squeeze else out


def resize_short(im, size):
    """Resize so the shorter edge equals ``size``, preserving aspect."""
    h, w = im.shape[:2]
    if h > w:
        out_h, out_w = int(round(h * size / float(w))), size
    else:
        out_h, out_w = size, int(round(w * size / float(h)))
    return _bilinear_resize(im, out_h, out_w)


def to_chw(im, order=(2, 0, 1)):
    """Transpose an HWC image to CHW (or any given axis order)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop a ``size x size`` window from the image center."""
    h, w = im.shape[:2]
    h0 = (h - size) // 2
    w0 = (w - size) // 2
    if len(im.shape) == 3 and is_color:
        return im[h0:h0 + size, w0:w0 + size, :]
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    """Crop a ``size x size`` window at a uniformly random offset."""
    h, w = im.shape[:2]
    h0 = np.random.randint(0, h - size + 1)
    w0 = np.random.randint(0, w - size + 1)
    if len(im.shape) == 3 and is_color:
        return im[h0:h0 + size, w0:w0 + size, :]
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    """Mirror the image horizontally."""
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """The standard train/eval augmentation: shorter-edge resize, then
    random crop + 50% flip (train) or center crop (eval), CHW float32,
    optional per-channel or elementwise mean subtraction."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)

    im = im.astype('float32')
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform in one call (reader mapper helper)."""
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train, is_color,
                            mean)


def simple_transform_batch(images, resize_size, crop_size, is_train,
                           mean=None, seed=0):
    """simple_transform over a whole same-sized [n, h, w, c] uint8 batch.

    Uses the multithreaded C++ kernel (csrc/image_aug.cpp) when built —
    the host-side hot loop of the imagenet-style input pipeline — and
    falls back to the per-image numpy path otherwise. Train-mode crops and
    flips draw from `seed` deterministically per image."""
    from ..utils import native
    out = native.image_transform_batch(images, resize_size, crop_size,
                                       is_train, mean=mean, seed=seed)
    if out is not None:
        return out
    # numpy fallback: deterministic per (seed, i) like the kernel (crop
    # positions differ between backends; determinism holds within each)
    outs = []
    for i, im in enumerate(np.asarray(images)):
        rng = np.random.RandomState((int(seed) * 1000003 + i) % (2 ** 31))
        im = resize_short(im, resize_size)
        h, w = im.shape[:2]
        if is_train:
            y0 = int(rng.randint(0, h - crop_size + 1))
            x0 = int(rng.randint(0, w - crop_size + 1))
            im = im[y0:y0 + crop_size, x0:x0 + crop_size]
            if rng.randint(2) == 0:
                im = left_right_flip(im)
        else:
            im = center_crop(im, crop_size)
        im = to_chw(im).astype('float32') if im.ndim == 3 \
            else im.astype('float32')
        if mean is not None:
            m = np.array(mean, dtype=np.float32)
            if m.ndim == 1 and m.shape[0] == im.shape[0]:
                m = m[:, np.newaxis, np.newaxis]
            im = im - m
        outs.append(im)
    return np.stack(outs)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pre-decode a tar of images into .npz batch files + a meta list.

    Reference writes pickled {data, label} blobs; here each batch is a
    compressed npz (data: [N] object array of encoded bytes, label: [N]
    int64) which round-trips without pickle. Returns the meta file path.
    """
    out_path = "%s/%s_%s" % (os.path.dirname(data_file), dataset_name, 'batch')
    if os.path.exists(out_path):
        return out_path + "/batch_file_list.txt"
    os.makedirs(out_path)

    tf = tarfile.open(data_file)
    names = [n for n in tf.getnames() if n in img2label]
    data, labels, file_id = [], [], 0
    names_written = []

    def _flush():
        nonlocal data, labels, file_id
        if not data:
            return
        fname = "%s/batch_%d.npz" % (out_path, file_id)
        np.savez_compressed(
            fname,
            data=np.array(data, dtype=object),
            label=np.array(labels, dtype=np.int64))
        names_written.append(fname)
        data, labels = [], []
        file_id += 1

    for name in names:
        data.append(tf.extractfile(name).read())
        labels.append(img2label[name])
        if len(data) == num_per_batch:
            _flush()
    _flush()

    meta = out_path + "/batch_file_list.txt"
    with open(meta, 'w') as f:
        f.write('\n'.join(names_written))
    return meta
