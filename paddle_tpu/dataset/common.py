"""Dataset plumbing. Parity: reference python/paddle/dataset/common.py."""
import hashlib
import os

import numpy as np

__all__ = ['DATA_HOME', 'download', 'md5file', 'data_path', 'synthetic_rng']

DATA_HOME = os.path.expanduser('~/.cache/paddle_tpu/dataset')


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


must_mkdirs(DATA_HOME)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def data_path(module_name, filename):
    return os.path.join(DATA_HOME, module_name, filename)


def download(url, module_name, md5sum, save_name=None):
    """Zero-egress: never fetches. Returns the cache path if the file was
    pre-seeded, else None (callers fall back to synthetic data)."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(dirname,
                            save_name or url.split('/')[-1])
    if os.path.exists(filename):
        return filename
    return None


def synthetic_rng(tag, seed=1234):
    """Deterministic per-dataset RNG for synthetic fallbacks."""
    h = int(hashlib.md5(tag.encode()).hexdigest()[:8], 16)
    return np.random.RandomState((seed + h) % (2 ** 31))
