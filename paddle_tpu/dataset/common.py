"""Dataset plumbing. Parity: reference python/paddle/dataset/common.py."""
import hashlib
import os

import numpy as np

__all__ = ['DATA_HOME', 'download', 'md5file', 'data_path', 'synthetic_rng',
           'split', 'cluster_files_reader', 'convert']

DATA_HOME = os.path.expanduser('~/.cache/paddle_tpu/dataset')


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


must_mkdirs(DATA_HOME)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def data_path(module_name, filename):
    return os.path.join(DATA_HOME, module_name, filename)


def download(url, module_name, md5sum, save_name=None, fetcher=None,
             retries=3, deadline=None, _sleep=None):
    """Zero-egress by default: with no `fetcher`, returns the cache path
    if the file was pre-seeded, else None (callers fall back to synthetic
    data).

    fetcher(url, dest_path): optional transport hook (an environment that
    IS allowed egress, or a test harness). It runs under
    utils.retry.retry_call — exponential backoff + jitter, bounded
    attempts, optional wall-clock deadline — and each attempt's result is
    md5-verified before the atomic rename into the cache, so a torn or
    corrupted transfer is retried instead of poisoning the cache."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(dirname,
                            save_name or url.split('/')[-1])
    if os.path.exists(filename):
        return filename
    if fetcher is None:
        return None

    from ..utils.retry import retry_call

    def attempt():
        tmp = filename + '.part'
        try:
            fetcher(url, tmp)
            if md5sum is not None and md5file(tmp) != md5sum:
                raise IOError(
                    'download %r: md5 mismatch (corrupted transfer)' % url)
            os.replace(tmp, filename)  # atomic: cache never holds a tear
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return filename

    import time
    return retry_call(attempt, retries=retries, deadline=deadline,
                      retry_on=(IOError, OSError),
                      sleep=time.sleep if _sleep is None else _sleep,
                      describe='download %r' % url)


def synthetic_rng(tag, seed=1234):
    """Deterministic per-dataset RNG for synthetic fallbacks."""
    h = int(hashlib.md5(tag.encode()).hexdigest()[:8], 16)
    return np.random.RandomState((seed + h) % (2 ** 31))


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Shard a reader's samples into files of line_count samples each
    (reference common.py:split; binary pickle by default)."""
    import pickle
    if dumper is None:
        dumper = pickle.dump
    if not callable(dumper):
        raise TypeError("dumper should be callable.")
    lines = []
    indx_f = 0
    for d in reader():
        lines.append(d)
        if len(lines) == line_count:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)
    return indx_f + (1 if lines else 0)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Round-robin the files produced by split() across trainers
    (reference common.py:cluster_files_reader)."""
    import glob
    import pickle
    if loader is None:
        loader = pickle.load

    def reader():
        if not callable(loader):
            raise TypeError("loader should be callable.")
        file_list = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(file_list):
            if idx % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for line in loader(f):
                        yield line

    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Serialize a reader into sharded recordio files
    `<output_path>/<name_prefix>-00000...` of line_count samples each
    (reference common.py:convert; backed by our C++-format recordio
    writer, one pickled sample per record)."""
    import pickle
    from ..reader.recordio import RecordIOWriter
    if line_count < 1:
        raise ValueError("line_count must be >= 1, got %r" % (line_count,))
    indx_f = 0
    written = 0

    def write_shard(idx, lines):
        filename = "%s/%s-%05d" % (output_path, name_prefix, idx)
        with RecordIOWriter(filename) as w:
            for l in lines:
                w.write(pickle.dumps(l, pickle.HIGHEST_PROTOCOL))

    lines = []
    for d in reader():
        lines.append(d)
        if len(lines) == line_count:
            write_shard(indx_f, lines)
            written += len(lines)
            lines = []
            indx_f += 1
    if lines:
        write_shard(indx_f, lines)
        written += len(lines)
    return written
