"""MQ2007 learning-to-rank. Parity: reference python/paddle/dataset/mq2007.py."""
import numpy as np
from . import common

__all__ = ['train', 'test']

_FEATS = 46


def _reader(tag, n, format):
    def reader():
        rng = common.synthetic_rng('mq2007_' + tag)
        w = common.synthetic_rng('mq2007_w').randn(_FEATS)
        for _ in range(n):
            if format == 'pairwise':
                a = rng.rand(_FEATS).astype('float32')
                b = rng.rand(_FEATS).astype('float32')
                # label implied by latent scorer
                if float(a @ w) >= float(b @ w):
                    yield a, b
                else:
                    yield b, a
            else:
                x = rng.rand(_FEATS).astype('float32')
                score = float(x @ w)
                label = float(np.clip(round(score + 1.5), 0, 2))
                yield label, x
    return reader


def train(format='pairwise'):
    return _reader('train', 2048, format)


def test(format='pairwise'):
    return _reader('test', 256, format)
