"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: TeggyYang/Paddle @ /root/reference).

Compute path: the Fluid-compatible Program IR lowers through a registry of
JAX rules into single fused XLA modules (jit/pjit over jax.sharding.Mesh);
hot kernels in paddle_tpu.ops use pallas. Parallelism (dp/tp/sp) is GSPMD
over the ICI mesh rather than NCCL/pserver.
"""
from .version import full_version as __version__  # noqa: E402
from .version import commit as __git_commit__  # noqa: E402

from . import obs  # noqa: F401  (stdlib-only; must precede fluid, which
#                                  instruments its hot paths through it)
from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import compat  # noqa: F401
from .batch import batch  # noqa: F401

__all__ = ['fluid', 'obs', 'reader', 'dataset', 'compat', 'batch',
           'install_as_paddle']


def install_as_paddle():
    """Alias this package as `paddle` so REFERENCE scripts run unmodified
    (`import paddle.fluid as fluid`, `from paddle.fluid.executor import
    Executor`, ...).

    Every already-imported `paddle_tpu.*` module is registered under the
    matching `paddle.*` name, and a meta-path finder resolves FUTURE
    `paddle.*` imports to the SAME module objects. The finder matters:
    without it, `import paddle.fluid.executor` would load a SECOND copy of
    executor.py through the package __path__, and isinstance checks
    (SeqValue, Variable) would silently fail across the two copies —
    values feed as dtype=object garbage instead of sequences.

    Raises RuntimeError if a DIFFERENT module named `paddle` is already
    imported (silently shadowing a real PaddlePaddle would be worse than
    failing loudly). Used by tests/test_reference_book_compat.py to run
    the reference's own book tests verbatim."""
    import importlib
    import importlib.abc
    import importlib.machinery
    import sys

    existing = sys.modules.get('paddle')
    if existing is not None and existing is not sys.modules[__name__]:
        raise RuntimeError(
            'a different `paddle` module is already imported; '
            'install_as_paddle() would shadow it')

    class _AliasLoader(importlib.abc.Loader):
        def __init__(self, module):
            self._module = module

        def create_module(self, spec):
            return self._module

        def exec_module(self, module):
            pass  # already executed under its paddle_tpu.* name

    class _AliasFinder(importlib.abc.MetaPathFinder):
        def find_spec(self, fullname, path=None, target=None):
            if fullname != 'paddle' and not fullname.startswith('paddle.'):
                return None
            real = __name__ + fullname[len('paddle'):]
            try:
                mod = importlib.import_module(real)
            except ImportError:
                return None
            return importlib.machinery.ModuleSpec(
                fullname, _AliasLoader(mod), is_package=hasattr(mod,
                                                                '__path__'))

    for name in list(sys.modules):
        if name == __name__ or name.startswith(__name__ + '.'):
            alias = 'paddle' + name[len(__name__):]
            sys.modules[alias] = sys.modules[name]
    if not any(getattr(f, '_paddle_tpu_alias', False) for f in sys.meta_path):
        finder = _AliasFinder()
        finder._paddle_tpu_alias = True
        sys.meta_path.insert(0, finder)
