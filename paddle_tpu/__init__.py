"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference: TeggyYang/Paddle @ /root/reference).

Compute path: the Fluid-compatible Program IR lowers through a registry of
JAX rules into single fused XLA modules (jit/pjit over jax.sharding.Mesh);
hot kernels in paddle_tpu.ops use pallas. Parallelism (dp/tp/sp) is GSPMD
over the ICI mesh rather than NCCL/pserver.
"""
from .version import full_version as __version__  # noqa: E402
from .version import commit as __git_commit__  # noqa: E402

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import compat  # noqa: F401
from .batch import batch  # noqa: F401

__all__ = ['fluid', 'reader', 'dataset', 'compat', 'batch']
