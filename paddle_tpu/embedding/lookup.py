"""The sharded-lookup wire: bucket -> dedup -> all_to_all -> gather -> return.

TPU-native rebuild of the reference's pserver `lookup_table` dispatch
(distribute_transpiler.py split table rows across parameter servers and
issued gRPC prefetch per shard). Here the table is row-sharded over ONE
mesh axis (`ParamAttr(sharding=(axis, None))`) and a lookup is a fixed
four-beat exchange inside a shard_map, the same machinery as
parallel/moe.py's expert dispatch:

  1. bucket  — each shard takes its slice of the flattened id vector and
               computes, per id, the owning shard (id // rows_per_shard);
  2. dedup   — ids are sorted and duplicates collapse onto one wire slot
               (the MergeAdd idea applied to the QUERY side: a hot id
               crosses the ICI once per shard, not once per occurrence);
  3. exchange— ONE lax.all_to_all ships each shard's per-owner query
               buckets; owners gather their local rows; a second
               all_to_all ships the rows back (the moe send/recv pattern,
               parallel/moe.py:165);
  4. return  — rows fan back out over the duplicate map and unsort into
               request order.

Static shapes throughout: per-shard query capacity is ceil(n/ws) ids and
the wire buffers are [ws, cap] / [ws, cap, D] — worst case (every id owned
by one shard) still fits, so unlike MoE packing NOTHING is ever dropped;
dedup narrows the rows actually gathered, not the buffer. All functions
are pure JAX, usable directly or through the `lookup_table` op
(ops_impl/embedding_ops.py). See docs/embedding.md for the wire diagram.
"""
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['sharded_lookup', 'dedup_plan', 'pad_vocab', 'wire_stats']

# sentinel sorted past every real id so padded query slots never start a
# dedup segment or perturb a real bucket (int32-safe)
_PAD_ID = jnp.iinfo(jnp.int32).max // 2


def pad_vocab(vocab, axis_size):
    """Round a vocab size up to a multiple of the mesh axis so the table
    row-shards evenly (the analysis pass rejects untileable tables —
    EmbeddingShardUntileable). The padding rows are never looked up; their
    optimizer state stays zero under the sparse path."""
    vocab, axis_size = int(vocab), int(axis_size)
    return ((vocab + axis_size - 1) // axis_size) * axis_size


def dedup_plan(ids, valid=None):
    """Collapse duplicate ids onto shared slots (static shapes).

    Returns (uids, seg, order, n_unique):
      uids     int32[c] — unique ids compacted to the front (slots past
                          n_unique hold the _PAD_ID sentinel);
      seg      int32[c] — for each SORTED position, its unique slot;
      order    int32[c] — argsort(ids): sorted position i holds request
                          order[i] (unsort via zeros.at[order].set(...));
      n_unique int32[]  — live unique count.
    `valid` masks padded query slots (they sort last via _PAD_ID and never
    open a segment)."""
    c = ids.shape[0]
    if valid is None:
        valid = jnp.ones((c,), bool)
    keyed = jnp.where(valid, ids, _PAD_ID)
    order = jnp.argsort(keyed)
    sid = keyed[order]
    svalid = valid[order]
    is_first = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]]) & svalid
    seg = jnp.cumsum(is_first) - 1                    # [c] slot per sorted pos
    # min-scatter: within a real segment every sid is equal, and invalid
    # tails carry the sentinel, which can never undercut a real id
    uids = jnp.full((c,), _PAD_ID, jnp.int32).at[seg].min(
        sid.astype(jnp.int32))
    return uids, seg, order, jnp.sum(is_first)


def _pack_queries(uids, n_unique, ws, rows_per_shard):
    """Bucket unique ids by owning shard into the [ws, c] wire buffer
    (the moe cumsum-slot pack, parallel/moe.py pack_topk — capacity c
    means nothing ever drops). Returns (send_ids, send_valid, owner, slot)
    with owner/slot the return map for the rows coming back."""
    c = uids.shape[0]
    valid_u = jnp.arange(c) < n_unique
    owner = jnp.clip(uids // rows_per_shard, 0, ws - 1)
    onehot = jax.nn.one_hot(owner, ws, dtype=jnp.int32) * \
        valid_u[:, None].astype(jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot         # 1-based within owner
    slot = jnp.sum(pos, axis=-1) - 1                  # [c]
    # scatter-add: (owner, slot) pairs are unique for live queries by the
    # cumsum construction; dead slots all add zeros at (0, 0)
    o = jnp.where(valid_u, owner, 0)
    s = jnp.where(valid_u, slot, 0)
    send_ids = jnp.zeros((ws, c), jnp.int32).at[o, s].add(
        jnp.where(valid_u, uids, 0))
    send_valid = jnp.zeros((ws, c), jnp.int32).at[o, s].add(
        valid_u.astype(jnp.int32)) > 0
    return send_ids, send_valid, owner, slot


def _exchange(w_local, send_ids, send_valid, axis):
    """The two all_to_alls around the local gather. Device j receives
    every peer's query bucket for j's row block, answers from its local
    shard, and ships the rows back in the same [ws, cap] layout."""
    ws, cap = send_ids.shape
    rows_local = w_local.shape[0]
    base = lax.axis_index(axis) * rows_local
    recv_ids = lax.all_to_all(send_ids, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    recv_valid = lax.all_to_all(send_valid, axis, split_axis=0,
                                concat_axis=0, tiled=True)
    local_idx = jnp.clip(recv_ids - base, 0, rows_local - 1)
    rows = jnp.where(recv_valid[..., None],
                     w_local[local_idx], 0).astype(w_local.dtype)
    return lax.all_to_all(rows, axis, split_axis=0, concat_axis=0,
                          tiled=True)                 # [ws, cap, D]


def _shard_body(axis, ws):
    def body(w_local, ids_local, valid_local):
        rows_per_shard = w_local.shape[0]
        uids, seg, order, n_unique = dedup_plan(ids_local, valid_local)
        send_ids, send_valid, owner, slot = _pack_queries(
            uids, n_unique, ws, rows_per_shard)
        back = _exchange(w_local, send_ids, send_valid, axis)
        urows = back[owner, slot]                     # [c, D] unique rows
        sorted_rows = urows[seg]                      # fan out duplicates
        out = jnp.zeros_like(sorted_rows).at[order].set(sorted_rows)
        return jnp.where(valid_local[:, None], out, 0)
    return body


def sharded_lookup(w, ids, mesh, axis, padding_idx=None):
    """Gather rows of a row-sharded table: `w` [V, D] sharded (axis, None),
    `ids` any int shape; returns ids.shape + [D].

    The flat id vector is split over `axis` (each shard runs the wire on
    its ceil(n/ws) slice, padded with sentinel slots), so query traffic
    scales down with the mesh exactly like the table's rows do. V must be
    a multiple of the axis size (pad_vocab; statically checked by
    fluid.analysis.sharding for annotated programs)."""
    from ..parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    ws = mesh.shape[axis]
    V, D = w.shape
    if V % ws:
        raise ValueError(
            'sharded_lookup: vocab %d is not divisible by mesh axis %r '
            'size %d — pad the table (embedding.pad_vocab)' % (V, axis, ws))
    ids_flat = ids.reshape(-1).astype(jnp.int32)
    n = ids_flat.shape[0]
    n_pad = -(-n // ws) * ws
    valid = jnp.arange(n_pad) < n
    ids_wire = jnp.concatenate(
        [ids_flat, jnp.zeros((n_pad - n,), jnp.int32)]) if n_pad != n \
        else ids_flat

    # manual over the WHOLE mesh with unmentioned axes replicated: on a
    # mixed mesh (dp x model) every dp group therefore repeats the
    # identical full-batch exchange — redundant wire traffic, correct
    # numerics. Going manual over the table axis only (axis_names=
    # {axis}, other axes auto) is the fix once the floor jax supports
    # partial-auto shard_map with all_to_all (0.4.x crashes on it);
    # single-axis meshes — the huge-vocab deployment shape — are
    # unaffected either way.
    fn = shard_map(
        _shard_body(axis, ws), mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis)),
        out_specs=P(axis, None), check_vma=False)
    out = fn(w, ids_wire, valid)[:n]
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids_flat == padding_idx)[:, None], 0.0, out)
    return out.reshape(ids.shape + (D,))


def wire_stats(n_ids, vocab, dim, axis_size, itemsize=4):
    """Static wire accounting for one lookup (docs/embedding.md + the
    embedding.lookup obs event): per-shard query capacity and the bytes
    each device puts on the ICI per exchange direction."""
    cap = -(-int(n_ids) // int(axis_size))
    return {
        'ids': int(n_ids), 'vocab': int(vocab), 'dim': int(dim),
        'axis_size': int(axis_size), 'query_capacity': cap,
        'id_bytes_per_device': cap * int(axis_size) * 4,
        'row_bytes_per_device': cap * int(axis_size) * int(dim) * itemsize,
    }
