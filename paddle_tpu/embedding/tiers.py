"""Tiered embedding storage: a host-RAM spill tier behind the HBM table.

At personalization scale the live vocab exceeds HBM even row-sharded,
and `streaming.VocabTable` eviction ZEROES a trained row + its optimizer
moments — a returning user restarts cold. That is a correctness hole as
much as a capacity ceiling (docs/embedding.md#tiers). This module turns
HBM into a CACHE TIER in front of a host-RAM arena, the reference's
pserver `SelectedRows` lookup-table cache rebuilt TPU-native:

  * :class:`HostArena` — a preallocated, mmap-backed row store holding
    one slot per spilled id: the table row plus every same-shape
    optimizer accumulator (`table_state_names` order — no optimizer
    hardcoding). Slots recycle free-list style; torn-write safety rides
    the checkpoint idiom: slot data is written and flushed FIRST, the
    manifest (id -> slot + CRC32) commits LAST via tmp + `.sum` sidecar
    + `os.replace` — a SIGKILL mid-spill leaves the slot unreferenced,
    never adoptable as garbage.
  * :class:`TieredVocabTable` — wraps a `VocabTable` so eviction SPILLS
    the HBM row + moments into the arena instead of zeroing, and
    re-admission of a spilled id RESTORES the trained state bit-exactly.
    Device traffic stays fixed-signature: one donated gather+zero jit
    (:class:`RowSpiller`, HBM->host on spill) and one donated scatter
    jit (:class:`RowRestorer`, host->HBM on restore), both bucket-padded
    like `RowResetter` — zero steady-state compiles.
  * ASYNC PREFETCH — `translate` runs on the `_iter_staged` prefetch
    worker (the `post=` hook); a re-admitted id's arena slot is read
    (host RAM, cheap) THERE, so the step-boundary device scatter never
    blocks on arena IO. Device mutation itself happens only at step
    boundaries (`apply_step_boundary`, driven by `Trainer.train_stream`
    alongside the plain reset path), where no batch is in flight.

Failure posture: arena-full falls back to the OLD zeroing path LOUDLY
(`streaming.tier.arena_full` event + RuntimeWarning — the id restarts
cold, never serves another row's state); a CRC-mismatched slot is
treated the same way (`streaming.tier.corrupt`), never adopted. Column
(dim) sharding of the table is out of scope and fails typed
(:class:`DimShardingUnsupported`) instead of spilling torn row halves.

Checkpointing: `state_dict()` folds the vocab map, the arena manifest
(spill map), and the not-yet-applied spill/restore ops into the
Trainer's checkpoint meta. Slots referenced by the last checkpoint are
NOT recycled until the next one commits (`mark_checkpoint`), so
resume-from-latest always finds its spilled rows intact; older fallback
serials degrade loudly through the CRC check, never silently.

Multi-host: each host owns its arena (`host_arena` appends the process
index to the path) — spills never cross the network, and the serving
side (`ShardedPredictor`) is untouched: spilled ids simply look up cold.
"""
import json
import os
import threading
import time
import warnings
import zlib

import numpy as np

from .. import obs

__all__ = ['HostArena', 'TieredVocabTable', 'RowSpiller', 'RowRestorer',
           'ArenaFull', 'ArenaCorrupt', 'DimShardingUnsupported',
           'host_arena']

_C_SPILLS = obs.counter('streaming.tier.spills')
_C_RESTORES = obs.counter('streaming.tier.restores')
_C_HITS = obs.counter('streaming.tier.hits')
_C_MISSES = obs.counter('streaming.tier.misses')
_C_DROPPED = obs.counter('streaming.tier.dropped')
_G_HIT_RATE = obs.gauge('streaming.tier_hit_rate')
_G_SPILL_MS = obs.gauge('streaming.tier_spill_ms')
_G_RESTORE_MS = obs.gauge('streaming.tier_restore_ms')
_G_OCCUPANCY = obs.gauge('streaming.tier_occupancy')

_DATA_FILE = 'arena.npy'
_MANIFEST = 'manifest.json'


class ArenaFull(RuntimeError):
    """A spill needed a slot but the arena has none free — the caller
    falls back to the zeroing path (loudly) or provisions more slots."""


class ArenaCorrupt(RuntimeError):
    """The arena's on-disk state failed verification: a torn/bit-rotted
    manifest (size/CRC sidecar mismatch), a data file that does not
    match the recorded geometry, or a slot whose bytes no longer match
    their committed CRC32. Never adopted, never served."""


class DimShardingUnsupported(ValueError):
    """The tiered table fronts a table whose EMBEDDING dim is sharded
    over the mesh (e.g. ``sharding=(None, 'model')``). A spill gathers
    whole rows; a dim-sharded row would spill torn halves per host.
    Column sharding for D > HBM is a named leftover (ROADMAP item 3) —
    fail typed instead of corrupting silently."""


def host_arena(path, slots, **kwargs):
    """A :class:`HostArena` under ``path/h<process_index>`` — on a
    multi-host mesh each host owns its spill tier (rows it gathers are
    addressable locally; spills never cross the network)."""
    try:
        import jax
        idx = jax.process_index()
    except Exception:
        idx = 0
    return HostArena(os.path.join(path, 'h%d' % idx), slots, **kwargs)


class HostArena(object):
    """Preallocated mmap-backed row store: the host-RAM spill tier.

    path:  directory holding ``arena.npy`` (a real .npy file opened as
           a memmap — preallocated once, rows written in place) and
           ``manifest.json`` (+ ``.sum`` sidecar): the committed
           id -> (slot, crc32) spill map.
    slots: row capacity of the tier — size it at (8-10x the HBM table)
           minus the HBM capacity; a full arena fails typed.

    Geometry (arrays per slot, row dim, dtype) binds on the first
    `put`; a dtype mix across the table and its moments is rejected
    (the slot store is one homogeneous memmap — casting would break the
    bit-exact round-trip contract).

    An existing committed manifest in `path` is adopted on construction
    (verified against its sidecar and the data file — failure is the
    typed :class:`ArenaCorrupt`); a data file WITHOUT a manifest is a
    crash before the first commit and adopts as empty: uncommitted
    slots are never adoptable.
    """

    def __init__(self, path, slots, name=None):
        self.path = str(path)
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError('arena needs at least 1 slot, got %d'
                             % self.slots)
        self.name = name or os.path.basename(self.path) or 'arena'
        self._lock = threading.RLock()
        self._mm = None                  # np.memmap [slots, n_arrays, D]
        self._geom = None                # (n_arrays, row_dim, dtype str)
        self._entries = {}               # raw id -> (slot, crc32)
        self._free = list(range(self.slots - 1, -1, -1))
        self._limbo = []                 # released since last checkpoint
        self.puts = 0
        self.takes = 0
        os.makedirs(self.path, exist_ok=True)
        mpath = os.path.join(self.path, _MANIFEST)
        if os.path.exists(mpath):
            self._adopt(mpath)

    # -- persistence -------------------------------------------------------

    def _data_path(self):
        return os.path.join(self.path, _DATA_FILE)

    def _ensure(self, n_arrays, row_dim, dtype):
        """Bind geometry + open (or create) the memmap. Idempotent."""
        geom = (int(n_arrays), int(row_dim), str(dtype))
        if self._geom is not None:
            if self._geom != geom:
                raise ValueError(
                    'arena %r holds %r-shaped slots; a spill of %r does '
                    'not fit (the table geometry changed under the '
                    'arena?)' % (self.name, self._geom, geom))
            return
        shape = (self.slots, geom[0], geom[1])
        dp = self._data_path()
        mm = None
        if os.path.exists(dp):
            try:
                mm = np.lib.format.open_memmap(dp, mode='r+')
                if mm.shape != shape or str(mm.dtype) != geom[2]:
                    mm = None            # stale geometry: recreate
            except (ValueError, OSError):
                mm = None
        if mm is None:
            mm = np.lib.format.open_memmap(dp, mode='w+',
                                           dtype=np.dtype(geom[2]),
                                           shape=shape)
        self._mm = mm
        self._geom = geom

    def _commit_locked(self):
        """Commit the manifest ATOMICALLY LAST (slot data is already
        flushed): tmp without the final suffix (scanner safety), `.sum`
        sidecar (size + CRC32 of the staged bytes) FIRST, then the
        rename — the serving/checkpoint atomic-replace idiom."""
        path = os.path.join(self.path, _MANIFEST)
        doc = {'geom': {'n_arrays': self._geom[0] if self._geom else None,
                        'row_dim': self._geom[1] if self._geom else None,
                        'dtype': self._geom[2] if self._geom else None,
                        'slots': self.slots},
               'entries': [[int(k), int(s), int(c)]
                           for k, (s, c) in self._entries.items()]}
        tmp = '%s.tmp%d' % (path, os.getpid())
        with open(tmp, 'w') as f:
            json.dump(doc, f)
        sum_tmp = '%s.sum.tmp%d' % (path, os.getpid())
        with open(sum_tmp, 'w') as f:
            json.dump({'file': _MANIFEST,
                       'bytes': os.path.getsize(tmp),
                       'crc32': _crc32_file(tmp)}, f)
        os.replace(sum_tmp, path + '.sum')
        os.replace(tmp, path)

    def _adopt(self, mpath):
        """Adopt a committed manifest (standalone reopen — the resume
        path overrides this via `load_snapshot` from checkpoint meta).
        Verification failure is typed, never a silent fresh arena."""
        sum_path = mpath + '.sum'
        try:
            with open(sum_path) as f:
                rec = json.load(f)
            want_bytes, want_crc = int(rec['bytes']), int(rec['crc32'])
        except (OSError, ValueError, KeyError) as e:
            raise ArenaCorrupt(
                'arena %r: manifest sidecar %r unreadable (%s: %s) — '
                'torn write or corruption; the spill map is not '
                'trustworthy' % (self.name, sum_path, type(e).__name__, e))
        got_bytes = os.path.getsize(mpath)
        if got_bytes != want_bytes:
            raise ArenaCorrupt(
                'arena %r: manifest is %d bytes, sidecar recorded %d '
                '(truncated write?)' % (self.name, got_bytes, want_bytes))
        if _crc32_file(mpath) != want_crc:
            raise ArenaCorrupt(
                'arena %r: manifest CRC32 does not match its sidecar — '
                'bit rot or a torn write' % self.name)
        with open(mpath) as f:
            doc = json.load(f)
        geom = doc.get('geom') or {}
        if int(geom.get('slots') or 0) != self.slots:
            raise ArenaCorrupt(
                'arena %r: manifest records %s slots, this arena was '
                'built with %d' % (self.name, geom.get('slots'),
                                   self.slots))
        self._load_entries(geom, doc.get('entries') or [])

    def _load_entries(self, geom, entries):
        if geom.get('n_arrays'):
            try:
                self._ensure(geom['n_arrays'], geom['row_dim'],
                             geom['dtype'])
            except (ValueError, OSError) as e:
                raise ArenaCorrupt(
                    'arena %r: data file does not match the recorded '
                    'geometry %r (%s: %s)' % (self.name, geom,
                                              type(e).__name__, e))
        self._entries = {}
        used = set()
        for raw, slot, crc in entries:
            slot = int(slot)
            if not 0 <= slot < self.slots or slot in used:
                raise ArenaCorrupt(
                    'arena %r: manifest references slot %d (slots=%d, '
                    'dup=%s) — not adoptable' % (self.name, slot,
                                                 self.slots, slot in used))
            used.add(slot)
            self._entries[int(raw)] = (slot, int(crc))
        self._free = [s for s in range(self.slots - 1, -1, -1)
                      if s not in used]
        self._limbo = []

    # -- spill / restore ---------------------------------------------------

    def put_many(self, items):
        """Spill `items` = [(raw_id, [row vectors in state-name order])]
        into free slots; ONE manifest commit for the batch. Returns the
        raw ids that did NOT fit (arena full) — the caller owns the loud
        fallback. Slot data flushes before the manifest references it:
        a crash mid-put leaves the old manifest and only unreferenced
        slots touched."""
        if not items:
            return []
        dropped = []
        with self._lock:
            vecs0 = items[0][1]
            dtypes = {str(np.asarray(v).dtype) for v in vecs0}
            if len(dtypes) > 1:
                raise ValueError(
                    'arena %r: mixed dtypes %s across the table and its '
                    'optimizer state — the slot store is one homogeneous '
                    'memmap; a cast would break the bit-exact round trip'
                    % (self.name, sorted(dtypes)))
            self._ensure(len(vecs0), np.asarray(vecs0[0]).shape[-1],
                         dtypes.pop())
            wrote = False
            for raw, vecs in items:
                raw = int(raw)
                old = self._entries.pop(raw, None)
                if old is not None:
                    self._limbo.append(old[0])
                if not self._free:
                    dropped.append(raw)
                    continue
                slot = self._free.pop()
                for i, v in enumerate(vecs):
                    self._mm[slot, i, :] = np.asarray(v).reshape(-1)
                crc = zlib.crc32(self._mm[slot].tobytes()) & 0xFFFFFFFF
                self._entries[raw] = (slot, crc)
                self.puts += 1
                wrote = True
            if wrote:
                self._mm.flush()
            self._commit_locked()
        return dropped

    def put(self, raw_id, vecs):
        """Single-id spill; ArenaFull is typed (put_many reports drops
        instead, for the trainer's loud-fallback path)."""
        if self.put_many([(raw_id, vecs)]):
            raise ArenaFull(
                'arena %r: no free slot for id %d (%d slots, %d limbo '
                'pending the next checkpoint)' % (self.name, int(raw_id),
                                                  self.slots,
                                                  len(self._limbo)))

    def peek(self, raw_id):
        """Read a spilled id's vectors WITHOUT releasing its slot (the
        prefetch leg — release happens at the step boundary through
        `discard_many` once the scatter landed). Returns None when the
        id is not spilled; a CRC mismatch is the typed ArenaCorrupt."""
        with self._lock:
            ent = self._entries.get(int(raw_id))
            if ent is None:
                return None
            slot, want = ent
            buf = np.array(self._mm[slot])    # copy out of the mmap
            got = zlib.crc32(buf.tobytes()) & 0xFFFFFFFF
            if got != want:
                raise ArenaCorrupt(
                    'arena %r: slot %d (id %d) CRC32 %08x does not match '
                    'the committed %08x — torn or bit-rotted; not served'
                    % (self.name, slot, int(raw_id), got, want))
            self.takes += 1
            return [buf[i] for i in range(buf.shape[0])]

    def discard_many(self, raw_ids):
        """Release restored ids' slots into LIMBO (recycled only after
        the next checkpoint commits — the last committed serial may
        still reference them) and commit the manifest once."""
        changed = False
        with self._lock:
            for raw in raw_ids:
                ent = self._entries.pop(int(raw), None)
                if ent is not None:
                    self._limbo.append(ent[0])
                    changed = True
            if changed:
                self._commit_locked()

    def mark_checkpoint(self):
        """A checkpoint committed: slots released since the last mark
        are no longer referenced by any resumable manifest — recycle
        them into the free list."""
        with self._lock:
            self._free.extend(self._limbo)
            self._limbo = []

    # -- checkpoint seam ---------------------------------------------------

    def snapshot(self):
        """JSON-able spill map for checkpoint meta (geometry + entries;
        free/limbo are derivable on load)."""
        with self._lock:
            return {'slots': self.slots,
                    'geom': {'n_arrays': self._geom[0],
                             'row_dim': self._geom[1],
                             'dtype': self._geom[2]}
                    if self._geom else None,
                    'entries': [[int(k), int(s), int(c)]
                                for k, (s, c) in self._entries.items()]}

    def load_snapshot(self, snap):
        """Exact-resume restore: the checkpoint-time spill map becomes
        the arena state (and is re-committed to the directory manifest
        so a later standalone adoption agrees). Slot data is verified
        lazily — a recycled-then-overwritten slot from a pre-checkpoint
        serial fails the CRC on peek, loudly."""
        if int(snap.get('slots') or 0) != self.slots:
            raise ValueError(
                'arena %r: checkpoint spill map is for %s slots, this '
                'arena has %d — geometry mismatch'
                % (self.name, snap.get('slots'), self.slots))
        with self._lock:
            self._load_entries(snap.get('geom') or {},
                               snap.get('entries') or [])
            self._commit_locked()
        return self

    def __contains__(self, raw_id):
        with self._lock:
            return int(raw_id) in self._entries

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self):
        with self._lock:
            return {'slots': self.slots, 'used': len(self._entries),
                    'free': len(self._free), 'limbo': len(self._limbo),
                    'puts': self.puts, 'takes': self.takes,
                    'bytes': int(self._mm.nbytes) if self._mm is not None
                    else 0}


def _crc32_file(path, chunk=1 << 20):
    crc = 0
    with open(path, 'rb') as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


class RowSpiller(object):
    """Gather rows to host AND zero them — ONE donated fixed-shape jit.

    The spill leg of the tier: the evicted rows' current values (table +
    moments) come back as host arrays for the arena, and the SAME
    dispatch zeroes them for their next owner (the old `RowResetter`
    semantics, fused). Rows pad to a fixed `batch` — the gather clips
    padding to row 0 and the host drops it; the zero-scatter uses the
    out-of-range index with mode='drop'. Arrays are donated and a
    NamedSharding input keeps its layout pinned, exactly like
    `RowResetter` — zero steady-state compiles."""

    def __init__(self):
        self._fns = {}     # (shapes/dtypes, batch) -> jitted

    @staticmethod
    def _signature(arrays, batch):
        return (tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
                int(batch))

    def _fn(self, arrays, batch):
        import jax
        import jax.numpy as jnp
        sig = self._signature(arrays, batch)
        fn = self._fns.get(sig)
        if fn is None:
            from jax.sharding import NamedSharding
            shardings = [a.sharding if isinstance(a, jax.Array)
                         and isinstance(getattr(a, 'sharding', None),
                                        NamedSharding) else None
                         for a in arrays]
            cap = int(arrays[0].shape[0])

            def spill(arrs, rows):
                take = jnp.clip(rows, 0, cap - 1)
                gathered = [jnp.take(a, take, axis=0) for a in arrs]
                zeroed = []
                for a, sh in zip(arrs, shardings):
                    z = a.at[rows].set(jnp.zeros((), a.dtype),
                                       mode='drop')
                    if sh is not None:
                        z = jax.lax.with_sharding_constraint(z, sh)
                    zeroed.append(z)
                return zeroed, gathered

            fn = jax.jit(spill, donate_argnums=0)
            self._fns[sig] = fn
        return fn

    def spill(self, arrays, rows, batch=256):
        """Returns (new_arrays_with_rows_zeroed, {row: [vec per
        array]}). Empty rows is a no-op."""
        import jax.numpy as jnp
        rows = [int(r) for r in rows]
        if not rows:
            return list(arrays), {}
        cap = int(arrays[0].shape[0])
        arrays = [a if hasattr(a, 'dtype') else np.asarray(a)
                  for a in arrays]
        fn = self._fn(arrays, batch)
        out = {}
        for lo in range(0, len(rows), batch):
            chunk = rows[lo:lo + batch]
            padded = chunk + [cap] * (batch - len(chunk))
            arrays, gathered = fn(arrays,
                                  jnp.asarray(padded, jnp.int32))
            host = [np.asarray(g) for g in gathered]
            for j, r in enumerate(chunk):
                out[r] = [h[j] for h in host]
        return list(arrays), out


class RowRestorer(object):
    """Scatter host row values back into the device table + moments —
    ONE donated fixed-shape jit (the restore leg). Bucket-padded with
    the out-of-range index + zero values, mode='drop'; sharded layouts
    pinned. Zero steady-state compiles."""

    def __init__(self):
        self._fns = {}

    @staticmethod
    def _signature(arrays, batch):
        return (tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
                int(batch))

    def _fn(self, arrays, batch):
        import jax
        import jax.numpy as jnp  # noqa: F401  (jit tracing)
        sig = self._signature(arrays, batch)
        fn = self._fns.get(sig)
        if fn is None:
            from jax.sharding import NamedSharding
            shardings = [a.sharding if isinstance(a, jax.Array)
                         and isinstance(getattr(a, 'sharding', None),
                                        NamedSharding) else None
                         for a in arrays]

            def restore(arrs, rows, vals):
                out = []
                for a, v, sh in zip(arrs, vals, shardings):
                    z = a.at[rows].set(v, mode='drop')
                    if sh is not None:
                        z = jax.lax.with_sharding_constraint(z, sh)
                    out.append(z)
                return out

            fn = jax.jit(restore, donate_argnums=0)
            self._fns[sig] = fn
        return fn

    def restore(self, arrays, rows, values, batch=256):
        """values: per-array [len(rows), D] host arrays (state-name
        order). Returns the new arrays."""
        import jax.numpy as jnp
        rows = [int(r) for r in rows]
        if not rows:
            return list(arrays)
        cap = int(arrays[0].shape[0])
        arrays = [a if hasattr(a, 'dtype') else np.asarray(a)
                  for a in arrays]
        fn = self._fn(arrays, batch)
        for lo in range(0, len(rows), batch):
            chunk = rows[lo:lo + batch]
            padded = chunk + [cap] * (batch - len(chunk))
            pvals = []
            for a, v in zip(arrays, values):
                pv = np.zeros((batch,) + tuple(a.shape[1:]),
                              np.dtype(str(a.dtype)))
                pv[:len(chunk)] = np.asarray(v)[lo:lo + batch]
                pvals.append(pv)
            arrays = fn(arrays, jnp.asarray(padded, jnp.int32),
                        [jnp.asarray(p) for p in pvals])
        return list(arrays)


class TieredVocabTable(object):
    """A `VocabTable` whose evictions SPILL to a :class:`HostArena` and
    whose re-admissions RESTORE from it — HBM as a cache tier.

    Duck-types the `VocabTable` surface `Trainer.train_stream` and the
    `DeltaPublisher` consume (translate / lookup / state_dict / ... all
    delegate), plus the tier seam the trainer drives:

      * `translate` additionally logs the vocab's admission/eviction
        MOVES and, for a re-admitted spilled id, prefetches its arena
        slot on the calling thread (the prefetch worker under
        double_buffer — the step never blocks on arena IO);
      * `apply_step_boundary(read, write, names)` applies the pending
        device traffic at the step boundary (where no batch is in
        flight): one gather+zero dispatch spills evicted rows into the
        arena, one scatter dispatch restores re-admitted rows — and
        returns {table: rows} it mutated so `DeltaPublisher.touched_rows`
        stays correct across a spill/restore cycle.

    Applying pending ops EARLY (at a boundary before the op's batch
    dispatches — the prefetch window) is safe by the lease invariant:
    an evicted row was unpinned, so no in-flight batch references it,
    and a restored row's first reader is the batch that admitted it.
    """

    def __init__(self, vocab, arena, spill_batch=256):
        self.vocab = vocab
        self.arena = arena
        self.spill_batch = int(spill_batch)
        vocab._log_moves = True
        # one lock serializes translate (worker) against the boundary
        # drain + state_dict (loop thread): a vocab mutation and its
        # move-log entry must never straddle a drain — a reset row
        # zeroed before its spill op is visible would lose the state
        self._lock = threading.RLock()
        self._ops = []        # ordered [('spill'|'restore', raw, row)]
        self._staged = {}     # raw id -> prefetched host vectors
        self._inflight_spill = set()   # ids being put_many'd right now
        self._spiller = RowSpiller()
        self._restorer = RowRestorer()
        # cumulative stats (bench + the obs_report tiers section)
        self.tier_hits = 0        # re-admissions restored from the arena
        self.tier_misses = 0      # admissions with no spilled state
        self.spilled = 0
        self.restored = 0
        self.dropped_full = 0     # loud arena-full fallbacks to zeroing
        self.corrupt_slots = 0    # loud CRC fallbacks to zeroing
        self.last_spill_ms = None
        self.last_restore_ms = None
        self.restore_ms_samples = []   # bounded; bench percentiles

    # -- delegated VocabTable surface --------------------------------------

    @property
    def table(self):
        return self.vocab.table

    @property
    def name(self):
        return self.vocab.name

    @property
    def capacity(self):
        return self.vocab.capacity

    @property
    def cold_row(self):
        return self.vocab.cold_row

    def lookup(self, ids):
        return self.vocab.lookup(ids)

    def resident_ids(self):
        return self.vocab.resident_ids()

    def rows_of(self, ids):
        return self.vocab.rows_of(ids)

    def drain_resets(self):
        return self.vocab.drain_resets()

    def __len__(self):
        return len(self.vocab)

    # -- translation + prefetch --------------------------------------------

    def translate(self, ids, pin=True):
        with self._lock:
            out = self.vocab.translate(ids, pin=pin)
            self._log_moves_locked()
        return out

    def preload(self, ids):
        with self._lock:
            self.vocab.preload(ids)
            self._log_moves_locked()
        return self

    def evict(self, raw_id):
        with self._lock:
            row = self.vocab.evict(raw_id)
            self._log_moves_locked()
        return row

    def _log_moves_locked(self):
        """Fold the vocab's admission/eviction moves into the pending op
        log; prefetch a re-admitted spilled id's slot HERE (the calling
        thread is the prefetch worker under double_buffer). Caller holds
        self._lock — the drain of moves is atomic with the vocab
        mutation that produced them."""
        moves = self.vocab.drain_moves()
        if not moves:
            return
        prefetched = []
        pending_spill = {raw for kind, raw, _ in self._ops
                         if kind == 'spill'}
        for kind, raw, row in moves:
            if kind == 'evict':
                self._ops.append(('spill', raw, row))
                pending_spill.add(raw)
                continue
            # admission: warm when the arena (or this window's
            # not-yet-applied / in-flight spills) holds trained state
            if raw in pending_spill or raw in self._inflight_spill:
                self._ops.append(('restore', raw, row))
                self.tier_hits += 1
                _C_HITS.inc()
                continue
            staged = None
            try:
                staged = self.arena.peek(raw)
            except ArenaCorrupt as e:
                self._corrupt_fallback(raw, e)
            if staged is None:
                self.tier_misses += 1
                _C_MISSES.inc()
                continue
            self._staged[raw] = staged
            self._ops.append(('restore', raw, row))
            self.tier_hits += 1
            _C_HITS.inc()
            prefetched.append(raw)
        if prefetched:
            obs.event('streaming.tier.prefetch', vocab=self.name,
                      rows=len(prefetched), sample=prefetched[:8])

    def _corrupt_fallback(self, raw, err):
        """A CRC-mismatched slot is NEVER served: drop it loudly and let
        the id restart cold (the zeroing path) — wrong state would be
        silent corruption, a cold row is just the pre-tier behavior."""
        self.corrupt_slots += 1
        self.arena.discard_many([raw])
        obs.event('streaming.tier.corrupt', vocab=self.name,
                  id=int(raw), error=str(err)[:200])
        warnings.warn(
            'tiered vocab %r: spilled state for id %d failed its CRC '
            'check and was dropped — the id restarts cold (%s)'
            % (self.name, int(raw), err), RuntimeWarning)

    # -- the step-boundary device seam -------------------------------------

    def validate_program(self, program):
        """Typed refusal of a dim-sharded table: spills gather WHOLE
        rows; column sharding (D > HBM) is the named ROADMAP leftover."""
        blk = program.global_block()
        tvar = blk.vars.get(self.table)
        if tvar is None:
            raise KeyError('no variable %r in the program'
                           % (self.table,))
        # mark the table var as tier-backed so the STATIC sharding pass
        # (fluid.analysis.sharding, DimSharding) and program_lint --mesh
        # can refuse a dim-sharded tiered table before any device is
        # touched; this runtime raise stays as the backstop
        tvar.tiered = True
        sh = getattr(tvar, 'sharding', None)
        if sh and any(ax is not None for ax in tuple(sh)[1:]):
            raise DimShardingUnsupported(
                'tiered vocab %r: table %r shards its EMBEDDING dim '
                '(sharding=%r) — a spill would tear rows across hosts. '
                'Column sharding for D > HBM is out of scope for the '
                'tier store (ROADMAP item 3); row-shard the table '
                '(e.g. sharding=(%r, None)) instead.'
                % (self.name, self.table, tuple(sh),
                   tuple(sh)[1] if len(sh) > 1 else 'model'))

    def apply_step_boundary(self, read, write, names):
        """Apply pending spills/restores + the reset zeroing in (at
        most) two fixed-signature dispatches. `read(name)`/`write(name,
        array)` are the trainer's scope accessors; `names` the
        `table_state_names` list. Returns {table: sorted row array} of
        every row mutated (zeroed or restored) — fed to the publisher
        so serving replicas converge after a spill/restore cycle."""
        with self._lock:
            # the drain is atomic with translate: every reset row's
            # spill op is already in the log (the translate that queued
            # the reset logged the move before releasing the lock)
            ops, self._ops = self._ops, []
            staged, self._staged = self._staged, {}
            rows_to_zero = self.vocab.drain_resets()
            spills = [(raw, row) for kind, raw, row in ops
                      if kind == 'spill']
            restores = [(raw, row) for kind, raw, row in ops
                        if kind == 'restore']
            # a re-admission racing the put_many below must see these
            # ids as warm (their state is in flight to the arena)
            self._inflight_spill = {raw for raw, _ in spills}
        if not rows_to_zero and not restores:
            with self._lock:
                self._inflight_spill = set()
            return None
        arrays = [read(n) for n in names]
        changed = set()

        if rows_to_zero:
            t0 = time.monotonic()
            arrays, gathered = self._spiller.spill(
                arrays, rows_to_zero, batch=self.spill_batch)
            dropped = self.arena.put_many(
                [(raw, gathered[row]) for raw, row in spills])
            with self._lock:
                self._inflight_spill = set()
            self.last_spill_ms = (time.monotonic() - t0) * 1000.0
            changed.update(rows_to_zero)
            n_spilled = len(spills) - len(dropped)
            self.spilled += n_spilled
            _C_SPILLS.inc(n_spilled)
            _G_SPILL_MS.set(self.last_spill_ms)
            st = self.arena.stats()
            _G_OCCUPANCY.set(st['used'] / float(st['slots']))
            obs.event('streaming.tier.spill', vocab=self.name,
                      rows=n_spilled, zeroed=len(rows_to_zero),
                      spill_ms=round(self.last_spill_ms, 3),
                      arena_used=st['used'], arena_slots=st['slots'])
            if dropped:
                self._arena_full_fallback(dropped, st)

        if restores:
            t0 = time.monotonic()
            ok_rows, ok_vals, ok_ids = [], [], []
            for raw, row in restores:
                vecs = staged.pop(raw, None)
                if vecs is None:
                    # spilled-and-re-admitted inside one prefetch
                    # window: the arena entry landed just above
                    try:
                        vecs = self.arena.peek(raw)
                    except ArenaCorrupt as e:
                        self._corrupt_fallback(raw, e)
                        continue
                if vecs is None:
                    # arena-full dropped this id's spill: it restarts
                    # cold (already counted loudly above)
                    continue
                ok_rows.append(row)
                ok_vals.append(vecs)
                ok_ids.append(raw)
            if ok_rows:
                values = [np.stack([v[i] for v in ok_vals])
                          for i in range(len(names))]
                arrays = self._restorer.restore(
                    arrays, ok_rows, values, batch=self.spill_batch)
                self.arena.discard_many(ok_ids)
                self.last_restore_ms = (time.monotonic() - t0) * 1000.0
                changed.update(ok_rows)
                self.restored += len(ok_rows)
                _C_RESTORES.inc(len(ok_rows))
                _G_RESTORE_MS.set(self.last_restore_ms)
                if len(self.restore_ms_samples) < 4096:
                    self.restore_ms_samples.append(self.last_restore_ms)
                obs.event('streaming.tier.restore', vocab=self.name,
                          rows=len(ok_rows),
                          restore_ms=round(self.last_restore_ms, 3))
        _G_HIT_RATE.set(self.hit_rate())

        for n, a in zip(names, arrays):
            write(n, a)
        if not changed:
            return None
        return {self.table: np.asarray(sorted(changed), np.int64)}

    def _arena_full_fallback(self, dropped, st):
        """Arena full: the old zeroing path, LOUDLY — the ids restart
        cold (their rows were zeroed by the spill dispatch; nothing
        wrong is ever served), typed event + warning, never silent."""
        self.dropped_full += len(dropped)
        _C_DROPPED.inc(len(dropped))
        obs.event('streaming.tier.arena_full', vocab=self.name,
                  dropped=len(dropped), sample=dropped[:8],
                  arena_slots=st['slots'])
        warnings.warn(
            'tiered vocab %r: arena %r is FULL (%d slots) — %d evicted '
            'id(s) fell back to the zeroing path and will re-admit '
            'cold. Provision more slots (or checkpoint more often to '
            'recycle limbo slots).' % (self.name, self.arena.name,
                                       st['slots'], len(dropped)),
            RuntimeWarning)

    def mark_checkpoint(self):
        """Trainer hook: a checkpoint committed — limbo slots recycle."""
        self.arena.mark_checkpoint()

    # -- checkpoint seam ---------------------------------------------------

    def state_dict(self):
        """Vocab map + arena spill map + pending (not-yet-applied) ops.
        Staged prefetch values are NOT serialized: their arena entries
        still exist (slots release only after the scatter lands), so a
        resumed table re-reads them by id."""
        with self._lock:
            # one lock span: the vocab map, its pending resets, the op
            # log, and the spill map must snapshot as ONE instant — a
            # translate landing mid-snapshot would desync them
            ops = [[k, int(r), int(w)] for k, r, w in self._ops]
            vocab_sd = self.vocab.state_dict()
            arena_sd = self.arena.snapshot()
        return {'tiered': True,
                'vocab': vocab_sd,
                'arena': arena_sd,
                'ops': ops,
                'stats': {'tier_hits': self.tier_hits,
                          'tier_misses': self.tier_misses,
                          'spilled': self.spilled,
                          'restored': self.restored,
                          'dropped_full': self.dropped_full,
                          'corrupt_slots': self.corrupt_slots}}

    def load_state_dict(self, state):
        with self._lock:
            return self._load_state_locked(state)

    def _load_state_locked(self, state):
        if not state.get('tiered'):
            # a plain-vocab checkpoint: adoptable (the tier starts
            # empty — pre-tier checkpoints stay resumable)
            self.vocab.load_state_dict(state)
            self.vocab.drain_moves()
            return self
        self.vocab.load_state_dict(state['vocab'])
        self.vocab.drain_moves()
        self.arena.load_snapshot(state['arena'])
        self._ops = [(str(k), int(r), int(w))
                     for k, r, w in state.get('ops', [])]
        self._staged = {}
        st = state.get('stats', {})
        self.tier_hits = int(st.get('tier_hits', 0))
        self.tier_misses = int(st.get('tier_misses', 0))
        self.spilled = int(st.get('spilled', 0))
        self.restored = int(st.get('restored', 0))
        self.dropped_full = int(st.get('dropped_full', 0))
        self.corrupt_slots = int(st.get('corrupt_slots', 0))
        return self

    # -- stats -------------------------------------------------------------

    def hit_rate(self):
        total = self.tier_hits + self.tier_misses
        return self.tier_hits / float(total) if total else 1.0

    def stats(self):
        out = self.vocab.stats()
        out.update(self.arena.stats())
        out.update({'tier_hits': self.tier_hits,
                    'tier_misses': self.tier_misses,
                    'tier_hit_rate': self.hit_rate(),
                    'spilled': self.spilled,
                    'restored': self.restored,
                    'dropped_full': self.dropped_full,
                    'corrupt_slots': self.corrupt_slots,
                    'last_spill_ms': self.last_spill_ms,
                    'last_restore_ms': self.last_restore_ms})
        return out
