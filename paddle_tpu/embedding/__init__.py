"""paddle_tpu.embedding — sharded huge-vocab embedding tables (docs/embedding.md).

The reference served millions-of-users recommendation by splitting
`lookup_table` rows across parameter servers (DistributeTranspiler +
gRPC prefetch). This package is that role rebuilt TPU-native on the
first-class GSPMD surface:

  * the TABLE is an ordinary parameter row-sharded over a mesh axis —
    ``ParamAttr(sharding=('model', None))`` (or `table_attr` below) on a
    Program with ``set_mesh({'model': N, ...})``;
  * the LOOKUP — ``layers.embedding(..., is_sparse=True,
    is_distributed=True)`` — lowers to the all_to_all wire in
    `embedding.lookup` (bucket by owning shard, dedup, exchange, gather,
    return), behind the plain `lookup_table` op: `Executor.run`,
    `run_bundle`, and `Trainer` need no wrapper;
  * the UPDATE stays sparse AND sharded: the backward produces a
    `lowering.SparseRows` (touched rows only) and sgd/adagrad/adam apply
    per-shard touched-row updates (ops_impl/optim_ops.py) — the dense
    [vocab, dim] gradient never exists on any device.

Functional surface (usable outside Programs too): `sharded_lookup`,
`pad_vocab`, `dedup_plan`, `wire_stats`, and `table_attr` /
`gather_table` helpers for building and exporting sharded models.
"""
from .lookup import sharded_lookup, dedup_plan, pad_vocab, wire_stats
from .tiers import (ArenaCorrupt, ArenaFull, DimShardingUnsupported,
                    HostArena, RowRestorer, RowSpiller, TieredVocabTable,
                    host_arena)

__all__ = ['sharded_lookup', 'dedup_plan', 'pad_vocab', 'wire_stats',
           'table_attr', 'gather_table',
           'HostArena', 'TieredVocabTable', 'RowSpiller', 'RowRestorer',
           'ArenaFull', 'ArenaCorrupt', 'DimShardingUnsupported',
           'host_arena']


def table_attr(name, axis='model', **kwargs):
    """ParamAttr for a row-sharded embedding table: dim 0 (vocab) over
    `axis`, the embedding dim whole on every shard."""
    from ..fluid.param_attr import ParamAttr
    return ParamAttr(name=name, sharding=(axis, None), **kwargs)


def gather_table(scope, name):
    """Materialize a (possibly mesh-sharded) table on the host as one
    numpy array — the export seam: after sharded training, inference
    artifacts (`export_compiled` / `save_inference_model`) trace against
    single-device values, so the trained shards are gathered once here,
    not inside the serving path."""
    import numpy as np
    holder = scope.find_var(name)
    if holder is None:
        raise KeyError('no variable %r in scope' % name)
    return np.asarray(holder.get_tensor())
