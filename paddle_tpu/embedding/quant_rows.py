"""Int8 embedding ROW codec: quantized storage, fp32 optimizer moments.

The embedding tier store (tiers.py, PR 18) and the delta publisher
(streaming/publish.py) both move table ROWS around — HBM bytes on the
serving side, wire bytes on the freshness loop. Row values tolerate
int8 (each row carries its own absmax scale — the per-channel axis-0
scheme of ops_impl/quant_ops.py, ONE definition of the rounding), while
the optimizer MOMENTS that ride next to them in training do not: their
magnitudes span the whole schedule, so moments stay fp32 and only the
VALUE bytes shrink. Note the HostArena (tiers.py) stores a slot's
table+moment rows in one homogeneous block and therefore keeps fp32 —
int8 rows pay off at the two boundaries where values travel ALONE: the
delta push (wire bytes per row: 4*D -> D + 4, the bench.py
`--phase quant` metric) and the quantized serving table
(quant_lookup_table's HBM: docs/perf.md#quantized-inference).
"""
import numpy as np

__all__ = ['quantize_rows', 'dequantize_rows', 'row_bytes',
           'ROW_SCALE_BYTES']

# one f32 absmax scale per row rides with the int8 payload
ROW_SCALE_BYTES = 4


def quantize_rows(vals):
    """[N, D] float rows -> (q int8 [N, D], scale f32 [N, 1]) with
    per-row symmetric absmax scales. Pure numpy (the publisher runs
    host-side, off the step path); same rounding as
    ops_impl.quant_ops.quantize_array(axis=0)."""
    vals = np.asarray(vals, np.float32)
    amax = np.max(np.abs(vals), axis=tuple(range(1, vals.ndim)),
                  keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.round(vals / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_rows(q, scale):
    """Invert quantize_rows: [N, D] int8 + [N, 1] f32 -> f32 rows. The
    round-trip error bound is half a step per element:
    |deq(q(x)) - x| <= max|x_row| / 254."""
    return q.astype(np.float32) * np.asarray(scale, np.float32)


def row_bytes(q, scale):
    """Payload bytes of a quantized row batch (values + scales) — what
    the delta push puts on the wire per table."""
    return int(np.asarray(q).nbytes + np.asarray(scale).nbytes)
