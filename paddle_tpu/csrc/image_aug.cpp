// Batched image augmentation: the host-side hot loop of the ResNet/flowers
// input pipeline (resize_short -> random/center crop -> flip -> CHW float32
// -> mean subtract), multithreaded across the batch.
//
// Counterpart of python/paddle/dataset/image.py:simple_transform in the
// reference (cv2-backed there); semantics match paddle_tpu/dataset/image.py
// exactly (same half-pixel bilinear, uint8 rounding after resize) so the
// numpy path and this one are interchangeable.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// xorshift64* — per-image deterministic stream from (seed, index)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ULL) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
  }
  // uniform integer in [0, n)
  uint64_t below(uint64_t n) { return n ? next() % n : 0; }
};

// bilinear resize (half-pixel centers) HWC uint8 -> HWC uint8.
// Column sample positions/weights are row-invariant: precompute them once,
// then each output row is two source-row passes the compiler can vectorize.
void resize_bilinear_u8(const uint8_t* src, int h, int w, int c,
                        uint8_t* dst, int oh, int ow) {
  const float sy = static_cast<float>(h) / oh;
  const float sx = static_cast<float>(w) / ow;
  std::vector<int> xo0(ow), xo1(ow);
  std::vector<float> wx(ow);
  for (int x = 0; x < ow; ++x) {
    float fx = (x + 0.5f) * sx - 0.5f;
    int x0 = std::min(std::max(static_cast<int>(std::floor(fx)), 0), w - 1);
    xo0[x] = x0 * c;
    xo1[x] = std::min(x0 + 1, w - 1) * c;
    wx[x] = std::min(std::max(fx - x0, 0.0f), 1.0f);
  }
  // horizontal pass scratch for the two source rows feeding an output row
  std::vector<float> rowa(static_cast<size_t>(ow) * c);
  std::vector<float> rowb(static_cast<size_t>(ow) * c);
  int cached_y0 = -1, cached_y1 = -1;

  auto hpass = [&](const uint8_t* srow, float* out) {
    for (int x = 0; x < ow; ++x) {
      const uint8_t* p0 = srow + xo0[x];
      const uint8_t* p1 = srow + xo1[x];
      const float fx = wx[x];
      float* o = out + static_cast<size_t>(x) * c;
      for (int k = 0; k < c; ++k)   // same formula as the numpy path
        o[k] = p0[k] * (1.0f - fx) + p1[k] * fx;
    }
  };

  for (int y = 0; y < oh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::min(std::max(static_cast<int>(std::floor(fy)), 0), h - 1);
    int y1 = std::min(y0 + 1, h - 1);
    float fwy = std::min(std::max(fy - y0, 0.0f), 1.0f);
    // consecutive output rows usually share source rows: reuse the pass
    if (y0 == cached_y1) {
      rowa.swap(rowb);
      cached_y0 = y0;
      if (y1 != y0) {
        hpass(src + static_cast<size_t>(y1) * w * c, rowb.data());
        cached_y1 = y1;
      } else {
        rowb = rowa;
        cached_y1 = y1;
      }
    } else if (y0 != cached_y0) {
      hpass(src + static_cast<size_t>(y0) * w * c, rowa.data());
      cached_y0 = y0;
      hpass(src + static_cast<size_t>(y1) * w * c, rowb.data());
      cached_y1 = y1;
    } else if (y1 != cached_y1) {
      hpass(src + static_cast<size_t>(y1) * w * c, rowb.data());
      cached_y1 = y1;
    }
    uint8_t* orow = dst + static_cast<size_t>(y) * ow * c;
    const float* ra = rowa.data();
    const float* rb = rowb.data();
    const int nn = ow * c;
    for (int i = 0; i < nn; ++i) {
      float v = ra[i] * (1.0f - fwy) + rb[i] * fwy;
      orow[i] = static_cast<uint8_t>(
          std::min(std::max(std::nearbyint(v), 0.0f), 255.0f));
    }
  }
}

void transform_one(const uint8_t* img, int h, int w, int c, int resize_size,
                   int crop_size, bool is_train, const float* mean,
                   int mean_len, Rng* rng, float* out) {
  // shorter-edge resize
  int oh, ow;
  if (h > w) {
    ow = resize_size;
    oh = static_cast<int>(
        std::nearbyint(static_cast<double>(h) * resize_size / w));
  } else {
    oh = resize_size;
    ow = static_cast<int>(
        std::nearbyint(static_cast<double>(w) * resize_size / h));
  }
  std::vector<uint8_t> resized;
  const uint8_t* rptr = img;
  if (oh != h || ow != w) {
    resized.resize(static_cast<size_t>(oh) * ow * c);
    resize_bilinear_u8(img, h, w, c, resized.data(), oh, ow);
    rptr = resized.data();
  }

  // crop offsets
  int y0, x0;
  bool flip = false;
  if (is_train) {
    y0 = static_cast<int>(rng->below(oh - crop_size + 1));
    x0 = static_cast<int>(rng->below(ow - crop_size + 1));
    flip = rng->below(2) == 0;
  } else {
    y0 = (oh - crop_size) / 2;
    x0 = (ow - crop_size) / 2;
  }

  // crop (+flip) -> CHW float32 - mean (scalar, per-channel, or a full
  // CHW mean image of crop_size^2 * c elements)
  const bool mean_image = mean && mean_len == c * crop_size * crop_size;
  for (int k = 0; k < c; ++k) {
    float m = 0.0f;
    if (mean && mean_len == c) m = mean[k];
    else if (mean && mean_len == 1) m = mean[0];
    const size_t plane_off = static_cast<size_t>(k) * crop_size * crop_size;
    float* plane = out + plane_off;
    const float* mplane = mean_image ? mean + plane_off : nullptr;
    for (int y = 0; y < crop_size; ++y) {
      const uint8_t* row = rptr + ((y0 + y) * ow + x0) * c;
      for (int x = 0; x < crop_size; ++x) {
        int sx = flip ? (crop_size - 1 - x) : x;
        float mm = mplane ? mplane[y * crop_size + x] : m;
        plane[y * crop_size + x] =
            static_cast<float>(row[sx * c + k]) - mm;
      }
    }
  }
}

}  // namespace

extern "C" {

// Transform a batch of same-sized raw images.
//   src:  n contiguous HWC uint8 images [n, h, w, c]
//   out:  n contiguous CHW float32 crops [n, c, crop, crop]
//   mean: nullptr, [1], or [c] per-channel values subtracted after cast
//   seed: deterministic stream; image i draws from (seed, i) independently
// Returns 0 on success, -1 on bad arguments.
int ptim_transform_batch(const uint8_t* src, int n, int h, int w, int c,
                         int resize_size, int crop_size, int is_train,
                         const float* mean, int mean_len, uint64_t seed,
                         float* out) {
  if (!src || !out || n <= 0 || c <= 0 || crop_size <= 0) return -1;
  int short_edge = std::min(h, w);
  if (resize_size <= 0 || crop_size > resize_size ||
      short_edge <= 0)
    return -1;
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = static_cast<int>(std::min<uint64_t>(hw ? hw : 2, n));
  std::atomic<int> next(0);
  const size_t in_stride = static_cast<size_t>(h) * w * c;
  const size_t out_stride = static_cast<size_t>(c) * crop_size * crop_size;

  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) break;
      Rng rng(seed * 0x9E3779B97F4A7C15ULL + 0xBF58476D1CE4E5B9ULL * (i + 1));
      transform_one(src + i * in_stride, h, w, c, resize_size, crop_size,
                    is_train != 0, mean, mean_len, &rng, out + i * out_stride);
    }
  };
  std::vector<std::thread> threads;
  for (int t = 1; t < nthreads; ++t) threads.emplace_back(worker);
  worker();
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
