// Chunked record IO + threaded prefetch — the native data-path runtime.
//
// Parity: reference paddle/fluid/recordio/{chunk,scanner,writer}.cc (C++
// chunked record storage with per-record checksums) and the reader op
// chain's double-buffer thread (operators/reader/
// create_double_buffer_reader_op.cc). TPU-first the device side is JAX, so
// the native runtime owns what stays on the host: zero-copy mmap record
// scanning and a background producer thread that stages decoded records in
// a bounded ring so the train loop never blocks on disk.
//
// Exposed as a C ABI consumed via ctypes (paddle_tpu/utils/native.py);
// format matches the pure-python fallback (reader/recordio.py):
//   magic "PTRIO1\n" | per record: u32 payload_len | u32 crc32 | payload
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[] = "PTRIO1\n";
constexpr size_t kMagicLen = 7;

// crc32 (IEEE, zlib-compatible) — table generated on first use
uint32_t crc32_ieee(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Scanner {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t size = 0;
  size_t off = 0;
  bool check_crc = true;
};

Scanner* open_scanner(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)kMagicLen) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(base, st.st_size, MADV_SEQUENTIAL);
  if (memcmp(base, kMagic, kMagicLen) != 0) {
    munmap(base, st.st_size);
    ::close(fd);
    return nullptr;
  }
  auto* s = new Scanner();
  s->fd = fd;
  s->base = static_cast<const uint8_t*>(base);
  s->size = st.st_size;
  s->off = kMagicLen;
  return s;
}

// returns payload length, sets *out to a pointer INTO the mapping (valid
// until close); -1 on EOF, -2 on corruption
ssize_t scanner_next(Scanner* s, const uint8_t** out) {
  if (s->off + 8 > s->size) {
    // 1-7 trailing bytes = a header truncated mid-write: corruption, not EOF
    return s->off == s->size ? -1 : -2;
  }
  uint32_t len, crc;
  memcpy(&len, s->base + s->off, 4);
  memcpy(&crc, s->base + s->off + 4, 4);
  s->off += 8;
  if (s->off + len > s->size) return -2;
  const uint8_t* payload = s->base + s->off;
  s->off += len;
  if (s->check_crc && crc32_ieee(payload, len) != crc) return -2;
  *out = payload;
  return (ssize_t)len;
}

void close_scanner(Scanner* s) {
  if (!s) return;
  if (s->base) munmap(const_cast<uint8_t*>(s->base), s->size);
  if (s->fd >= 0) ::close(s->fd);
  delete s;
}

// ---------------------------------------------------------------------------
// threaded prefetch: producer thread scans records into a bounded deque
// ---------------------------------------------------------------------------

struct Prefetcher {
  Scanner* scanner = nullptr;
  size_t depth = 4;
  std::deque<std::vector<uint8_t>> queue;
  std::vector<uint8_t> current;  // last record handed to the consumer
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  bool done = false, error = false, stop = false;
  std::thread worker;

  void run() {
    for (;;) {
      const uint8_t* p = nullptr;
      ssize_t n = scanner_next(scanner, &p);
      std::unique_lock<std::mutex> lk(mu);
      if (n == -1 || n == -2 || stop) {
        error = (n == -2);
        done = true;
        cv_get.notify_all();
        return;
      }
      cv_put.wait(lk, [&] { return queue.size() < depth || stop; });
      if (stop) {
        done = true;
        cv_get.notify_all();
        return;
      }
      queue.emplace_back(p, p + n);
      cv_get.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// -- plain scanner ABI (see utils/native.py recordio_iter) --
void* ptrio_open(const char* path) { return open_scanner(path); }

// returns payload length; -1 on clean EOF, -2 on corruption
ssize_t ptrio_next(void* h, const char** out) {
  const uint8_t* p = nullptr;
  ssize_t n = scanner_next(static_cast<Scanner*>(h), &p);
  *out = reinterpret_cast<const char*>(p);
  return n;
}

void ptrio_close(void* h) { close_scanner(static_cast<Scanner*>(h)); }

// -- record writer (streaming append) --
void* ptrio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  fwrite(kMagic, 1, kMagicLen, f);
  return f;
}

int ptrio_writer_write(void* h, const char* data, uint64_t len) {
  FILE* f = static_cast<FILE*>(h);
  if (len > UINT32_MAX) return -1;  // u32 length field; don't truncate
  uint32_t l = (uint32_t)len;
  uint32_t crc = crc32_ieee(reinterpret_cast<const uint8_t*>(data), len);
  if (fwrite(&l, 4, 1, f) != 1) return -1;
  if (fwrite(&crc, 4, 1, f) != 1) return -1;
  if (len && fwrite(data, 1, len, f) != len) return -1;
  return 0;
}

void ptrio_writer_close(void* h) { fclose(static_cast<FILE*>(h)); }

// -- threaded prefetch ABI --
void* ptrio_prefetch_open(const char* path, uint64_t depth) {
  Scanner* s = open_scanner(path);
  if (!s) return nullptr;
  auto* p = new Prefetcher();
  p->scanner = s;
  p->depth = depth ? depth : 4;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// pops the next record; returns length (pointer valid until the next call
// or close), -1 on clean EOF, -2 on corruption
ssize_t ptrio_prefetch_next(void* h, const char** out) {
  auto* p = static_cast<Prefetcher*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_get.wait(lk, [&] { return !p->queue.empty() || p->done; });
  if (p->queue.empty()) return p->error ? -2 : -1;
  p->current = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_put.notify_one();
  *out = reinterpret_cast<const char*>(p->current.data());
  return (ssize_t)p->current.size();
}

void ptrio_prefetch_close(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_put.notify_all();
  }
  p->worker.join();
  close_scanner(p->scanner);
  delete p;
}

}  // extern "C"
