"""TPU kernels (pallas) for the hot ops.

The compute path is JAX/XLA; these kernels take over where hand-tiling
beats the compiler — flash attention (the reference's equivalent hot
path is the cuDNN/cuBLAS attention chain in its benchmark models), and
the `kernels/` registry (paged decode-attention, fused sparse
optimizers) that the lowering rules dispatch into behind the
per-kernel `PADDLE_TPU_KERNELS` knob (docs/perf.md#kernel-layer).
"""
from .flash_attention import flash_attention, flash_attention_lse, \
    reference_attention
from . import kernels

__all__ = ['flash_attention', 'flash_attention_lse', 'reference_attention',
           'kernels']
