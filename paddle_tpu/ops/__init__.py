"""TPU kernels (pallas) for the hot ops.

The compute path is JAX/XLA; these kernels take over where hand-tiling
beats the compiler — currently flash attention (the reference's equivalent
hot path is the cuDNN/cuBLAS attention chain in its benchmark models).
"""
from .flash_attention import flash_attention, flash_attention_lse, \
    reference_attention

__all__ = ['flash_attention', 'flash_attention_lse', 'reference_attention']
