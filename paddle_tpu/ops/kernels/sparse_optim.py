"""Fused sharded-sparse optimizer kernels (docs/perf.md#kernel-layer).

The streaming `train_stream` step's hottest op after the lookup is the
sparse optimizer update (ops_impl/optim_ops.py adagrad/adam
SelectedRows branches): after `_merge_sparse` dedups the batch's rows,
XLA emits a gather of the param/moment rows, the moment math, and a
scatter-add of the deltas — three HBM round-trips over [N, D] plus the
table-row traffic. These kernels fuse gather + moment update + scatter
into ONE pallas call: the merged uids ride scalar prefetch and serve as
the BlockSpec index maps for the param/moment ROWS (in and out — the
tables are aliased via `input_output_aliases`, so the update is
in-place row traffic and the [N, D] gathered copies never exist in
HBM). The dedup merge itself (sort/segment-sum, embedding.lookup.
dedup_plan) stays XLA: it is id-space bookkeeping with no row traffic,
and sharing ONE definition of the dedup invariant with the lookup wire
beats fusing it.

Write-hazard analysis (why the grid runs the slots in REVERSE): the
merge clamps its invalid tail slots to row 0, so row 0 can be visited
more than once. Valid uids are unique, and an invalid slot's write is
always value-preserving (its delta is masked to zero — it writes the
row it read). Processing slots back-to-front puts every invalid visit
of row 0 BEFORE the (at most one) valid visit, so no grid step ever
reads a row that an earlier step changed. That makes the kernel correct
under BOTH aliasing semantics in play: the pallas interpreter (tier-1,
CPU), whose input carry is a snapshot that never sees in-grid writes,
and compiled Mosaic, where the aliased buffer is live and input
prefetch may race a write by a few pipeline stages — hazard-free
because the only re-read row only ever received no-op writes first.

Numerics: per-row math is the fallback's elementwise expressions in the
same order on the same f32 rows, so parity is effectively exact;
tests/test_kernels.py pins |kernel - fallback| <= 1e-6 absolute
(docs/perf.md carries the table). Sharded steps (ctx.mesh set) keep the
XLA fallback — the kernel is per-shard-local and its shard_map wiring
is a follow-on; dispatch sites route accordingly.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import register_kernel, interpret_default

SPARSE_ADAGRAD = register_kernel(
    'sparse_adagrad',
    'merged-row gather + adagrad moment update + scatter fused, tables '
    'aliased in-place')
SPARSE_ADAM = register_kernel(
    'sparse_adam',
    'merged-row gather + adam moment update + scatter fused, tables '
    'aliased in-place')


def _adagrad_kernel(uids_ref, valid_ref, lr_ref, gm_ref, p_ref, m_ref,
                    p_out, m_out, *, eps):
    i = pl.program_id(0)
    r = pl.num_programs(0) - 1 - i
    vm = (valid_ref[r] > 0).astype(jnp.float32)
    lr = lr_ref[0, 0]
    g = gm_ref[...]                     # (1, D) merged grad for this slot
    p_row = p_ref[...]
    m_row = m_ref[...]
    m_new = m_row + g * g
    p_delta = -lr * g / (jnp.sqrt(m_new) + eps) * vm
    p_out[...] = p_row + p_delta
    m_out[...] = m_row + (m_new - m_row) * vm


def _adam_kernel(uids_ref, valid_ref, lr_ref, gm_ref, p_ref, m1_ref,
                 m2_ref, p_out, m1_out, m2_out, *, b1, b2, eps):
    i = pl.program_id(0)
    r = pl.num_programs(0) - 1 - i
    vm = (valid_ref[r] > 0).astype(jnp.float32)
    lr = lr_ref[0, 0]
    g = gm_ref[...]
    p_row = p_ref[...]
    m1_row = m1_ref[...]
    m2_row = m2_ref[...]
    m1_new = b1 * m1_row + (1 - b1) * g
    m2_new = b2 * m2_row + (1 - b2) * g * g
    p_delta = -lr * m1_new / (jnp.sqrt(m2_new) + eps) * vm
    p_out[...] = p_row + p_delta
    m1_out[...] = m1_row + (m1_new - m1_row) * vm
    m2_out[...] = m2_row + (m2_new - m2_row) * vm


def _row_spec(uids_name_unused, n):
    # param/moment rows: the page table of this kernel is the merged uid
    # vector — scalar prefetch indexes the row block directly (reversed:
    # see the hazard analysis in the module docstring)
    return lambda i, u, v: (u[n - 1 - i], 0)


def fused_sparse_adagrad(p, m, uids, gm, valid, lr, eps, interpret=None):
    """Apply the merged sparse adagrad update in one pallas call.
    Same contract as the optim_ops fallback: returns (ParamOut,
    MomentOut) full tables; invalid slots are exact no-ops."""
    if interpret is None:
        interpret = interpret_default()
    n, d = gm.shape
    uids = uids.astype(jnp.int32)
    valid = valid.astype(jnp.int32)
    lr2 = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    row = _row_spec(uids, n)
    kern = functools.partial(_adagrad_kernel, eps=float(eps))
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i, u, v: (0, 0)),
                pl.BlockSpec((1, d), lambda i, u, v, _n=n: (_n - 1 - i, 0)),
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
            ],
            out_specs=[
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(m.shape, m.dtype)],
        # flattened arg indices (scalar prefetch counts): uids 0, valid
        # 1, lr 2, gm 3, p 4, m 5 — tables update in place
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(uids, valid, lr2, gm, p, m)


def fused_sparse_adam(p, m1, m2, uids, gm, valid, lr, b1, b2, eps,
                      interpret=None):
    """Apply the merged sparse adam update in one pallas call. `lr` is
    the bias-corrected rate (the caller applies the beta-pow correction
    exactly as the fallback does). Returns (ParamOut, Moment1Out,
    Moment2Out) full tables."""
    if interpret is None:
        interpret = interpret_default()
    n, d = gm.shape
    uids = uids.astype(jnp.int32)
    valid = valid.astype(jnp.int32)
    lr2 = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    row = _row_spec(uids, n)
    kern = functools.partial(_adam_kernel, b1=float(b1), b2=float(b2),
                             eps=float(eps))
    return pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i, u, v: (0, 0)),
                pl.BlockSpec((1, d), lambda i, u, v, _n=n: (_n - 1 - i, 0)),
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
            ],
            out_specs=[
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
                pl.BlockSpec((1, d), row),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(m1.shape, m1.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype)],
        # uids 0, valid 1, lr 2, gm 3, p 4, m1 5, m2 6
        input_output_aliases={4: 0, 5: 1, 6: 2},
        interpret=interpret,
    )(uids, valid, lr2, gm, p, m1, m2)
