"""Pallas kernel registry + enablement knob (docs/perf.md#kernel-layer).

`ops/` stopped being "one flash-attention file" here: every hand-tiled
kernel registers under a NAME, ships alongside the pure-XLA lowering it
replaces (the fallback contract — with the kernel disabled the op's
lowering is byte-identical to the pre-kernel code path, because the
dispatch sites keep the original jnp code as the `else` branch), and
runs under the pallas interpreter off-TPU so tier-1 drills the real
kernel bodies on `JAX_PLATFORMS=cpu`.

Enablement is per-kernel, resolved at TRACE time (the decision is baked
into the compiled module; the Executor keys its step cache on
`signature()` so flipping the knob recompiles instead of serving the
other variant's cached step):

  * env `PADDLE_TPU_KERNELS` — `0`/`off`/unset: all kernels disabled
    (the default; nothing changes for existing programs); `1`/`on`/
    `all`: every registered kernel; a comma list enables by name, and
    a `-name` entry subtracts (`all,-paged_attention`).
  * `configure(spec)` — the programmatic surface (the predictor-config
    path: `inference.Predictor(..., kernels=...)` routes here). Takes
    the same grammar (str), an iterable of names, a bool, or None to
    fall back to the env. Overrides the env while set.

Dispatch sites call `enabled(name)` (via `lowering.use_kernel`) and bump
the per-kernel dispatch/fallback counters — `kernels.dispatch` /
`kernels.fallback` totals plus `kernels.<name>.dispatch` — at trace
time, so the counters count COMPILED modules carrying the kernel, not
steady-state steps (which re-trace nothing). Each dispatch also writes
a `kernels.dispatch` event (once per trace, for the obs_report
`-- kernels --` section).
"""
import os

from ... import obs

__all__ = ['register_kernel', 'available', 'enabled', 'configure',
           'signature', 'note_dispatch', 'interpret_default',
           'ENV_KERNELS',
           'paged_attention', 'paged_attention_reference',
           'fused_sparse_adagrad', 'fused_sparse_adam']

ENV_KERNELS = 'PADDLE_TPU_KERNELS'

_REGISTRY = {}        # name -> short description (the catalog)
_CONFIG = None        # configure() override; None = consult the env

_C_DISPATCH = obs.counter('kernels.dispatch')
_C_FALLBACK = obs.counter('kernels.fallback')


def register_kernel(name, description=''):
    """Add `name` to the kernel catalog (module import time). Returns the
    name so kernel modules can do `NAME = register_kernel('x', ...)`."""
    _REGISTRY[name] = description
    return name


def available():
    """Registered kernel names, sorted (the catalog docs/perf.md lists)."""
    return tuple(sorted(_REGISTRY))


def _parse(spec):
    """Normalize an enablement spec to a frozenset of enabled names.
    Accepts bool, None/'' (nothing), 'all'/'1'/'on', comma grammar with
    `-name` subtraction, or an iterable of names."""
    if spec is None:
        return frozenset()
    if isinstance(spec, bool):
        return frozenset(_REGISTRY) if spec else frozenset()
    if isinstance(spec, (list, tuple, set, frozenset)):
        return frozenset(str(s) for s in spec)
    s = str(spec).strip().lower()
    if s in ('', '0', 'off', 'false', 'no', 'none'):
        return frozenset()
    on, off = set(), set()
    for tok in s.split(','):
        tok = tok.strip()
        if not tok:
            continue
        if tok in ('1', 'on', 'true', 'all'):
            on |= set(_REGISTRY)
        elif tok.startswith('-'):
            off.add(tok[1:])
        else:
            on.add(tok)
    return frozenset(on - off)


def configure(spec):
    """Set (or with None, clear) the programmatic enablement override.
    Returns the previous override so callers can restore it."""
    global _CONFIG
    prev = _CONFIG
    _CONFIG = spec
    return prev


def _enabled_set():
    if _CONFIG is not None:
        return _parse(_CONFIG)
    return _parse(os.environ.get(ENV_KERNELS))


def enabled(name):
    """Is kernel `name` enabled right now? (Trace-time decision; the
    executor's cache key carries signature() so this never flips a
    cached module.)"""
    return name in _enabled_set()


def signature():
    """Hashable summary of the current enablement, for compile-cache
    keys: the enabled subset of the registered names."""
    return tuple(sorted(_enabled_set() & set(_REGISTRY)))


def note_dispatch(name, used):
    """Record one trace-time routing decision: `used`=True means the
    pallas kernel was emitted, False means the XLA fallback. Called by
    `lowering.use_kernel` — dispatch sites don't bump counters
    themselves."""
    if used:
        _C_DISPATCH.inc()
        obs.counter('kernels.%s.dispatch' % name).inc()
    else:
        _C_FALLBACK.inc()
        obs.counter('kernels.%s.fallback' % name).inc()
    obs.event('kernels.dispatch', kernel=name,
              mode='kernel' if used else 'fallback')


def interpret_default():
    """Pallas interpret mode default: real Mosaic lowering only on a TPU
    backend, the (slow, exact) interpreter everywhere else — the
    ops/flash_attention.py convention that keeps tier-1 green on
    JAX_PLATFORMS=cpu while still executing the kernel bodies."""
    import jax
    return jax.default_backend() != 'tpu'


from .paged_attention import paged_attention, \
    paged_attention_reference  # noqa: E402
from .sparse_optim import fused_sparse_adagrad, \
    fused_sparse_adam  # noqa: E402
