"""Fused paged decode-attention kernel (docs/perf.md#kernel-layer).

The paged decode ops (ops_impl/sampled_ops.py, PR 11) assemble each
slot's encoder rows from fixed-size pages through an int32 page table —
`_gather_paged_enc` materializes [slots, src_cap, D] in HBM (then
`jnp.repeat`s it per beam!) before the attention consumes it. This
kernel fuses the page-table lookup + QK scores + masking + softmax + PV
context into ONE pallas call: pages stream through VMEM via a
scalar-prefetch-indexed BlockSpec (the page table IS the index map —
exactly the shape pltpu.PrefetchScalarGridSpec exists for), the softmax
runs online across a slot's pages (flash-attention style), and the
gathered [slots, src_cap, D] buffer — let alone its beam-replicated
[slots*beam, src_cap, D] copy — never exists in HBM. Per-dispatch HBM
traffic drops from O(C*beam*S*D) to O(C*beam*D + pages-touched), which
is what pays at serving batch sizes.

Numerics: masked positions score `jnp.finfo(f32).min` (the value the
XLA lowering uses), so a fully-masked row degrades to the same
uniform-softmax the oracle produces; positions at or past `src_cap`
score -inf (they are SLICED off in the oracle — exp(-inf)=0 reproduces
the slice). Online vs one-shot softmax reassociates the sum, so parity
vs `paged_attention_reference` is tolerance-bounded, not bitwise:
|kernel - oracle| <= 1e-5 + 1e-5*|oracle| on fp32 (tests/test_kernels.py
drills it; docs/perf.md carries the table).

On-chip alignment: D and page_size should be multiples of the (8, 128)
fp32 tile for Mosaic; the interpreter (CPU tier-1) takes any shape.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import register_kernel, interpret_default

PAGED_ATTENTION = register_kernel(
    'paged_attention',
    'page-table gather + attention + masking fused for the paged decode '
    'ops')

NEG_MASKED = float(jnp.finfo(jnp.float32).min)   # oracle's mask value
LANES = 128


def paged_attention_reference(q, enc_pages, mask_pages, pt_enc, src_cap):
    """XLA oracle: the exact gather + attend math of the paged decode
    lowering (sampled_ops._gather_paged_enc + lod_beam's attend lines),
    kept verbatim so the kernel has a bit-true fallback to A/B against.

    q [B, D] with B = slots*beam (beam rows of one slot contiguous);
    enc_pages [Pe, ps, D]; mask_pages [Pe, ps]; pt_enc [slots, NPE]
    int32. Returns ctx [B, D] float32."""
    pt = pt_enc.astype(jnp.int32)
    C, NPE = pt.shape
    ps, D = enc_pages.shape[1], enc_pages.shape[2]
    enc = jnp.take(enc_pages, pt, axis=0).reshape(C, NPE * ps, D)
    enc = enc[:, :src_cap]
    mask = jnp.take(mask_pages, pt, axis=0).reshape(C, NPE * ps)
    mask = mask[:, :src_cap]
    beam = q.shape[0] // C
    enc_t = jnp.repeat(enc, beam, axis=0)
    mask_t = jnp.repeat(mask, beam, axis=0)
    scores = jnp.einsum('bd,bsd->bs', q, enc_t)
    scores = jnp.where(mask_t > 0, scores, NEG_MASKED)
    alpha = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bs,bsd->bd', alpha, enc_t)


def _kernel(pt_ref, q_ref, page_ref, mask_ref, o_ref, m_s, l_s, acc_s, *,
            page_size, src_cap):
    j = pl.program_id(1)
    npe = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    q = q_ref[0].astype(jnp.float32)                    # [beam, D]
    kpage = page_ref[0].astype(jnp.float32)             # [ps, D]
    mrow = mask_ref[0].astype(jnp.float32)              # [ps]
    beam = q.shape[0]
    s = jnp.dot(q, kpage.T, preferred_element_type=jnp.float32)
    s = jnp.where(mrow[None, :] > 0, s, NEG_MASKED)
    # positions >= src_cap are SLICED off by the oracle; -inf contributes
    # exp(-inf)=0 to the online sum (every page starts below src_cap, so
    # the running max never stays -inf)
    pos = j * page_size + lax.broadcasted_iota(
        jnp.int32, (beam, page_size), 1)
    s = jnp.where(pos < src_cap, s, -jnp.inf)

    m_prev = m_s[:, 0]
    l_prev = l_s[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + p.sum(axis=-1)
    acc_s[:] = acc_s[:] * alpha[:, None] + jnp.dot(
        p, kpage, preferred_element_type=jnp.float32)
    m_s[:] = jnp.broadcast_to(m_new[:, None], m_s.shape)
    l_s[:] = jnp.broadcast_to(l_new[:, None], l_s.shape)

    @pl.when(j == npe - 1)
    def _finish():
        o_ref[0] = (acc_s[:] / jnp.maximum(l_new, 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def paged_attention(q, enc_pages, mask_pages, pt_enc, src_cap,
                    interpret=None):
    """Fused page-gather attention: ctx [B, D] from q [B, D] against the
    paged encoder pool, one pallas call. Same contract as
    `paged_attention_reference` (the dispatch sites' fallback)."""
    if interpret is None:
        interpret = interpret_default()
    pt = pt_enc.astype(jnp.int32)
    C, NPE = pt.shape
    ps, D = enc_pages.shape[1], enc_pages.shape[2]
    B = q.shape[0]
    beam = B // C
    qs = q.astype(jnp.float32).reshape(C, beam, D)
    kern = functools.partial(_kernel, page_size=ps, src_cap=int(src_cap))
    out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(C, NPE),
            in_specs=[
                pl.BlockSpec((1, beam, D), lambda c, j, pt: (c, 0, 0)),
                pl.BlockSpec((1, ps, D), lambda c, j, pt: (pt[c, j], 0, 0)),
                pl.BlockSpec((1, ps), lambda c, j, pt: (pt[c, j], 0)),
            ],
            out_specs=pl.BlockSpec((1, beam, D), lambda c, j, pt: (c, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((beam, LANES), jnp.float32),
                pltpu.VMEM((beam, LANES), jnp.float32),
                pltpu.VMEM((beam, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((C, beam, D), jnp.float32),
        interpret=interpret,
    )(pt, qs, enc_pages, mask_pages)
    return out.reshape(B, D)
