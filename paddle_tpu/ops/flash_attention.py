"""Pallas TPU flash attention (forward + backward kernels).

TPU-first replacement for the reference's attention chain
(benchmark/fluid/models/machine_translation.py + nets.py
scaled_dot_product_attention: QK^T -> softmax -> PV as separate ops, which
materializes the [B,H,Tq,Tk] score matrix in HBM). FlashAttention-2 style:
K/V are tiled through the innermost grid dimension, so VMEM only ever holds
[block_q, D] + [block_k, D] tiles plus the online-softmax state — sequence
length is bounded by HBM, not VMEM. The forward keeps a running
(max, sum, acc) in VMEM scratch across the k-grid; the backward recomputes
probabilities from the saved logsumexp. HBM traffic drops from O(T^2) to
O(T*D).

Supports an additive per-key bias [B, Tk] (padding mask; treated as a
constant — stop_gradient'd by the op lowering) and causal masking —
together these cover every mask the Transformer model builds
(models/transformer.py _pad_mask_bias). Arbitrary [B,H,Tq,Tk] biases fall
back to the XLA path in the op lowering (ops_impl/nn_ops.py).

Causal self-attention (Tq == Tk, square blocks) runs on a LINEARIZED
LOWER-TRIANGLE grid: scalar-prefetch index arrays enumerate only the
(q-block, k-block) pairs on or below the diagonal, so blocks above it are
never computed — causal forward+backward costs ~half the rectangular
FLOPs. See the strategy note above _tri_maps for why this (and not
compute predication) is the safe way to skip blocks under Mosaic.

Off-TPU the kernels run under the pallas interpreter (slow; tests use tiny
shapes) — the op lowering only routes here on real TPU backends.

Degenerate rows whose every key is masked (key_bias=-1e9 on all causally
visible positions) produce garbage outputs/grads in BOTH this kernel and
the XLA oracle — the -1e9 offsets cancel in exp(s - lse), amplifying
rounding noise. Real pad masks never do this (the first key of a sequence
is live); such rows are pad queries whose loss contribution is masked.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e9   # finite mask value: keeps fully-masked rows NaN-free
LANES = 128      # stats scratch is lane-broadcast to keep stores tiled


def _round_up(x, m):
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# grid shapes. Two causal strategies:
#   rectangular  — grid (B, H, nq, nk), every block computed, upper-triangle
#                  blocks masked to NEG_BIG. Predicating the COMPUTE on the
#                  grid position is NOT safe: it desynchronizes Mosaic's
#                  block pipelining when a revisited input block's index map
#                  depends on an outer grid dim (observed: batch>1 +
#                  key-bias blocks read stale data).
#   triangular   — grid (B, H, n_tri) where n_tri enumerates ONLY the
#                  lower-triangle (q-block, k-block) pairs; the (i, j)
#                  coordinates come from scalar-prefetch index arrays
#                  (pltpu.PrefetchScalarGridSpec). Upper blocks are never in
#                  the grid, so causal pays ~half the FLOPs, and every block
#                  is visited exactly once — no predication, so the Mosaic
#                  hazard above never arises. Used when Tq == Tk and
#                  bq == bk (decoder self-attention); anything else falls
#                  back to rectangular.
# ---------------------------------------------------------------------------


def _tri_maps(n):
    """Row-major lower-triangle enumeration: (0,0),(1,0),(1,1),(2,0),...
    Returns int32 (i_map, j_map) with j <= i, length n*(n+1)//2."""
    import numpy as np
    i = np.repeat(np.arange(n), np.arange(1, n + 1))
    j = np.concatenate([np.arange(r + 1) for r in range(n)])
    return i.astype(np.int32), j.astype(np.int32)


def _tri_maps_kv(n):
    """Lower-triangle enumeration ordered for the dk/dv kernel: k-block j
    outer (visited last-to-first), its contributing q-blocks i = j..n-1
    inner, so the (dk, dv) accumulator runs over consecutive steps."""
    import numpy as np
    ii, jj = [], []
    for a in range(n):          # a = n-1-j
        j = n - 1 - a
        ii.append(np.arange(j, n))
        jj.append(np.full(n - j, j))
    return (np.concatenate(ii).astype(np.int32),
            np.concatenate(jj).astype(np.int32))


# ---------------------------------------------------------------------------
# forward kernel body + rectangular/triangular wrappers
# ---------------------------------------------------------------------------

def _fwd_body(q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref,
              m_s, l_s, acc_s, i, j, is_first, is_last, *,
              scale, causal, block_q, block_k):
    @pl.when(is_first)
    def _init():
        m_s[:] = jnp.full_like(m_s, -1e30)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale            # [bq, D]
        kb = k_ref[0, 0].astype(jnp.float32)                   # [bk, D]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        s = s + kb_ref[0, 0][None, :]
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_BIG)
        m_prev = m_s[:, 0]
        l_prev = l_s[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc_s[:] = acc_s[:] * alpha[:, None] + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new[:, None], m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new[:, None], l_s.shape)

    _compute()

    @pl.when(is_last)
    def _finish():
        m, l = m_s[:, 0], jnp.maximum(l_s[:, 0], 1e-30)
        o_ref[0, 0] = (acc_s[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                         lse_ref.shape[2:])


def _fwd_kernel(q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, scale, causal, block_q, block_k):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    _fwd_body(q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref, m_s, l_s, acc_s,
              i, j, j == 0, j == nk - 1,
              scale=scale, causal=causal, block_q=block_q, block_k=block_k)


def _fwd_kernel_tri(im_ref, jm_ref, q_ref, k_ref, v_ref, kb_ref,
                    o_ref, lse_ref, m_s, l_s, acc_s, *,
                    scale, block_q, block_k):
    t = pl.program_id(2)
    i, j = im_ref[t], jm_ref[t]
    # j == 0 starts row i; j == i is the diagonal block, last for row i
    _fwd_body(q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref, m_s, l_s, acc_s,
              i, j, j == 0, j == i,
              scale=scale, causal=True, block_q=block_q, block_k=block_k)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dq_s, i, j, is_first, is_last, *,
                 scale, causal, block_q, block_k):
    @pl.when(is_first)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0, 0][None, :]
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_BIG)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_s[:] = dq_s[:] + jnp.dot(ds, kb,
                                    preferred_element_type=jnp.float32)

    _compute()

    @pl.when(is_last)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_s, *, scale, causal, block_q, block_k):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    _bwd_dq_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dq_s, i, j, j == 0, j == nk - 1,
                 scale=scale, causal=causal, block_q=block_q, block_k=block_k)


def _bwd_dq_kernel_tri(im_ref, jm_ref, q_ref, k_ref, v_ref, kb_ref, do_ref,
                       lse_ref, delta_ref, dq_ref, dq_s, *,
                       scale, block_q, block_k):
    t = pl.program_id(2)
    i, j = im_ref[t], jm_ref[t]
    _bwd_dq_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dq_s, i, j, j == 0, j == i,
                 scale=scale, causal=True, block_q=block_q, block_k=block_k)


def _bwd_dkv_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                  dk_ref, dv_ref, dk_s, dv_s, i, j, is_first, is_last, *,
                  scale, causal, block_q, block_k):
    @pl.when(is_first)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    def _compute():
        k = k_ref[0, 0].astype(jnp.float32)                    # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0].astype(jnp.float32)                   # [bq, D]
        dob = do_ref[0, 0].astype(jnp.float32)
        lse_b = lse_ref[0, 0][:, 0]
        delta_b = delta_ref[0, 0][:, 0]
        s = jnp.dot(qb, k.T, preferred_element_type=jnp.float32) * scale
        s = s + kb_ref[0, 0][None, :]
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_BIG)
        p = jnp.exp(s - lse_b[:, None])                        # [bq, bk]
        dv_s[:] = dv_s[:] + jnp.dot(p.T, dob,
                                    preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_b[:, None]) * scale
        dk_s[:] = dk_s[:] + jnp.dot(ds.T, qb,
                                    preferred_element_type=jnp.float32)

    _compute()

    @pl.when(is_last)
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_s, dv_s, *, scale, causal, block_q,
                    block_k):
    j, i = pl.program_id(2), pl.program_id(3)   # k block outer, q block inner
    nq = pl.num_programs(3)
    _bwd_dkv_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                  dk_ref, dv_ref, dk_s, dv_s, i, j, i == 0, i == nq - 1,
                  scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k)


def _bwd_dkv_kernel_tri(im_ref, jm_ref, q_ref, k_ref, v_ref, kb_ref, do_ref,
                        lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                        scale, block_q, block_k, nq):
    t = pl.program_id(2)
    i, j = im_ref[t], jm_ref[t]
    # contributing q-blocks for k-block j run i = j..nq-1 (tri_maps_kv
    # order): the accumulator starts at the diagonal and ends at the last
    # q-block
    _bwd_dkv_body(q_ref, k_ref, v_ref, kb_ref, do_ref, lse_ref, delta_ref,
                  dk_ref, dv_ref, dk_s, dv_s, i, j, i == j, i == nq - 1,
                  scale=scale, causal=True,
                  block_q=block_q, block_k=block_k)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------

def _use_tri(causal, Tq, Tk, bq, bk):
    """Triangular (block-skipping) causal grid applies to the aligned
    self-attention case; nq == 1 has no upper blocks to skip.
    PADDLE_TPU_FLASH_TRI=0 forces the rectangular fallback (escape hatch
    if a Mosaic version mishandles the scalar-prefetch grid on-chip)."""
    import os
    if os.environ.get('PADDLE_TPU_FLASH_TRI', '1') != '1':
        return False
    return causal and Tq == Tk and bq == bk and Tq // bq > 1


def _tri_specs(bq, bk, D):
    """Shared BlockSpecs for the triangular grids: q-row-indexed [bq, D]
    blocks (q/do/dq), k-col-indexed [bk, D] blocks (k/v/dk/dv), the
    [1, bk] key-bias block, and the q-row [bq, LANES] stats block
    (lse/delta). One definition keeps the three pallas_calls in sync."""
    qrow = pl.BlockSpec((1, 1, bq, D), lambda b, h, t, im, jm: (b, h, im[t], 0))
    kcol = pl.BlockSpec((1, 1, bk, D), lambda b, h, t, im, jm: (b, h, jm[t], 0))
    kbias = pl.BlockSpec((1, 1, bk), lambda b, h, t, im, jm: (b, 0, jm[t]))
    stats = pl.BlockSpec((1, 1, bq, LANES),
                         lambda b, h, t, im, jm: (b, h, im[t], 0))
    return qrow, kcol, kbias, stats


def _fwd_call(q, k, v, kb, causal, scale, bq, bk, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((B, H, Tq, LANES), jnp.float32),
    ]
    scratch_shapes = [
        pltpu.VMEM((bq, LANES), jnp.float32),
        pltpu.VMEM((bq, LANES), jnp.float32),
        pltpu.VMEM((bq, D), jnp.float32),
    ]
    if _use_tri(causal, Tq, Tk, bq, bk):
        im, jm = _tri_maps(Tq // bq)
        qrow, kcol, kbias, stats = _tri_specs(bq, bk, D)
        kern = functools.partial(_fwd_kernel_tri, scale=scale,
                                 block_q=bq, block_k=bk)
        return pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(B, H, len(im)),
                in_specs=[qrow, kcol, kcol, kbias],
                out_specs=[qrow, stats],
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(jnp.asarray(im), jnp.asarray(jm), q, k, v, kb)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=bq, block_k=bk)
    return pl.pallas_call(
        kern,
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(q, k, v, kb)


def _bwd_call_tri(q, k, v, kb, do, lse, delta, scale, bq, bk, interpret):
    """Causal backward over the linearized lower-triangle grid (see the
    strategy note at the top): dq accumulates over a q-row's k-blocks, then
    dk/dv re-walk the triangle k-block-major (_tri_maps_kv order)."""
    B, H, Tq, D = q.shape
    nq = Tq // bq
    qrow, kcol, kbias, stats = _tri_specs(bq, bk, D)
    bwd_in_specs = [qrow, kcol, kcol, kbias, qrow, stats, stats]
    im, jm = _tri_maps(nq)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_tri, scale=scale,
                          block_q=bq, block_k=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, len(im)),
            in_specs=bwd_in_specs,
            out_specs=qrow,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(jnp.asarray(im), jnp.asarray(jm), q, k, v, kb, do, lse, delta)
    im2, jm2 = _tri_maps_kv(nq)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_tri, scale=scale,
                          block_q=bq, block_k=bk, nq=nq),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, len(im2)),
            in_specs=bwd_in_specs,
            out_specs=[kcol, kcol],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(jnp.asarray(im2), jnp.asarray(jm2), q, k, v, kb, do, lse, delta)
    return dq, dk, dv


def _bwd_call(q, k, v, kb, do, lse, delta, causal, scale, bq, bk, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if _use_tri(causal, Tq, Tk, bq, bk):
        return _bwd_call_tri(q, k, v, kb, do, lse, delta, scale, bq, bk,
                             interpret)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, kb, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(B, H, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, j, i: (b, 0, j)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, j, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, kb, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_lse(q, k, v, kb, causal, scale, bq, bk, interpret):
    o, lse = _fwd_call(q, k, v, kb, causal, scale, bq, bk, interpret)
    return o, lse[..., 0]


def _flash_lse_fwd(q, k, v, kb, causal, scale, bq, bk, interpret):
    o, lse = _fwd_call(q, k, v, kb, causal, scale, bq, bk, interpret)
    return (o, lse[..., 0]), (q, k, v, kb, o, lse)


def _flash_lse_bwd(causal, scale, bq, bk, interpret, res, cot):
    """Backward with an lse cotangent, sharing the kernels unchanged:
    lse = logsumexp(S) gives dS|lse = P * dlse, and the kernels compute
    dS = P * (dP - delta), so folding delta' = delta - dlse routes the lse
    gradient through the same two pallas calls (the FlashAttention D-trick
    extended one term)."""
    do, dlse = cot
    q, k, v, kb, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))
    dq, dk, dv = _bwd_call(q, k, v, kb, do, lse, delta, causal, scale,
                           bq, bk, interpret)
    # kb is a mask constant (see module docstring): zero cotangent
    return dq, dk, dv, jnp.zeros_like(kb)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# On-chip tuned tile defaults (tools/tune_flash.py sweep, TPU v5e, bf16,
# D in {64, 128}, T in {256, 1024}, full fwd+bwd timed as chained
# on-device steps advancing q, k AND v — the axon tunnel's
# block_until_ready returns early, and a chain consuming only dq would
# DCE the dk/dv kernel): 512x512 tiles win at every swept shape, ~40%
# over the old 128/128 (8.27 -> 4.84 ms/step at causal T=1024 D=64;
# 3.97-4.10 ms/step at T=1024 D=128; T=256 clips to 256x256, its own
# winner). Equal bq == bk keeps the causal triangular block-skipping grid
# eligible (_use_tri). Shorter sequences clip the tiles in _prep
# automatically. PADDLE_TPU_FLASH_BQ/BK override.
_TUNED_BQ_BK = {True: (512, 512), False: (512, 512)}


def _prep(q, k, v, key_bias, sm_scale, block_q, block_k, interpret,
          causal=False):
    """Shared block-size/padding/bias plumbing for the public wrappers."""
    import os
    tuned_bq, tuned_bk = _TUNED_BQ_BK[bool(causal)]
    if block_q is None:
        block_q = int(os.environ.get('PADDLE_TPU_FLASH_BQ', tuned_bq))
    if block_k is None:
        block_k = int(os.environ.get('PADDLE_TPU_FLASH_BK', tuned_bk))
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    if key_bias is None:
        key_bias = jnp.zeros((B, Tk), jnp.float32)
    else:
        key_bias = key_bias.reshape(B, Tk).astype(jnp.float32)
    key_bias = lax.stop_gradient(key_bias)
    bq = min(block_q, _round_up(Tq, 128))
    bk = min(block_k, _round_up(Tk, 128))
    Tq_p = _round_up(Tq, bq)
    Tk_p = _round_up(Tk, bk)
    if Tq_p != Tq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Tq_p - Tq), (0, 0)))
    if Tk_p != Tk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Tk_p - Tk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Tk_p - Tk), (0, 0)))
        key_bias = jnp.pad(key_bias, ((0, 0), (0, Tk_p - Tk)),
                           constant_values=NEG_BIG)
    # (B, 1, Tk): Mosaic block shapes need the sublane dim to equal the
    # array dim, so the bias carries an explicit singleton sublane
    key_bias = key_bias.reshape(B, 1, Tk_p)
    return (q, k, v, key_bias, float(sm_scale), int(bq), int(bk),
            bool(interpret), Tq, Tq_p)


def flash_attention_lse(q, k, v, key_bias=None, causal=False, sm_scale=None,
                        block_q=None, block_k=None, interpret=None):
    """flash_attention that ALSO returns the per-query logsumexp
    ([B, H, Tq], f32) — the combine statistic ring attention needs to merge
    partial attention over key shards. Differentiable in q/k/v through BOTH
    outputs (see _flash_lse_bwd)."""
    (q, k, v, kb, scale, bq, bk, interp, Tq, Tq_p) = _prep(
        q, k, v, key_bias, sm_scale, block_q, block_k, interpret,
        causal=causal)
    o, lse = _flash_lse(q, k, v, kb, bool(causal), scale, bq, bk, interp)
    if Tq_p != Tq:
        o = o[:, :, :Tq, :]
        lse = lse[:, :, :Tq]
    return o, lse


def flash_attention(q, k, v, key_bias=None, causal=False, sm_scale=None,
                    block_q=None, block_k=None, interpret=None):
    """Flash attention over [B, H, T, D] tensors.

    key_bias: optional additive [B, Tk] bias (e.g. -1e9 on padded keys);
              treated as a non-differentiable mask.
    causal:   lower-triangular masking (decoder self-attention).
    block_q/block_k: kernel tile sizes (defaults from the on-chip-tuned
              _TUNED_BQ_BK table — causal 256/256, else 256/128 —
              overridable with PADDLE_TPU_FLASH_BQ / PADDLE_TPU_FLASH_BK;
              see tools/tune_flash.py for the sweep).
    Returns [B, H, Tq, D] in q's dtype; differentiable w.r.t. q/k/v.
    """
    # one custom_vjp serves both wrappers: the unused lse output gets a
    # zero cotangent, making _flash_lse_bwd exactly the classic backward
    o, _ = flash_attention_lse(q, k, v, key_bias=key_bias, causal=causal,
                               sm_scale=sm_scale, block_q=block_q,
                               block_k=block_k, interpret=interpret)
    return o


def reference_attention(q, k, v, key_bias=None, causal=False, sm_scale=None):
    """Plain-XLA attention with the same signature (fallback + test oracle).
    key_bias is stop_gradient'd to match the kernel's semantics."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    s = jnp.einsum('bhqd,bhkd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if key_bias is not None:
        s = s + lax.stop_gradient(
            key_bias.reshape(B, 1, 1, Tk).astype(jnp.float32))
    if causal:
        qpos = jnp.arange(Tq)[:, None]
        kpos = jnp.arange(Tk)[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', p,
                      v.astype(jnp.float32)).astype(q.dtype)
