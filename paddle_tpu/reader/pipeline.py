"""Background host->device prefetch pipeline.

TPU-first equivalent of the reference's C++ double_buffer reader
(paddle/fluid/operators/reader/create_double_buffer_reader_op.cc): a
daemon thread stages upcoming batches so device steps never wait on host
IO. A C++ staged loader (paddle_tpu/csrc) backs the recordio path.

Contract (regression-tested in tests/test_reader.py):
  * a reader exception is RE-RAISED in the consumer — not swallowed into
    a silent short epoch;
  * a consumer that stops early (break, generator close) unblocks the
    worker thread, which would otherwise sit in q.put forever;
  * `transform` runs in the worker thread — the hook for host->device
    staging (jax.device_put / Executor._to_device / DataFeeder.feed), so
    transfer cost overlaps the consumer's step. `bundle` groups batches
    into the K-step lists Executor.run_bundle consumes.
"""
import sys
from queue import Empty, Full, Queue
from threading import Event, Thread

__all__ = ['prefetch', 'bundle']

_END = object()
# how long the worker's q.put may block before re-checking whether the
# consumer has gone away (early break/close sets the stop event)
_PUT_POLL_S = 0.05


class _WorkerError(object):
    """Carries the worker's exc_info across the queue so the consumer
    re-raises the ORIGINAL exception with its traceback."""

    __slots__ = ('exc_info',)

    def __init__(self, exc_info):
        self.exc_info = exc_info


def prefetch(reader, depth=2, transform=None):
    """Wrap a generator-factory with an N-deep background prefetch queue.

    transform(item), when given, runs IN THE WORKER THREAD on every item
    before it is queued — e.g. ``transform=exe._to_device`` (or a feeder
    + device_put composition) stages upcoming batches onto the device
    while the previous step still runs, which is what feeds
    `Executor.run_bundle`'s stacker without a host stall."""

    def wrapped():
        q = Queue(maxsize=depth)
        stop = Event()

        def _put(item):
            """Blocking put that gives up when the consumer is gone.
            Returns False when the stop event fired first."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=_PUT_POLL_S)
                    return True
                except Full:
                    continue
            return False

        def worker():
            try:
                for item in reader():
                    if transform is not None:
                        item = transform(item)
                    if not _put(item):
                        return
            except BaseException:
                # propagate to the consumer — the old `finally: put(_END)`
                # shape turned a reader crash into a silent short epoch
                _put(_WorkerError(sys.exc_info()))
                return
            _put(_END)

        t = Thread(target=worker)
        t.daemon = True
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, _WorkerError):
                    _tp, exc, tb = item.exc_info
                    raise exc.with_traceback(tb)
                yield item
        finally:
            # consumer done (exhausted, break, or close()): release the
            # worker — set the stop flag, then drain so a put blocked
            # between polls returns immediately
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except Empty:
                pass

    return wrapped


def bundle(reader, steps, drop_last=False):
    """Group a batch reader into lists of `steps` consecutive batches —
    the per-step feed lists `Executor.run_bundle` / a
    `Trainer(bundle_steps=K)` loop consume. The final short group is
    yielded unless drop_last (a short group still runs; it just compiles
    its own scan length once)."""
    if steps < 1:
        raise ValueError('bundle steps must be >= 1, got %r' % (steps,))

    def wrapped():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == steps:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return wrapped
