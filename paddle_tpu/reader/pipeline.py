"""Background host->device prefetch pipeline.

TPU-first equivalent of the reference's C++ double_buffer reader
(paddle/fluid/operators/reader/create_double_buffer_reader_op.cc): a
daemon thread stages upcoming batches so device steps never wait on host
IO. A C++ staged loader (paddle_tpu/csrc) backs the recordio path.
"""
from queue import Queue
from threading import Thread

__all__ = ['prefetch']

_END = object()


def prefetch(reader, depth=2):
    """Wrap a generator-factory with an N-deep background prefetch queue."""

    def wrapped():
        q = Queue(maxsize=depth)

        def worker():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_END)

        t = Thread(target=worker)
        t.daemon = True
        t.start()
        while True:
            item = q.get()
            if item is _END:
                break
            yield item

    return wrapped
