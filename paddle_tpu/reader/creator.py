"""Reader creators.

Parity: reference python/paddle/reader/creator.py — build sample readers
from in-memory arrays, text files, and recordio chunk files.
"""
__all__ = ['np_array', 'text_file', 'recordio']


def np_array(x):
    """Reader yielding the rows of a numpy array (reference
    creator.py:np_array)."""
    import numpy as np
    arr = np.asarray(x)

    def reader():
        for row in arr:
            yield row

    return reader


def text_file(path):
    """Reader yielding lines of a text file without the trailing newline
    (reference creator.py:text_file)."""

    def reader():
        with open(path, 'r') as f:
            for line in f:
                yield line.rstrip('\n')

    return reader


def recordio(paths, buf_size=100):
    """Reader yielding raw records from recordio chunk file(s); paths is a
    path or comma-separated list (reference creator.py:recordio, minus the
    cloud-reader branch which served the retired pserver infrastructure)."""
    from . import recordio as rio

    if isinstance(paths, str):
        path_list = paths.split(',')
    else:
        path_list = list(paths)

    def reader():
        for p in path_list:
            for rec in rio.RecordIOReader(p):
                yield rec

    return reader
