"""Chunked record file format (recordio equivalent).

Parity: reference paddle/fluid/recordio/ (C++ chunked writer/reader with
per-chunk checksums) + python recordio usage in benchmark/fluid.
Format: magic | per-record [u32 len | payload] with chunk framing; the
C++ fast path (paddle_tpu/csrc/recordio.cpp) mmaps and parses chunks; this
module is the pure-python fallback and the writer.
"""
import os
import struct
import zlib

import numpy as np

__all__ = ['RecordIOWriter', 'RecordIOReader', 'write_samples', 'read_samples',
           'convert_reader_to_recordio_file']

_MAGIC = b'PTRIO1\n'


class RecordIOWriter(object):
    def __init__(self, path):
        self._f = open(path, 'wb')
        self._f.write(_MAGIC)

    def write(self, payload: bytes):
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(struct.pack('<II', len(payload), crc))
        self._f.write(payload)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordIOReader(object):
    def __init__(self, path):
        self.path = path

    def __iter__(self):
        with open(self.path, 'rb') as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError("%s is not a paddle_tpu recordio file" % self.path)
            while True:
                hdr = f.read(8)
                if not hdr:
                    break
                if len(hdr) < 8:
                    raise IOError("truncated record header in %s (file cut "
                                  "mid-write?)" % self.path)
                ln, crc = struct.unpack('<II', hdr)
                payload = f.read(ln)
                if len(payload) < ln:
                    raise IOError("truncated record payload in %s" % self.path)
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise IOError("checksum mismatch in %s" % self.path)
                yield payload


def _pack_sample(arrays):
    parts = [struct.pack('<I', len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = a.dtype.str.encode()
        parts.append(struct.pack('<I', len(dt)))
        parts.append(dt)
        parts.append(struct.pack('<I', a.ndim))
        parts.append(struct.pack('<%dq' % a.ndim, *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack('<Q', len(raw)))
        parts.append(raw)
    return b''.join(parts)


def _unpack_sample(payload):
    off = 0

    def take(n):
        nonlocal off
        out = payload[off:off + n]
        off += n
        return out

    n_arr, = struct.unpack('<I', take(4))
    out = []
    for _ in range(n_arr):
        dt_len, = struct.unpack('<I', take(4))
        dt = take(dt_len).decode()
        ndim, = struct.unpack('<I', take(4))
        shape = struct.unpack('<%dq' % ndim, take(8 * ndim))
        raw_len, = struct.unpack('<Q', take(8))
        arr = np.frombuffer(take(raw_len), dtype=np.dtype(dt)).reshape(shape)
        out.append(arr)
    return tuple(out)


def write_samples(path, samples):
    with RecordIOWriter(path) as w:
        n = 0
        for s in samples:
            if not isinstance(s, (list, tuple)):
                s = (s,)
            w.write(_pack_sample([np.asarray(x) for x in s]))
            n += 1
    return n


def read_samples(path, shapes=None, dtypes=None, prefetch_depth=4):
    # C++ fast path when the native library is built: a background thread
    # scans+checksums records while Python decodes the previous one. The
    # fallback decision happens BEFORE the first yield — mid-stream errors
    # (corruption etc.) propagate rather than silently re-reading.
    use_native = False
    try:
        from ..utils import native
        use_native = native.available()
    except Exception:
        pass
    if use_native:
        it = (native.recordio_prefetch_iter(path, prefetch_depth)
              if prefetch_depth else native.recordio_iter(path))
    else:
        it = iter(RecordIOReader(path))
    for payload in it:
        yield _unpack_sample(payload)


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None):
    """Parity: fluid.recordio_writer.convert_reader_to_recordio_file."""
    return write_samples(filename, reader_creator())
