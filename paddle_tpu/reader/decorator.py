"""Reader decorators, importable at the reference's module path.

Parity: reference python/paddle/reader/decorator.py. The implementations
live in paddle_tpu.reader (the package __init__, where the reference
re-exports them anyway); this module mirrors the reference layout so
`from paddle.reader.decorator import shuffle`-style imports port verbatim.
"""
from . import (Fake, ComposeNotAligned, PipeReader, buffered, cache, chain,
               compose, fault_tolerant, firstn, map_readers, shuffle,
               xmap_readers)

__all__ = [
    'map_readers', 'buffered', 'compose', 'chain', 'shuffle',
    'ComposeNotAligned', 'firstn', 'xmap_readers', 'Fake', 'cache',
    'PipeReader', 'fault_tolerant',
]
