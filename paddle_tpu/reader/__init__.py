"""Reader decorators. Parity: reference python/paddle/reader/decorator.py."""
import itertools
import random
from queue import Queue
from threading import Condition, Thread

__all__ = [
    'map_readers', 'buffered', 'compose', 'chain', 'shuffle',
    'ComposeNotAligned', 'firstn', 'xmap_readers', 'Fake', 'cache',
    'PipeReader', 'fault_tolerant', 'shard',
]

from . import pipeline  # noqa: F401
from . import recordio  # noqa: F401
from . import creator  # noqa: F401


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e
    return reader


def shuffle(reader, buf_size):
    """Block shuffle: consume the stream in blocks of up to buf_size
    samples and yield each block in random order. buf_size >= dataset
    size gives a full shuffle; smaller sizes trade memory for locality."""
    def data_reader():
        it = iter(reader())
        while True:
            block = list(itertools.islice(it, buf_size))
            if not block:
                return
            random.shuffle(block)
            yield from block
    return data_reader


def shard(reader, num_shards, shard_id):
    """Per-host reader sharding for the multi-process GSPMD runtime
    (docs/parallel.md): host `shard_id` of `num_shards` sees every
    num_shards-th sample (round-robin by stream index), so the hosts'
    slices partition the stream without coordination and — batched with
    the same batch size — reassemble into the global batch the Executor
    builds via `parallel.global_batch`. Deterministic over a
    deterministic source; compose as
    ``paddle.batch(reader.shard(base, n_hosts, host_id), bs_per_host)``.

    Samples beyond the last complete round are DROPPED (not yielded to
    any shard): an uneven tail would give the hosts different step
    counts, deadlocking the collective at the shorter host's last step.
    """
    num_shards = int(num_shards)
    shard_id = int(shard_id)
    if num_shards < 1:
        raise ValueError('num_shards must be >= 1, got %d' % num_shards)
    if not 0 <= shard_id < num_shards:
        raise ValueError('shard_id %d out of range for %d shard(s)'
                         % (shard_id, num_shards))

    def sharded_reader():
        it = iter(reader())
        while True:
            block = list(itertools.islice(it, num_shards))
            if len(block) < num_shards:
                return   # incomplete round: dropped on every host
            yield block[shard_id]
    return sharded_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e
    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip several readers into one, concatenating their samples into a
    flat tuple per step. With check_alignment (default), a reader ending
    before the others raises ComposeNotAligned; without it, the stream
    silently stops at the shortest reader."""
    check_alignment = kwargs.pop('check_alignment', True)
    _missing = object()

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
            return
        for outputs in itertools.zip_longest(*rs, fillvalue=_missing):
            if any(o is _missing for o in outputs):
                raise ComposeNotAligned(
                    "outputs of composed readers are not aligned: one "
                    "reader ended before the others")
            yield sum(map(make_tuple, outputs), ())
    return reader


def buffered(reader, size):
    """Decouple production from consumption: a daemon thread runs the
    source reader up to `size` samples ahead of the consumer."""
    def data_reader():
        done = object()
        q = Queue(maxsize=size)
        failure = []

        def pump():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:   # re-raised at the consumer
                failure.append(e)
            finally:
                q.put(done)

        Thread(target=pump, daemon=True).start()
        yield from iter(q.get, done)
        if failure:
            raise failure[0]
    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


class XmapEndSignal():
    """Kept for API compat with code that imported it; the pool below uses
    private sentinels."""


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool map over a reader (API parity with the reference's
    xmap_readers; the pool itself is a from-scratch design).

    A feeder thread enumerates the source into a bounded feed queue as
    (seq, sample); process_num workers apply `mapper` concurrently and
    push results to a bounded output queue. With order=True a Condition
    gates each push until the worker's seq is next — workers sleep on the
    condition rather than spinning, so a slow mapper never busy-waits the
    (single-core) host. A mapper exception is forwarded to the consumer
    and re-raised there instead of hanging the stream."""
    def xreader():
        stop = object()
        feed_q = Queue(buffer_size)
        out_q = Queue(buffer_size)
        turn = Condition()
        state = {'next_seq': 0, 'error': None}

        def feeder():
            try:
                for item in enumerate(reader()):
                    feed_q.put(item)
            except BaseException as e:   # source errors forward too
                with turn:
                    state['error'] = e
                    turn.notify_all()
            finally:
                for _ in range(process_num):
                    feed_q.put(stop)

        def worker():
            while True:
                item = feed_q.get()
                if item is stop:
                    out_q.put(stop)
                    return
                seq, sample = item
                try:
                    result = mapper(sample)
                except BaseException as e:   # forwarded, not swallowed
                    with turn:
                        state['error'] = e
                        turn.notify_all()
                    out_q.put(stop)
                    return
                if order:
                    with turn:
                        turn.wait_for(
                            lambda: state['next_seq'] == seq
                            or state['error'] is not None)
                        if state['error'] is not None:
                            out_q.put(stop)
                            return
                        out_q.put(result)
                        state['next_seq'] += 1
                        turn.notify_all()
                else:
                    out_q.put(result)

        Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            Thread(target=worker, daemon=True).start()

        # every worker flushes its results before its stop marker, so the
        # stream is complete once all process_num markers are seen
        finished = 0
        while finished < process_num:
            item = out_q.get()
            if item is stop:
                finished += 1
                if state['error'] is not None:
                    raise state['error']
            else:
                yield item
    return xreader


def fault_tolerant(reader, max_retries=3, retry_on=(IOError, OSError),
                   base_delay=0.05, max_delay=2.0, seed=None,
                   sleep=None):
    """Make a reader survive transient source failures (flaky NFS / GCS /
    preempted sidecar): when iterating the stream raises a `retry_on`
    exception, the source reader is re-opened (with utils.retry's
    exponential-backoff schedule) and fast-forwarded past the samples
    already emitted, so the consumer sees no duplicates and no gaps.
    After `max_retries` re-opens the stream DEGRADES instead of dying: a
    loud RuntimeWarning reports how many samples were delivered and the
    epoch ends early — a multi-hour training job keeps its progress and
    checkpoints rather than crashing on a bad input shard.

    REQUIRES a deterministic source: the fast-forward skips the first
    `emitted` samples of the re-opened stream by INDEX, which only
    reproduces the already-delivered prefix if the reader yields the same
    order every time. Wrap the deterministic base reader and put
    nondeterministic decorators (shuffle) OUTSIDE:
    `shuffle(fault_tolerant(base), buf)` — wrapping `shuffle` itself
    would silently duplicate/drop samples across a retry.

    sleep is injectable for tests (None = time.sleep).

    Telemetry (docs/observability.md): every source re-open bumps the
    reader.retries counter and records a reader.retry event; a degrade
    bumps reader.degraded and records reader.degrade with how many
    samples survived; per-sample production latency feeds the
    reader.batch.seconds histogram — a slow input pipeline shows up in
    obs_report next to the step times it is starving."""
    import time as _time
    import warnings

    from .. import obs
    from ..utils.retry import backoff_delays

    def fault_tolerant_reader():
        emitted = 0
        delays = backoff_delays(max_retries, base_delay=base_delay,
                                max_delay=max_delay, seed=seed)
        do_sleep = _time.sleep if sleep is None else sleep
        latency = obs.histogram('reader.batch.seconds')
        while True:
            try:
                src = enumerate(reader())
                while True:
                    t0 = _time.perf_counter()
                    try:
                        i, sample = next(src)
                    except StopIteration:
                        return
                    if i < emitted:
                        continue  # fast-forward past a replayed prefix
                    # observed only for DELIVERED samples: replayed
                    # prefixes (usually page-cache fast) would skew the
                    # latency histogram low after a retry
                    latency.observe(_time.perf_counter() - t0)
                    yield sample
                    emitted += 1
            except retry_on as e:
                delay = next(delays, None)
                if delay is None:
                    obs.counter('reader.degraded').inc()
                    obs.event('reader.degrade', emitted=emitted,
                              attempts=max_retries + 1, error=repr(e))
                    warnings.warn(
                        'fault_tolerant reader: source failed %d times '
                        '(last: %r); degrading to skip — stream ends '
                        'after %d sample(s) instead of raising'
                        % (max_retries + 1, e, emitted), RuntimeWarning)
                    return
                obs.counter('reader.retries').inc()
                obs.event('reader.retry', emitted=emitted,
                          delay_s=delay, error=repr(e))
                do_sleep(delay)

    return fault_tolerant_reader


def cache(reader):
    all_data = []

    def __impl__():
        if not all_data:
            for d in reader():
                all_data.append(d)
                yield d
        else:
            for d in all_data:
                yield d
    return __impl__


class PipeReader(object):
    """Stream data from a shell command's stdout (reference
    decorator.py:PipeReader) — e.g. ``hadoop fs -cat ...``, ``curl ...``.
    file_type 'gzip' transparently inflates; get_line() yields decoded
    lines (or raw buffers with cut_lines=False). Unlike the reference,
    commands are shlex-split (quoted paths with spaces work), multi-byte
    characters may straddle buffer boundaries, a failing command raises
    instead of silently truncating the dataset, and abandoning the
    generator early terminates the child (no leaked processes)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import shlex
        import subprocess
        import zlib
        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type %s is not allowed" % file_type)
        self.command = command
        self.file_type = file_type
        if file_type == "gzip":
            # wbits offset 32: auto-detect the gzip header
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            shlex.split(command), bufsize=bufsize, stdout=subprocess.PIPE)

    def close(self):
        """Terminate + reap the child (idempotent; safe mid-stream)."""
        p = self.process
        if p.poll() is None:
            p.terminate()
        if p.stdout is not None:
            p.stdout.close()
        p.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        decoder = codecs.getincrementaldecoder('utf-8')()
        remained = ""
        finished = False
        try:
            while True:
                buff = self.process.stdout.read(self.bufsize)
                if buff:
                    if self.file_type == "gzip":
                        buff = self.dec.decompress(buff)
                    # incremental: multi-byte chars may straddle chunks
                    decomp_buff = decoder.decode(buff)
                    if cut_lines:
                        lines = decomp_buff.split(line_break)
                        lines[0] = remained + lines[0]
                        remained = lines.pop()  # possibly-partial tail
                        for line in lines:
                            yield line
                    else:
                        if decomp_buff:
                            yield decomp_buff
                else:
                    remained += decoder.decode(b'', final=True)
                    if remained:
                        yield remained
                    finished = True
                    break
        finally:
            if finished:
                rc = self.process.wait()
                if rc != 0:
                    raise IOError(
                        "PipeReader command %r exited with %d — dataset "
                        "stream is incomplete" % (self.command, rc))
            else:
                self.close()  # consumer abandoned the stream


class Fake(object):
    """Cache the first sample and replay it n times (reference
    decorator.py:Fake) — for IO-free benchmarking."""

    def __init__(self):
        self.data = None
        self.yield_num = 0

    def _read_into_memory(self, reader):
        self.data = next(reader())

    def __call__(self, reader, n):
        def fake_reader():
            if self.data is None:
                self._read_into_memory(reader)
            while self.yield_num < n:
                yield self.data
                self.yield_num += 1
            self.yield_num = 0
        return fake_reader
