"""Reader decorators. Parity: reference python/paddle/reader/decorator.py."""
import itertools
import random
from queue import Queue
from threading import Thread

__all__ = [
    'map_readers', 'buffered', 'compose', 'chain', 'shuffle',
    'ComposeNotAligned', 'firstn', 'xmap_readers', 'Fake', 'cache',
    'PipeReader',
]

from . import pipeline  # noqa: F401
from . import recordio  # noqa: F401
from . import creator  # noqa: F401


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e
    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if len(buf) > 0:
            random.shuffle(buf)
            for b in buf:
                yield b
    return data_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e
    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        else:
            return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in zip(*rs):
                lens = set(map(len, outputs)) if all(
                    isinstance(o, tuple) for o in outputs) else None
                yield sum(list(map(make_tuple, outputs)), ())
    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples in a background thread."""

    class EndSignal():
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()
    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


class XmapEndSignal():
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (reference
    decorator.py:xmap_readers)."""
    end = XmapEndSignal()

    def read_worker(reader, in_queue):
        for i in reader():
            in_queue.put(i)
        in_queue.put(end)

    def order_read_worker(reader, in_queue):
        in_order = 0
        for i in reader():
            in_queue.put((in_order, i))
            in_order += 1
        in_queue.put(end)

    def handle_worker(in_queue, out_queue, mapper):
        sample = in_queue.get()
        while not isinstance(sample, XmapEndSignal):
            r = mapper(sample)
            out_queue.put(r)
            sample = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def order_handle_worker(in_queue, out_queue, mapper, out_order):
        ins = in_queue.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            r = mapper(sample)
            while order != out_order[0]:
                pass
            out_queue.put(r)
            out_order[0] += 1
            ins = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def xreader():
        in_queue = Queue(buffer_size)
        out_queue = Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = Thread(target=target, args=(reader, in_queue))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_queue, out_queue, mapper, out_order) if order else (
            in_queue, out_queue, mapper)
        workers = []
        for i in range(process_num):
            worker = Thread(target=target, args=args)
            worker.daemon = True
            workers.append(worker)
        for w in workers:
            w.start()
        # drain until EVERY worker has signalled end — each worker enqueues
        # all of its samples before its end signal, so counting all
        # process_num ends guarantees no tail sample is dropped
        finished = 0
        while finished < process_num:
            sample = out_queue.get()
            if isinstance(sample, XmapEndSignal):
                finished += 1
            else:
                yield sample
    return xreader


def cache(reader):
    all_data = []

    def __impl__():
        if not all_data:
            for d in reader():
                all_data.append(d)
                yield d
        else:
            for d in all_data:
                yield d
    return __impl__


class PipeReader(object):
    """Stream data from a shell command's stdout (reference
    decorator.py:PipeReader) — e.g. ``hadoop fs -cat ...``, ``curl ...``.
    file_type 'gzip' transparently inflates; get_line() yields decoded
    lines (or raw buffers with cut_lines=False). Unlike the reference,
    commands are shlex-split (quoted paths with spaces work), multi-byte
    characters may straddle buffer boundaries, a failing command raises
    instead of silently truncating the dataset, and abandoning the
    generator early terminates the child (no leaked processes)."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import shlex
        import subprocess
        import zlib
        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type %s is not allowed" % file_type)
        self.command = command
        self.file_type = file_type
        if file_type == "gzip":
            # wbits offset 32: auto-detect the gzip header
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            shlex.split(command), bufsize=bufsize, stdout=subprocess.PIPE)

    def close(self):
        """Terminate + reap the child (idempotent; safe mid-stream)."""
        p = self.process
        if p.poll() is None:
            p.terminate()
        if p.stdout is not None:
            p.stdout.close()
        p.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        decoder = codecs.getincrementaldecoder('utf-8')()
        remained = ""
        finished = False
        try:
            while True:
                buff = self.process.stdout.read(self.bufsize)
                if buff:
                    if self.file_type == "gzip":
                        buff = self.dec.decompress(buff)
                    # incremental: multi-byte chars may straddle chunks
                    decomp_buff = decoder.decode(buff)
                    if cut_lines:
                        lines = decomp_buff.split(line_break)
                        lines[0] = remained + lines[0]
                        remained = lines.pop()  # possibly-partial tail
                        for line in lines:
                            yield line
                    else:
                        if decomp_buff:
                            yield decomp_buff
                else:
                    remained += decoder.decode(b'', final=True)
                    if remained:
                        yield remained
                    finished = True
                    break
        finally:
            if finished:
                rc = self.process.wait()
                if rc != 0:
                    raise IOError(
                        "PipeReader command %r exited with %d — dataset "
                        "stream is incomplete" % (self.command, rc))
            else:
                self.close()  # consumer abandoned the stream


class Fake(object):
    """Cache the first sample and replay it n times (reference
    decorator.py:Fake) — for IO-free benchmarking."""

    def __init__(self):
        self.data = None
        self.yield_num = 0

    def _read_into_memory(self, reader):
        self.data = next(reader())

    def __call__(self, reader, n):
        def fake_reader():
            if self.data is None:
                self._read_into_memory(reader)
            while self.yield_num < n:
                yield self.data
                self.yield_num += 1
            self.yield_num = 0
        return fake_reader
