"""DeepFM / wide&deep CTR model with high-dimensional sparse features.

Parity: BASELINE.json config 5 (DeepFM CTR, pserver->ICI allreduce); the
reference trains CTR models through fluid embedding + fc layers with
is_sparse lookups and pserver distribution. TPU-first: embeddings are dense
gathers fused by XLA (gradient = scatter-add in the same module) and
distribution is GSPMD data-parallel; the embedding table can additionally be
sharded over the mesh (paddle_tpu.parallel) when it exceeds one chip's HBM.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

__all__ = ['deepfm', 'get_model', 'synthetic_ctr_reader']

NUM_FIELDS = 26
VOCAB = 100000


def deepfm(feat_ids, label, num_fields=NUM_FIELDS, vocab_size=VOCAB,
           embed_dim=10, hidden=[400, 400, 400], dist_axis=None,
           is_sparse=False):
    """feat_ids: int64 [B, num_fields]; one id per field.

    dist_axis: row-shard both FM tables over this mesh axis (the sharded-
    embedding subsystem, docs/embedding.md) — pair with
    `Program.set_mesh({dist_axis: N, ...})` and is_sparse=True for
    sharded-sparse training; vocab_size must be a multiple of the axis
    size (embedding.pad_vocab)."""
    def _table(name):
        sharding = (dist_axis, None) if dist_axis else None
        return fluid.ParamAttr(name=name, sharding=sharding)

    dist = dist_axis is not None
    # ---- FM first order: w[ids] summed over fields
    first_w = layers.embedding(input=feat_ids, size=[vocab_size, 1],
                               is_sparse=is_sparse, is_distributed=dist,
                               param_attr=_table('fm_first_w'))
    # [B, F, 1] -> [B, 1]
    first = layers.reduce_sum(first_w, dim=1)

    # ---- FM second order: 0.5 * ((sum_f v_f)^2 - sum_f v_f^2)
    emb = layers.embedding(input=feat_ids, size=[vocab_size, embed_dim],
                           is_sparse=is_sparse, is_distributed=dist,
                           param_attr=_table('fm_embed'))
    sum_v = layers.reduce_sum(emb, dim=1)                    # [B, D]
    sum_v_sq = layers.square(sum_v)
    sq_v = layers.square(emb)
    sq_sum_v = layers.reduce_sum(sq_v, dim=1)
    second = layers.scale(
        layers.elementwise_sub(sum_v_sq, sq_sum_v), scale=0.5)  # [B, D]
    second = layers.reduce_sum(second, dim=1, keep_dim=True)    # [B, 1]

    # ---- deep part: MLP over concatenated field embeddings
    deep = layers.reshape(emb, shape=[-1, num_fields * embed_dim])
    for h in hidden:
        deep = layers.fc(input=deep, size=h, act='relu')
    deep_out = layers.fc(input=deep, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first, second), deep_out)
    loss = layers.sigmoid_cross_entropy_with_logits(
        logit, layers.cast(label, 'float32'))
    avg_cost = layers.mean(loss)
    prob = layers.sigmoid(logit)
    return avg_cost, prob, logit


def synthetic_ctr_reader(n=4096, num_fields=NUM_FIELDS, vocab=VOCAB,
                         tag='train'):
    """Deterministic learnable CTR stream: latent weight per bucket."""
    from paddle_tpu.dataset import common

    def reader():
        rng = common.synthetic_rng('ctr_' + tag)
        w = common.synthetic_rng('ctr_w').randn(4096) * 0.7
        for _ in range(n):
            ids = rng.randint(0, vocab, size=num_fields).astype('int64')
            score = w[ids % 4096].sum()
            p = 1.0 / (1.0 + np.exp(-score))
            label = int(rng.rand() < p)
            yield ids, label
    return reader


def get_model(batch_size=256, embed_dim=10, learning_rate=1e-3):
    feat_ids = layers.data(name='feat_ids', shape=[NUM_FIELDS], dtype='int64')
    label = layers.data(name='label', shape=[1], dtype='int64')
    avg_cost, prob, logit = deepfm(feat_ids, label)
    auc = layers.auc(prob if prob.shape[-1] == 2 else
                     layers.concat([layers.scale(prob, -1.0, 1.0), prob],
                                   axis=1), label)
    opt = fluid.optimizer.Adam(learning_rate=learning_rate)
    opt.minimize(avg_cost)
    train_reader = paddle.batch(synthetic_ctr_reader(tag='train'),
                                batch_size=batch_size)
    test_reader = paddle.batch(synthetic_ctr_reader(1024, tag='test'),
                               batch_size=batch_size)
    return avg_cost, auc, train_reader, test_reader, ['feat_ids', 'label']
