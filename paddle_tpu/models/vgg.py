"""VGG-16 benchmark model.

Parity: reference benchmark/fluid/models/vgg.py (vgg16_bn_drop:29,
get_model:55).
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['vgg16_bn_drop', 'get_model']


def vgg16_bn_drop(input):
    def conv_block(input, num_filter, groups, dropouts):
        return fluid.nets.img_conv_group(
            input=input, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act='relu', conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type='max')

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = fluid.layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = fluid.layers.fc(input=drop, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act='relu')
    drop2 = fluid.layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = fluid.layers.fc(input=drop2, size=512, act=None)
    return fc2


def get_model(data_set='cifar10', batch_size=32, learning_rate=1e-3):
    if data_set == "cifar10":
        classdim = 10
        data_shape = [3, 32, 32]
        train_reader = paddle.dataset.cifar.train10()
        test_reader = paddle.dataset.cifar.test10()
    else:
        classdim = 102
        data_shape = [3, 224, 224]
        train_reader = paddle.dataset.flowers.train()
        test_reader = paddle.dataset.flowers.test()

    images = fluid.layers.data(name='data', shape=data_shape, dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    net = vgg16_bn_drop(images)
    predict = fluid.layers.fc(input=net, size=classdim, act='softmax')
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Adam(learning_rate=learning_rate)
    optimizer.minimize(avg_cost)

    return (avg_cost, inference_program,
            paddle.batch(train_reader, batch_size=batch_size),
            paddle.batch(test_reader, batch_size=batch_size), batch_acc)
