"""Linear regression on UCI housing (Fluid book ch01).

Parity: reference python/paddle/fluid/tests/book/test_fit_a_line.py.
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['get_model']


def get_model(batch_size=20, learning_rate=0.01):
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(x=cost)
    inference_program = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.uci_housing.train(),
                              buf_size=500), batch_size=batch_size)
    test_reader = paddle.batch(paddle.dataset.uci_housing.test(),
                               batch_size=batch_size)
    return avg_cost, inference_program, train_reader, test_reader, ['x', 'y']
