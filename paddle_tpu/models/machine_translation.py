"""Seq2seq LSTM encoder-decoder with attention (WMT en-fr).

Parity: reference benchmark/fluid/models/machine_translation.py
(seq_to_seq_net:91, lstm_step:31). The reference steps the decoder with
per-timestep fc/sigmoid ops in a StaticRNN-style loop; TPU-first the whole
decoder is the fused `attention_lstm_decoder` scan op (see
ops_impl/sequence_ops.py) so the per-step attention + cell is one XLA
while-loop body of batched MXU matmuls.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.layer_helper import LayerHelper

__all__ = ['seq_to_seq_net', 'get_model']


def _attention_decoder(trg_emb, enc_out, hidden_dim, name='mt'):
    helper = LayerHelper('attention_lstm_decoder')
    dtype = trg_emb.dtype
    e = trg_emb.shape[-1]
    d = enc_out.shape[-1]
    w_dec = helper.get_or_create_parameter(
        name + '_w_dec', shape=[e + d, 4 * hidden_dim], dtype=dtype)
    u_dec = helper.get_or_create_parameter(
        name + '_u_dec', shape=[hidden_dim, 4 * hidden_dim], dtype=dtype)
    b_dec = helper.get_or_create_parameter(
        name + '_b_dec', shape=[1, 4 * hidden_dim], dtype=dtype, is_bias=True)
    w_q = helper.get_or_create_parameter(
        name + '_w_attnq', shape=[hidden_dim, d], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='attention_lstm_decoder',
                     inputs={'TrgEmb': [trg_emb], 'EncOut': [enc_out],
                             'WDec': [w_dec], 'UDec': [u_dec],
                             'BDec': [b_dec], 'WAttnQ': [w_q]},
                     outputs={'Hidden': [out]})
    return out


def _beam_decode(enc_out, decoder_size, target_dict_dim, embedding_dim,
                 beam_size, max_length, start_id=0, end_id=1, name='mt'):
    """Fused whole-decode beam search (one lax.scan — see
    ops_impl/sampled_ops.py:attention_lstm_beam_decode). Reuses the
    training decoder's parameters by name, plus the target embedding and
    output projection, so generation follows training with no re-plumbing.
    Parity: reference book test_machine_translation.py:decode() (While-loop
    beam search over LoD beams)."""
    helper = LayerHelper('attention_lstm_beam_decode')
    dtype = enc_out.dtype
    d = enc_out.shape[-1]
    e = embedding_dim
    h = decoder_size
    w_dec = helper.get_or_create_parameter(
        name + '_w_dec', shape=[e + d, 4 * h], dtype=dtype)
    u_dec = helper.get_or_create_parameter(
        name + '_u_dec', shape=[h, 4 * h], dtype=dtype)
    b_dec = helper.get_or_create_parameter(
        name + '_b_dec', shape=[1, 4 * h], dtype=dtype, is_bias=True)
    w_q = helper.get_or_create_parameter(
        name + '_w_attnq', shape=[h, d], dtype=dtype)
    w_emb = helper.get_or_create_parameter(
        name + '_trg_emb', shape=[target_dict_dim, e], dtype=dtype)
    w_out = helper.get_or_create_parameter(
        name + '_w_out', shape=[h, target_dict_dim], dtype=dtype)
    b_out = helper.get_or_create_parameter(
        name + '_b_out', shape=[1, target_dict_dim], dtype=dtype, is_bias=True)
    sent_ids = helper.create_variable_for_type_inference('int64')
    sent_scores = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='attention_lstm_beam_decode',
        inputs={'EncOut': [enc_out], 'WDec': [w_dec], 'UDec': [u_dec],
                'BDec': [b_dec], 'WAttnQ': [w_q], 'WEmb': [w_emb],
                'WOut': [w_out], 'BOut': [b_out]},
        outputs={'SentenceIds': [sent_ids], 'SentenceScores': [sent_scores]},
        attrs={'beam_size': beam_size, 'max_len': max_length,
               'start_id': start_id, 'end_id': end_id})
    return sent_ids, sent_scores


def seq_to_seq_net(embedding_dim, encoder_size, decoder_size, source_dict_dim,
                   target_dict_dim, is_generating=False, beam_size=3,
                   max_length=50, name='mt'):
    """reference machine_translation.py:seq_to_seq_net."""
    src_word_idx = fluid.layers.data(name='source_sequence', shape=[1],
                                     dtype='int64', lod_level=1)
    src_embedding = fluid.layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim])
    src_forward = fluid.layers.fc(input=src_embedding,
                                  size=encoder_size * 4, bias_attr=True)
    enc_fwd, _ = fluid.layers.dynamic_lstm(input=src_forward,
                                           size=encoder_size * 4,
                                           use_peepholes=False)
    src_reversed = fluid.layers.fc(input=src_embedding,
                                   size=encoder_size * 4, bias_attr=True)
    enc_bwd, _ = fluid.layers.dynamic_lstm(input=src_reversed,
                                           size=encoder_size * 4,
                                           use_peepholes=False,
                                           is_reverse=True)
    encoded_vector = fluid.layers.concat(input=[enc_fwd, enc_bwd], axis=2)

    if is_generating:
        return _beam_decode(encoded_vector, decoder_size, target_dict_dim,
                            embedding_dim, beam_size, max_length, name=name)

    trg_word_idx = fluid.layers.data(name='target_sequence', shape=[1],
                                     dtype='int64', lod_level=1)
    trg_embedding = fluid.layers.embedding(
        input=trg_word_idx, size=[target_dict_dim, embedding_dim],
        param_attr=fluid.ParamAttr(name=name + '_trg_emb'))

    dec_hidden = _attention_decoder(trg_embedding, encoded_vector,
                                    decoder_size, name=name)
    prediction = fluid.layers.fc(
        input=dec_hidden, size=target_dict_dim, act='softmax',
        num_flatten_dims=2, param_attr=fluid.ParamAttr(name=name + '_w_out'),
        bias_attr=fluid.ParamAttr(name=name + '_b_out'))

    label = fluid.layers.data(name='label_sequence', shape=[1],
                              dtype='int64', lod_level=1)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = _masked_mean(cost)
    feeding_list = ["source_sequence", "target_sequence", "label_sequence"]
    return avg_cost, feeding_list


def _masked_mean(cost):
    """Mean over valid (non-padded) timesteps: masked per-sequence sums over
    the SeqValue's lengths (the lod carries the mask at run time)."""
    per_seq = fluid.layers.sequence_pool(cost, 'sum')
    total = fluid.layers.reduce_sum(per_seq)
    ones = fluid.layers.scale(cost, scale=0.0, bias=1.0)  # SeqValue of 1s
    denom = fluid.layers.reduce_sum(fluid.layers.sequence_pool(ones, 'sum'))
    return fluid.layers.elementwise_div(total, denom)


def get_model(batch_size=16, embedding_dim=512, encoder_size=512,
              decoder_size=512, dict_size=30000):
    avg_cost, feeding_list = seq_to_seq_net(
        embedding_dim, encoder_size, decoder_size, dict_size, dict_size,
        False)
    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Adam(learning_rate=0.0002)
    optimizer.minimize(avg_cost)

    train_reader = paddle.batch(
        paddle.dataset.wmt14.train(dict_size), batch_size=batch_size)
    test_reader = paddle.batch(
        paddle.dataset.wmt14.test(dict_size), batch_size=batch_size)
    return avg_cost, inference_program, train_reader, test_reader, feeding_list
