"""Seq2seq LSTM encoder-decoder with attention (WMT en-fr).

Parity: reference benchmark/fluid/models/machine_translation.py
(seq_to_seq_net:91, lstm_step:31). The reference steps the decoder with
per-timestep fc/sigmoid ops in a StaticRNN-style loop; TPU-first the whole
decoder is the fused `attention_lstm_decoder` scan op (see
ops_impl/sequence_ops.py) so the per-step attention + cell is one XLA
while-loop body of batched MXU matmuls.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.layer_helper import LayerHelper

__all__ = ['seq_to_seq_net', 'get_model']


def _attention_decoder(trg_emb, enc_out, hidden_dim):
    helper = LayerHelper('attention_lstm_decoder')
    dtype = trg_emb.dtype
    e = trg_emb.shape[-1]
    d = enc_out.shape[-1]
    w_dec = helper.create_parameter(attr=helper.param_attr,
                                    shape=[e + d, 4 * hidden_dim], dtype=dtype)
    u_dec = helper.create_parameter(attr=fluid.ParamAttr(),
                                    shape=[hidden_dim, 4 * hidden_dim],
                                    dtype=dtype)
    b_dec = helper.create_parameter(attr=fluid.ParamAttr(), is_bias=True,
                                    shape=[1, 4 * hidden_dim], dtype=dtype)
    w_q = helper.create_parameter(attr=fluid.ParamAttr(),
                                  shape=[hidden_dim, d], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='attention_lstm_decoder',
                     inputs={'TrgEmb': [trg_emb], 'EncOut': [enc_out],
                             'WDec': [w_dec], 'UDec': [u_dec],
                             'BDec': [b_dec], 'WAttnQ': [w_q]},
                     outputs={'Hidden': [out]})
    return out


def seq_to_seq_net(embedding_dim, encoder_size, decoder_size, source_dict_dim,
                   target_dict_dim, is_generating=False, beam_size=3,
                   max_length=50):
    """reference machine_translation.py:seq_to_seq_net."""
    src_word_idx = fluid.layers.data(name='source_sequence', shape=[1],
                                     dtype='int64', lod_level=1)
    src_embedding = fluid.layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim])
    src_forward = fluid.layers.fc(input=src_embedding,
                                  size=encoder_size * 4, bias_attr=True)
    enc_fwd, _ = fluid.layers.dynamic_lstm(input=src_forward,
                                           size=encoder_size * 4,
                                           use_peepholes=False)
    src_reversed = fluid.layers.fc(input=src_embedding,
                                   size=encoder_size * 4, bias_attr=True)
    enc_bwd, _ = fluid.layers.dynamic_lstm(input=src_reversed,
                                           size=encoder_size * 4,
                                           use_peepholes=False,
                                           is_reverse=True)
    encoded_vector = fluid.layers.concat(input=[enc_fwd, enc_bwd], axis=2)

    trg_word_idx = fluid.layers.data(name='target_sequence', shape=[1],
                                     dtype='int64', lod_level=1)
    trg_embedding = fluid.layers.embedding(
        input=trg_word_idx, size=[target_dict_dim, embedding_dim])

    dec_hidden = _attention_decoder(trg_embedding, encoded_vector,
                                    decoder_size)
    prediction = fluid.layers.fc(input=dec_hidden, size=target_dict_dim,
                                 act='softmax', num_flatten_dims=2)

    label = fluid.layers.data(name='label_sequence', shape=[1],
                              dtype='int64', lod_level=1)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = _masked_mean(cost)
    feeding_list = ["source_sequence", "target_sequence", "label_sequence"]
    return avg_cost, feeding_list


def _masked_mean(cost):
    """Mean over valid (non-padded) timesteps: masked per-sequence sums over
    the SeqValue's lengths (the lod carries the mask at run time)."""
    per_seq = fluid.layers.sequence_pool(cost, 'sum')
    total = fluid.layers.reduce_sum(per_seq)
    ones = fluid.layers.scale(cost, scale=0.0, bias=1.0)  # SeqValue of 1s
    denom = fluid.layers.reduce_sum(fluid.layers.sequence_pool(ones, 'sum'))
    return fluid.layers.elementwise_div(total, denom)


def get_model(batch_size=16, embedding_dim=512, encoder_size=512,
              decoder_size=512, dict_size=30000):
    avg_cost, feeding_list = seq_to_seq_net(
        embedding_dim, encoder_size, decoder_size, dict_size, dict_size,
        False)
    inference_program = fluid.default_main_program().clone(for_test=True)
    optimizer = fluid.optimizer.Adam(learning_rate=0.0002)
    optimizer.minimize(avg_cost)

    train_reader = paddle.batch(
        paddle.dataset.wmt14.train(dict_size), batch_size=batch_size)
    test_reader = paddle.batch(
        paddle.dataset.wmt14.test(dict_size), batch_size=batch_size)
    return avg_cost, inference_program, train_reader, test_reader, feeding_list
