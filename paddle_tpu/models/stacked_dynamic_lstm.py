"""Stacked LSTM for IMDB sentiment.

Parity: reference benchmark/fluid/models/stacked_dynamic_lstm.py
(get_model:46). The reference hand-rolls the LSTM cell inside a DynamicRNN
block (one C++ op dispatch per gate per timestep); TPU-first this uses the
fused dynamic_lstm op — one lax.scan whose body is a single gate matmul on
the MXU, identical math (sigmoid gates, tanh candidate/output over
fc(word) + fc(hidden)).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.dataset import imdb

__all__ = ['get_model']


def crop_sentence(reader, crop_size):
    unk_value = None

    def __impl__():
        for item in reader():
            if len(item[0]) < crop_size:
                yield item
    return __impl__


def lstm_net(data, dict_dim, lstm_size=512, emb_dim=512, stacked_num=1):
    sentence = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim])
    sentence = fluid.layers.fc(input=sentence, size=lstm_size, act='tanh')
    inputs = sentence
    for _ in range(stacked_num):
        gates = fluid.layers.fc(input=inputs, size=lstm_size * 4,
                                bias_attr=True)
        hidden, cell = fluid.layers.dynamic_lstm(
            input=gates, size=lstm_size * 4, use_peepholes=False)
        inputs = hidden
    last = fluid.layers.sequence_pool(inputs, 'last')
    logit = fluid.layers.fc(input=last, size=2, act='softmax')
    return logit


def get_model(batch_size=32, lstm_size=512, emb_dim=512, crop_size=1500):
    word_dict = imdb.word_dict()
    data = fluid.layers.data(name="words", shape=[1], lod_level=1,
                             dtype='int64')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    logit = lstm_net(data, len(word_dict), lstm_size, emb_dim)
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(input=logit, label=label))
    batch_acc = fluid.layers.accuracy(input=logit, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    adam = fluid.optimizer.Adam()
    adam.minimize(loss)

    train_reader = paddle.batch(
        paddle.reader.shuffle(
            crop_sentence(imdb.train(word_dict), crop_size), buf_size=25000),
        batch_size=batch_size)
    test_reader = paddle.batch(
        crop_sentence(imdb.test(word_dict), crop_size),
        batch_size=batch_size)
    return loss, inference_program, train_reader, test_reader, batch_acc
