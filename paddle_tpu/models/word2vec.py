"""N-gram word embedding model (Fluid book ch04 word2vec).

Parity: reference python/paddle/fluid/tests/book/test_word2vec.py — 4 input
words -> embeddings -> concat -> fc -> softmax over vocab.
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['get_model']

EMBED_SIZE = 32
HIDDEN_SIZE = 256
N = 5


def ngram_net(words, dict_size, embed_size=EMBED_SIZE):
    embeds = []
    for w in words[:-1]:
        embeds.append(fluid.layers.embedding(
            input=w, size=[dict_size, embed_size],
            param_attr=fluid.ParamAttr(name='shared_w')))
    concat = fluid.layers.concat(input=embeds, axis=1)
    hidden = fluid.layers.fc(input=concat, size=HIDDEN_SIZE, act='sigmoid')
    predict = fluid.layers.softmax(
        fluid.layers.fc(input=hidden, size=dict_size))
    return predict


def get_model(batch_size=64, learning_rate=0.001):
    word_dict = paddle.dataset.imikolov.build_dict()
    dict_size = len(word_dict)
    words = [fluid.layers.data(name='word_%d' % i, shape=[1], dtype='int64')
             for i in range(N)]
    predict = ngram_net(words, dict_size)
    cost = fluid.layers.cross_entropy(input=predict, label=words[-1])
    avg_cost = fluid.layers.mean(x=cost)
    inference_program = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    train_reader = paddle.batch(paddle.dataset.imikolov.train(word_dict, N),
                                batch_size)
    test_reader = paddle.batch(paddle.dataset.imikolov.test(word_dict, N),
                               batch_size)
    feeds = ['word_%d' % i for i in range(N)]
    return avg_cost, inference_program, train_reader, test_reader, feeds
