"""MovieLens recommender (Fluid book ch05).

Parity: reference python/paddle/fluid/tests/book/test_recommender_system.py
(user tower: id/gender/age/job embeddings -> fc concat -> 200-d tanh;
movie tower: id embedding + category sum-pool + title sequence_conv_pool
-> 200-d tanh; cos_sim scaled to [0,5], square_error_cost vs score)."""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, nets

__all__ = ['model', 'get_model', 'FEED_ORDER']

FEED_ORDER = ['user_id', 'gender_id', 'age_id', 'job_id', 'movie_id',
              'category_id', 'movie_title', 'score']


def _big_table(name, dist_axis):
    """ParamAttr for a huge-vocab table: row-sharded over `dist_axis`
    when the model is built for the sharded-embedding subsystem
    (docs/embedding.md), plain otherwise. The tiny side tables (gender/
    age/job, a handful of rows) always stay replicated — sharding them
    would cost a wire exchange to save nothing."""
    import paddle_tpu.fluid as _fluid
    return _fluid.ParamAttr(
        name=name, sharding=(dist_axis, None) if dist_axis else None)


def _pad(n, dist_axis, axis_size):
    if not dist_axis:
        return n
    from paddle_tpu.embedding import pad_vocab
    return pad_vocab(n, axis_size)


def get_usr_combined_features(emb_dim=32, out_dim=200, dist_axis=None,
                              axis_size=1, is_sparse=False):
    usr_dict_size = _pad(paddle.dataset.movielens.max_user_id() + 1,
                         dist_axis, axis_size)
    uid = layers.data(name='user_id', shape=[1], dtype='int64')
    usr_emb = layers.embedding(input=uid, dtype='float32',
                               size=[usr_dict_size, emb_dim],
                               is_sparse=is_sparse,
                               is_distributed=dist_axis is not None,
                               param_attr=_big_table('user_table',
                                                     dist_axis))
    usr_fc = layers.fc(input=usr_emb, size=emb_dim)

    usr_gender_id = layers.data(name='gender_id', shape=[1], dtype='int64')
    usr_gender_emb = layers.embedding(input=usr_gender_id, size=[2, 16],
                                      param_attr='gender_table')
    usr_gender_fc = layers.fc(input=usr_gender_emb, size=16)

    age_size = len(paddle.dataset.movielens.age_table)
    usr_age_id = layers.data(name='age_id', shape=[1], dtype='int64')
    usr_age_emb = layers.embedding(input=usr_age_id, size=[age_size, 16],
                                   param_attr='age_table')
    usr_age_fc = layers.fc(input=usr_age_emb, size=16)

    job_size = paddle.dataset.movielens.max_job_id() + 1
    usr_job_id = layers.data(name='job_id', shape=[1], dtype='int64')
    usr_job_emb = layers.embedding(input=usr_job_id, size=[job_size, 16],
                                   param_attr='job_table')
    usr_job_fc = layers.fc(input=usr_job_emb, size=16)

    concat_embed = layers.concat(
        input=[usr_fc, usr_gender_fc, usr_age_fc, usr_job_fc], axis=1)
    return layers.fc(input=concat_embed, size=out_dim, act='tanh')


def get_mov_combined_features(emb_dim=32, out_dim=200, dist_axis=None,
                              axis_size=1, is_sparse=False):
    mov_dict_size = _pad(paddle.dataset.movielens.max_movie_id() + 1,
                         dist_axis, axis_size)
    mov_id = layers.data(name='movie_id', shape=[1], dtype='int64')
    mov_emb = layers.embedding(input=mov_id, dtype='float32',
                               size=[mov_dict_size, emb_dim],
                               is_sparse=is_sparse,
                               is_distributed=dist_axis is not None,
                               param_attr=_big_table('movie_table',
                                                     dist_axis))
    mov_fc = layers.fc(input=mov_emb, size=emb_dim)

    category_size = len(paddle.dataset.movielens.movie_categories())
    category_id = layers.data(name='category_id', shape=[1], dtype='int64',
                              lod_level=1)
    mov_categories_emb = layers.embedding(input=category_id,
                                          size=[category_size, emb_dim])
    mov_categories_hidden = layers.sequence_pool(
        input=mov_categories_emb, pool_type='sum')

    title_size = _pad(len(paddle.dataset.movielens.get_movie_title_dict()),
                      dist_axis, axis_size)
    mov_title_id = layers.data(name='movie_title', shape=[1], dtype='int64',
                               lod_level=1)
    mov_title_emb = layers.embedding(input=mov_title_id,
                                     size=[title_size, emb_dim],
                                     is_sparse=is_sparse,
                                     is_distributed=dist_axis is not None,
                                     param_attr=_big_table('title_table',
                                                           dist_axis))
    mov_title_conv = nets.sequence_conv_pool(
        input=mov_title_emb, num_filters=emb_dim, filter_size=3, act='tanh',
        pool_type='sum')

    concat_embed = layers.concat(
        input=[mov_fc, mov_categories_hidden, mov_title_conv], axis=1)
    return layers.fc(input=concat_embed, size=out_dim, act='tanh')


def model(emb_dim=32, tower_dim=200, dist_axis=None, axis_size=1,
          is_sparse=False):
    """dist_axis/axis_size/is_sparse: build the big tables (user/movie/
    title) row-sharded for the sharded-embedding subsystem — vocabs are
    padded to the axis size (docs/embedding.md)."""
    usr = get_usr_combined_features(emb_dim, tower_dim, dist_axis,
                                    axis_size, is_sparse)
    mov = get_mov_combined_features(emb_dim, tower_dim, dist_axis,
                                    axis_size, is_sparse)
    inference = layers.cos_sim(X=usr, Y=mov)
    scale_infer = layers.scale(x=inference, scale=5.0)

    label = layers.data(name='score', shape=[1], dtype='float32')
    avg_cost = layers.mean(
        layers.square_error_cost(input=scale_infer, label=label))
    return scale_infer, avg_cost


def get_model(batch_size=256, learning_rate=0.2, emb_dim=32, tower_dim=200):
    scale_infer, avg_cost = model(emb_dim, tower_dim)
    inference_program = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.movielens.train(),
                              buf_size=8192), batch_size=batch_size)
    test_reader = paddle.batch(paddle.dataset.movielens.test(),
                               batch_size=batch_size)
    return (avg_cost, scale_infer, inference_program, train_reader,
            test_reader, list(FEED_ORDER))
