"""Transformer-base (WMT en-de) — the flagship sequence benchmark.

Parity: the reference ships seq2seq in benchmark/fluid/models/
machine_translation.py and the Transformer in its models repo built on the
same fluid.layers surface (fc num_flatten_dims=2, layer_norm, matmul,
softmax, label_smooth — all present here). Dense padded [B, S] inputs with
in-graph pad masks (TPU-friendly static shapes); every attention head is a
batched MXU matmul and the whole train step is one fused XLA module. For
long sequences the pallas flash-attention kernel (paddle_tpu.ops) replaces
the naive score matrix, and sequence parallelism comes from
paddle_tpu.parallel.ring_attention.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

__all__ = ['transformer', 'get_model']


def _position_encoding(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype('float32')
    i = np.arange(d_model)[None, :].astype('float32')
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.zeros((max_len, d_model), dtype='float32')
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc


def _pre_post_process(prev, out, dropout_rate, mode='da'):
    """residual + dropout + layernorm (post-process 'dan' order)."""
    if 'd' in mode and dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    if 'a' in mode and prev is not None:
        out = layers.elementwise_add(out, prev)
    if 'n' in mode:
        out = layers.layer_norm(out, begin_norm_axis=len(out.shape) - 1)
    return out


def multi_head_attention(queries, keys, values, key_bias, d_model, n_head,
                         causal=False, cache=None):
    """q/k/v projections + ONE fused flash-attention op + output projection.

    key_bias is the [B, S] pad bias; causal adds the decoder's triangular
    mask inside the kernel — no [B,H,T,T] bias tensor is ever built.
    Deviation from the reference: softmax-weight dropout is omitted (the
    flash kernel never materializes the weights) — hence no dropout_rate
    parameter; the sublayer's output dropout in _pre_post_process provides
    the regularization, as in most flash-attention trainers."""
    d_key = d_model // n_head
    q = layers.fc(input=queries, size=d_model, num_flatten_dims=2,
                  bias_attr=False)
    k = layers.fc(input=keys, size=d_model, num_flatten_dims=2,
                  bias_attr=False)
    v = layers.fc(input=values, size=d_model, num_flatten_dims=2,
                  bias_attr=False)

    def split_heads(x):
        x = layers.reshape(x, shape=[0, 0, n_head, d_key])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    q = split_heads(q)
    k = split_heads(k)
    v = split_heads(v)
    ctx = layers.fused_attention(q, k, v, key_bias=key_bias, causal=causal,
                                 scale=d_key ** -0.5)
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, shape=[0, 0, d_model])
    return layers.fc(input=ctx, size=d_model, num_flatten_dims=2,
                     bias_attr=False)


def ffn(x, d_inner, d_model, dropout_rate):
    hidden = layers.fc(input=x, size=d_inner, num_flatten_dims=2, act='relu')
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_prob=dropout_rate)
    return layers.fc(input=hidden, size=d_model, num_flatten_dims=2)


def encoder_layer(x, key_bias, d_model, n_head, d_inner, dropout_rate):
    attn = multi_head_attention(x, x, x, key_bias, d_model, n_head)
    x = _pre_post_process(x, attn, dropout_rate, 'dan')
    f = ffn(x, d_inner, d_model, dropout_rate)
    return _pre_post_process(x, f, dropout_rate, 'dan')


def decoder_layer(x, enc_out, self_key_bias, cross_key_bias, d_model, n_head,
                  d_inner, dropout_rate):
    attn = multi_head_attention(x, x, x, self_key_bias, d_model, n_head,
                                causal=True)
    x = _pre_post_process(x, attn, dropout_rate, 'dan')
    cross = multi_head_attention(x, enc_out, enc_out, cross_key_bias,
                                 d_model, n_head)
    x = _pre_post_process(x, cross, dropout_rate, 'dan')
    f = ffn(x, d_inner, d_model, dropout_rate)
    return _pre_post_process(x, f, dropout_rate, 'dan')


def _pad_mask_bias(word, name):
    """[B, S] additive key bias: -1e9 on pad (id 0) positions. The fused
    attention op broadcasts it over heads/queries; the decoder's causal
    mask is applied inside the kernel (causal=True), so no [B,H,T,T] bias
    tensor exists anywhere."""
    w = layers.cast(word, 'float32')
    nonpad = layers.clip(w, 0.0, 1.0)  # id 0 -> 0, others -> 1
    return layers.scale(nonpad, scale=1e9, bias=-1e9)  # 0 -> -1e9, 1 -> 0


def _embed(word, vocab_size, d_model, max_len, dropout_rate, name_prefix):
    emb = layers.embedding(
        input=word, size=[vocab_size, d_model],
        param_attr=fluid.ParamAttr(
            name=name_prefix + '_emb',
            initializer=fluid.initializer.Normal(0., d_model ** -0.5)))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    pos = layers.assign(_position_encoding(max_len, d_model))
    pos.stop_gradient = True
    out = layers.elementwise_add(emb, pos, axis=1)
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    return out


def transformer(src_vocab_size, trg_vocab_size, max_length, n_layer=6,
                d_model=512, n_head=8, d_inner=2048, dropout_rate=0.1,
                label_smooth_eps=0.1, pp_decoder=False):
    """Build the training graph; returns (avg_cost, token_count, feeds).

    pp_decoder wraps the decoder layers in device_guard('pipe:k') so
    PipelineTranspiler can run the decoder stack as a GPipe schedule over
    a `pp` mesh axis. True stamps one stage per layer; an int S groups
    n_layer into S equal multi-layer stages (n_layer % S == 0 — fewer
    chips than layers, the standard GPipe packing; the stages stay
    structurally identical so the transpiler's alignment holds). The
    encoder + embeddings stay in the prologue and the enc output / pad
    biases become streamed pipeline extras. Without transpiling, the
    stamps are inert."""
    import contextlib
    if pp_decoder and pp_decoder is not True:
        if int(pp_decoder) < 2:
            raise ValueError(
                'pp_decoder stage count must be >= 2 (or True for one '
                'stage per layer), got %r' % (pp_decoder,))
        if n_layer % int(pp_decoder):
            raise ValueError(
                'pp_decoder=%d stages must divide n_layer=%d'
                % (pp_decoder, n_layer))
        layers_per_stage = n_layer // int(pp_decoder)
    else:
        layers_per_stage = 1
    src_word = layers.data(name='src_word', shape=[max_length],
                           dtype='int64')
    trg_word = layers.data(name='trg_word', shape=[max_length],
                           dtype='int64')
    lbl_word = layers.data(name='lbl_word', shape=[max_length],
                           dtype='int64')

    src_bias = _pad_mask_bias(src_word, 'src')
    self_bias = _pad_mask_bias(trg_word, 'trg')

    enc = _embed(src_word, src_vocab_size, d_model, max_length,
                 dropout_rate, 'src')
    for _ in range(n_layer):
        enc = encoder_layer(enc, src_bias, d_model, n_head, d_inner,
                            dropout_rate)

    dec = _embed(trg_word, trg_vocab_size, d_model, max_length,
                 dropout_rate, 'trg')
    for k in range(n_layer):
        guard = (fluid.device_guard('pipe:%d' % (k // layers_per_stage))
                 if pp_decoder else contextlib.nullcontext())
        with guard:
            dec = decoder_layer(dec, enc, self_bias, src_bias, d_model,
                                n_head, d_inner, dropout_rate)

    logits = layers.fc(input=dec, size=trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    logits2d = layers.reshape(logits, shape=[-1, trg_vocab_size])
    lbl2d = layers.reshape(lbl_word, shape=[-1, 1])
    if label_smooth_eps:
        soft = layers.label_smooth(
            layers.one_hot(lbl2d, depth=trg_vocab_size),
            epsilon=label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(logits2d, soft,
                                                 soft_label=True)
    else:
        cost = layers.softmax_with_cross_entropy(logits2d, lbl2d)
    weights = layers.clip(layers.cast(lbl2d, 'float32'), 0.0, 1.0)
    weighted = layers.elementwise_mul(cost, weights)
    token_count = layers.reduce_sum(weights)
    avg_cost = layers.elementwise_div(layers.reduce_sum(weighted),
                                      token_count)
    return avg_cost, token_count, ['src_word', 'trg_word', 'lbl_word']


def pad_batch(batch, max_length):
    """Host-side: pad wmt16-style (src, trg, lbl) id lists to max_length."""
    out = []
    for src, trg, lbl in batch:
        def pad(x):
            x = list(x)[:max_length]
            return np.asarray(x + [0] * (max_length - len(x)), dtype='int64')
        out.append((pad(src), pad(trg), pad(lbl)))
    return out


def get_model(batch_size=16, max_length=64, n_layer=6, d_model=512,
              n_head=8, d_inner=2048, dict_size=10000, learning_rate=2.0,
              warmup_steps=4000, pp_decoder=False):
    avg_cost, token_count, feeds = transformer(
        dict_size, dict_size, max_length, n_layer, d_model, n_head, d_inner,
        pp_decoder=pp_decoder)
    lr = layers.learning_rate_scheduler.noam_decay(d_model, warmup_steps)
    lr = layers.scale(lr, scale=float(learning_rate))
    opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.98,
                               epsilon=1e-9)
    opt.minimize(avg_cost)

    raw_train = paddle.dataset.wmt16.train(dict_size, dict_size)
    raw_test = paddle.dataset.wmt16.test(dict_size, dict_size)

    def train_reader():
        for b in paddle.batch(raw_train, batch_size)():
            yield pad_batch(b, max_length)

    def test_reader():
        for b in paddle.batch(raw_test, batch_size)():
            yield pad_batch(b, max_length)

    return avg_cost, token_count, train_reader, test_reader, feeds
