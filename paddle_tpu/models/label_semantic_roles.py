"""SRL deep bidirectional LSTM + CRF (Fluid book ch07).

Parity: reference python/paddle/fluid/tests/book/test_label_semantic_roles.py
(db_lstm: 8 feature embeddings -> summed fc -> stacked alternating-direction
dynamic_lstm with direct edges -> linear_chain_crf loss / crf_decoding
inference). Sizes are parameters so tests can run a small instance.
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['db_lstm', 'get_model', 'load_pretrained_embedding', 'FEED_ORDER']

FEED_ORDER = ['word_data', 'ctx_n2_data', 'ctx_n1_data', 'ctx_0_data',
              'ctx_p1_data', 'ctx_p2_data', 'verb_data', 'mark_data',
              'target']

MARK_DICT_LEN = 2


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, label_dict_len, pred_dict_len,
            word_dim=32, mark_dim=5, hidden_dim=512, depth=8,
            embedding_name='emb'):
    predicate_embedding = fluid.layers.embedding(
        input=predicate, size=[pred_dict_len, word_dim], dtype='float32',
        param_attr='vemb')
    mark_embedding = fluid.layers.embedding(
        input=mark, size=[MARK_DICT_LEN, mark_dim], dtype='float32')

    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        fluid.layers.embedding(
            size=[word_dict_len, word_dim], input=x,
            param_attr=fluid.ParamAttr(name=embedding_name, trainable=False))
        for x in word_input
    ]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0 = fluid.layers.sums(input=[
        fluid.layers.fc(input=emb, size=hidden_dim) for emb in emb_layers])

    lstm_0, _ = fluid.layers.dynamic_lstm(
        input=hidden_0, size=hidden_dim, candidate_activation='relu',
        gate_activation='sigmoid', cell_activation='sigmoid')

    # stacked L/R LSTMs with direct edges (alternating direction per depth)
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=hidden_dim),
            fluid.layers.fc(input=input_tmp[1], size=hidden_dim),
        ])
        lstm, _ = fluid.layers.dynamic_lstm(
            input=mix_hidden, size=hidden_dim,
            candidate_activation='relu', gate_activation='sigmoid',
            cell_activation='sigmoid', is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = fluid.layers.sums(input=[
        fluid.layers.fc(input=input_tmp[0], size=label_dict_len, act='tanh'),
        fluid.layers.fc(input=input_tmp[1], size=label_dict_len, act='tanh'),
    ])
    return feature_out


def get_model(word_dim=32, mark_dim=5, hidden_dim=128, depth=4,
              mix_hidden_lr=1e-3, batch_size=10):
    """Build train net + crf decode; returns (avg_cost, crf_decode,
    train_reader, feed_order)."""
    word_dict, verb_dict, label_dict = paddle.dataset.conll05.get_dict()
    word_dict_len = len(word_dict)
    label_dict_len = len(label_dict)
    pred_dict_len = len(verb_dict)

    def seq_data(name):
        return fluid.layers.data(name=name, shape=[1], dtype='int64',
                                 lod_level=1)

    word = seq_data('word_data')
    ctx_n2 = seq_data('ctx_n2_data')
    ctx_n1 = seq_data('ctx_n1_data')
    ctx_0 = seq_data('ctx_0_data')
    ctx_p1 = seq_data('ctx_p1_data')
    ctx_p2 = seq_data('ctx_p2_data')
    predicate = seq_data('verb_data')
    mark = seq_data('mark_data')
    target = seq_data('target')

    feature_out = db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1,
                          ctx_p2, mark, word_dict_len, label_dict_len,
                          pred_dict_len, word_dim=word_dim,
                          mark_dim=mark_dim, hidden_dim=hidden_dim,
                          depth=depth)
    crf_cost = fluid.layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name='crfw', learning_rate=mix_hidden_lr))
    avg_cost = fluid.layers.mean(crf_cost)

    crf_decode = fluid.layers.crf_decoding(
        input=feature_out, param_attr=fluid.ParamAttr(name='crfw'))

    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.conll05.train(), buf_size=1024),
        batch_size=batch_size)
    return avg_cost, crf_decode, train_reader, list(FEED_ORDER)


def load_pretrained_embedding(scope=None, embedding_name='emb'):
    """Install the conll05 pretrained word embedding into the frozen
    `emb` table AFTER the startup program ran (the reference book's
    load_parameter(embedding_param) step — the table is trainable=False,
    so without this it would stay at random init forever). Columns are
    sliced/tiled if the model was built with word_dim != the pretrained
    width."""
    import numpy as np
    import jax.numpy as jnp
    from ..fluid.executor import global_scope
    scope = scope or global_scope()
    if embedding_name not in scope.vars or scope.vars[embedding_name] is None:
        raise ValueError('run the startup program before loading the '
                         'pretrained embedding')
    cur = np.asarray(scope.vars[embedding_name])
    # get_embedding returns a PATH (reference API: a downloaded binary,
    # 16-byte header + raw float32 [vocab, 32] — book load_parameter)
    with open(paddle.dataset.conll05.get_embedding(), 'rb') as f:
        f.read(16)
        emb = np.fromfile(f, dtype=np.float32).reshape(-1, 32)
    if emb.shape[1] < cur.shape[1]:
        reps = -(-cur.shape[1] // emb.shape[1])
        emb = np.tile(emb, (1, reps))
    emb = emb[:cur.shape[0], :cur.shape[1]].astype(cur.dtype)
    scope.vars[embedding_name] = jnp.asarray(emb)
    return emb.shape
