"""MNIST LeNet benchmark model.

Parity: reference benchmark/fluid/models/mnist.py (cnn_model:37,
get_model:68).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['cnn_model', 'get_model']


def cnn_model(data):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    SIZE = 10
    input_shape = conv_pool_2.shape
    param_shape = [int(np.prod(input_shape[1:]))] + [SIZE]
    scale = (2.0 / (param_shape[0] ** 2 * SIZE)) ** 0.5
    predict = fluid.layers.fc(
        input=conv_pool_2, size=SIZE, act="softmax",
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.NormalInitializer(
                loc=0.0, scale=scale)))
    return predict


def get_model(batch_size=128, learning_rate=0.001):
    images = fluid.layers.data(name='pixel', shape=[1, 28, 28],
                               dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = cnn_model(images)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    inference_program = fluid.default_main_program().clone(for_test=True)
    opt = fluid.optimizer.AdamOptimizer(learning_rate=learning_rate,
                                        beta1=0.9, beta2=0.999)
    opt.minimize(avg_cost)

    train_reader = paddle.batch(paddle.dataset.mnist.train(),
                                batch_size=batch_size)
    test_reader = paddle.batch(paddle.dataset.mnist.test(),
                               batch_size=batch_size)
    return avg_cost, inference_program, train_reader, test_reader, batch_acc
