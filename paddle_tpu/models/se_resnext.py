"""SE-ResNeXt-50 (squeeze-and-excitation ResNeXt).

Parity: reference python/paddle/fluid/tests/unittests/test_parallel_executor.py
builds SE-ResNeXt as its heavyweight ParallelExecutor workload; same topology
here (cardinality-32 bottlenecks + SE blocks).
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['SE_ResNeXt', 'get_model']


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None):
    conv = fluid.layers.conv2d(input=input, num_filters=num_filters,
                               filter_size=filter_size, stride=stride,
                               padding=(filter_size - 1) // 2, groups=groups,
                               act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = fluid.layers.pool2d(input=input, pool_type='avg',
                               global_pooling=True)
    squeeze = fluid.layers.fc(input=pool,
                              size=num_channels // reduction_ratio,
                              act='relu')
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act='sigmoid')
    excitation = fluid.layers.reshape(excitation,
                                      shape=[-1, num_channels, 1, 1])
    return fluid.layers.elementwise_mul(x=input, y=excitation, axis=0)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        filter_size = 1
        return conv_bn_layer(input, ch_out, filter_size, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio):
    conv0 = conv_bn_layer(input, num_filters, 1, act='relu')
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act='relu')
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride)
    return fluid.layers.elementwise_add(x=short, y=scale, act='relu')


def SE_ResNeXt(input, class_dim, depth=50, cardinality=32,
               reduction_ratio=16):
    cfg = {50: [3, 4, 6, 3], 152: [3, 8, 36, 3]}
    blocks = cfg[depth]
    num_filters = [128, 256, 512, 1024]
    conv = conv_bn_layer(input, num_filters=64, filter_size=7, stride=2,
                         act='relu')
    conv = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type='max')
    for block in range(len(blocks)):
        for i in range(blocks[block]):
            conv = bottleneck_block(conv, num_filters[block],
                                    2 if i == 0 and block != 0 else 1,
                                    cardinality, reduction_ratio)
    pool = fluid.layers.pool2d(input=conv, pool_type='avg',
                               global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.2)
    return fluid.layers.fc(input=drop, size=class_dim, act='softmax')


def get_model(batch_size=16, class_dim=102, learning_rate=0.01):
    img = fluid.layers.data(name='data', shape=[3, 224, 224],
                            dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    out = SE_ResNeXt(img, class_dim)
    avg_cost = fluid.layers.mean(
        fluid.layers.cross_entropy(input=out, label=label))
    acc = fluid.layers.accuracy(input=out, label=label)
    fluid.optimizer.Momentum(learning_rate=learning_rate,
                             momentum=0.9).minimize(avg_cost)
    train_reader = paddle.batch(paddle.dataset.flowers.train(),
                                batch_size=batch_size)
    test_reader = paddle.batch(paddle.dataset.flowers.test(),
                               batch_size=batch_size)
    return avg_cost, acc, train_reader, test_reader, ['data', 'label']
