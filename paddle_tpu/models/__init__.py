"""Benchmark + book models.

Parity: reference benchmark/fluid/models/__init__.py model registry plus
the book-chapter models (fluid/tests/book/).
"""
__all__ = ['model_list', 'get_model_module']

model_list = ['fit_a_line', 'mnist', 'vgg', 'resnet',
              'stacked_dynamic_lstm', 'machine_translation', 'transformer',
              'deepfm', 'word2vec', 'se_resnext', 'understand_sentiment',
              'label_semantic_roles', 'recommender_system']


def get_model_module(name):
    import importlib
    if name not in model_list:
        raise ValueError("unknown model %r (choose from %s)" %
                         (name, model_list))
    return importlib.import_module('paddle_tpu.models.' + name)
