"""Sentiment classification conv net (Fluid book ch06).

Parity: reference python/paddle/fluid/tests/book/test_understand_sentiment.py
(convolution_net).
"""
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.dataset import imdb

__all__ = ['convolution_net', 'get_model']


def convolution_net(data, label, input_dim, class_dim=2, emb_dim=32,
                    hid_dim=32):
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])
    conv_3 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=3, act="tanh",
                                           pool_type="sqrt")
    conv_4 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=4, act="tanh",
                                           pool_type="sqrt")
    prediction = fluid.layers.fc(input=[conv_3, conv_4], size=class_dim,
                                 act="softmax")
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    accuracy = fluid.layers.accuracy(input=prediction, label=label)
    return avg_cost, accuracy, prediction


def get_model(batch_size=32, learning_rate=0.002):
    word_dict = imdb.word_dict()
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, accuracy, prediction = convolution_net(data, label,
                                                     len(word_dict))
    inference_program = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.Adagrad(learning_rate=learning_rate).minimize(avg_cost)
    train_reader = paddle.batch(
        paddle.reader.shuffle(imdb.train(word_dict), buf_size=1000),
        batch_size=batch_size)
    test_reader = paddle.batch(imdb.test(word_dict), batch_size=batch_size)
    return (avg_cost, accuracy, train_reader, test_reader,
            ['words', 'label'])
