"""ResNet for cifar10/imagenet — the flagship image benchmark.

Parity: reference benchmark/fluid/models/resnet.py (conv_bn_layer:33,
shortcut:45, basicblock:53, bottleneck:60, resnet_imagenet:75,
resnet_cifar10:102). Built with the same layer calls; on TPU the whole
train step compiles to one XLA module with convs on the MXU.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid

__all__ = ['resnet_cifar10', 'resnet_imagenet', 'get_model']


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu',
                  data_format='NCHW'):
    conv1 = fluid.layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False,
        data_format=data_format)
    return fluid.layers.batch_norm(input=conv1, act=act,
                                   data_layout=data_format)


def shortcut(input, ch_out, stride, data_format='NCHW'):
    ch_in = input.shape[-1 if data_format == 'NHWC' else 1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             data_format=data_format)
    return input


def basicblock(input, ch_out, stride, data_format='NCHW'):
    short = shortcut(input, ch_out, stride, data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None,
                          data_format=data_format)
    return fluid.layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_out, stride, data_format='NCHW'):
    short = shortcut(input, ch_out * 4, stride, data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          data_format=data_format)
    return fluid.layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_out, count, stride, data_format='NCHW'):
    res_out = block_func(input, ch_out, stride, data_format)
    for i in range(1, count):
        res_out = block_func(res_out, ch_out, 1, data_format)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, data_format='NCHW'):
    """data_format='NHWC' runs the whole tower channels-last (the native
    XLA:TPU layout; feed [N, H, W, 3]) with layout-portable OIHW weights."""
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, data_format=data_format)
    pool1 = fluid.layers.pool2d(input=conv1, pool_type='avg', pool_size=3,
                                pool_stride=2, data_format=data_format)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, data_format)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, data_format)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, data_format)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, data_format)
    pool2 = fluid.layers.pool2d(input=res4, pool_size=7, pool_type='avg',
                                pool_stride=1, global_pooling=True,
                                data_format=data_format)
    out = fluid.layers.fc(input=pool2, size=class_dim, act='softmax')
    return out


def resnet_cifar10(input, class_dim, depth=32):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1)
    res1 = layer_warp(basicblock, conv1, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 64, n, 2)
    pool = fluid.layers.pool2d(input=res3, pool_size=8, pool_type='avg',
                               pool_stride=1)
    out = fluid.layers.fc(input=pool, size=class_dim, act='softmax')
    return out


def get_model(data_set='cifar10', depth=None, batch_size=32,
              learning_rate=0.01, use_bf16=False):
    """Build the train graph + readers (reference resnet.py:get_model).
    Returns (avg_cost, accuracy, train_reader, test_reader)."""
    if data_set == "cifar10":
        class_dim = 10
        dshape = [3, 32, 32]
        model = resnet_cifar10
        depth = depth or 32
        train_reader = paddle.dataset.cifar.train10()
        test_reader = paddle.dataset.cifar.test10()
    else:
        class_dim = 102 if data_set == 'flowers' else 1000
        dshape = [3, 224, 224]
        model = resnet_imagenet
        depth = depth or 50
        train_reader = paddle.dataset.flowers.train()
        test_reader = paddle.dataset.flowers.test()

    input = fluid.layers.data(name='data', shape=dshape, dtype='float32')
    label = fluid.layers.data(name='label', shape=[1], dtype='int64')
    predict = model(input, class_dim, depth=depth)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    batch_acc = fluid.layers.accuracy(input=predict, label=label)

    optimizer = fluid.optimizer.Momentum(learning_rate=learning_rate,
                                         momentum=0.9)
    optimizer.minimize(avg_cost)

    batched_train = paddle.batch(train_reader, batch_size=batch_size)
    batched_test = paddle.batch(test_reader, batch_size=batch_size)
    return avg_cost, batch_acc, batched_train, batched_test
