"""Host-side utilities: native runtime bindings (utils.native) and
mesh-sharded checkpointing (utils.checkpoint)."""
from . import checkpoint  # noqa: F401
