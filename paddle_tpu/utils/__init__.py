"""Host-side utilities: native runtime bindings (utils.native),
mesh-sharded checkpointing (utils.checkpoint), retry/backoff primitives
(utils.retry), and the deterministic fault-injection harness
(utils.faults)."""
from . import checkpoint  # noqa: F401
from . import faults  # noqa: F401
from . import retry  # noqa: F401
