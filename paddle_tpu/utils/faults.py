"""Deterministic fault injection for the robustness test suite.

Every fault-tolerance behavior in the runtime — NaN-step skip (executor
anomaly guard), checkpoint CRC fallback, reader retry-then-degrade,
preemption-safe Trainer shutdown — is TESTED through this harness rather
than asserted in prose. All randomness flows from one seeded RandomState,
so a failing fault drill reproduces bit-for-bit from its seed.

The injectors deliberately operate at the host boundary (file bytes,
Python callables, OS signals, feed batches): the compiled XLA step stays
byte-identical with and without the harness, so the tests exercise the
SAME code paths production hits.
"""
import os
import signal
import socket
import threading
import time

import numpy as np

__all__ = ['ChaosProxy', 'FaultInjector', 'send_preemption']


def send_preemption(sig=signal.SIGTERM, pid=None):
    """Deliver a preemption signal to this process (default SIGTERM — what
    a TPU-VM maintenance event or k8s eviction sends). The Trainer's
    preemption handler finishes the in-flight step, flushes an emergency
    checkpoint, and returns from train() cleanly."""
    os.kill(os.getpid() if pid is None else pid, sig)


class FaultInjector(object):
    """Seeded source of faults. One instance per test; every choice
    (which byte to flip, which call to fail, where to poison) derives from
    `seed`, so drills are reproducible."""

    def __init__(self, seed=0):
        self.seed = int(seed)
        self.rng = np.random.RandomState(self.seed)

    # -- callable faults ---------------------------------------------------

    def flaky(self, fn, fail_times=1, exc_factory=None):
        """Wrap fn to raise on its first `fail_times` calls, then succeed.
        Models transient I/O: the retry layer should absorb exactly
        `fail_times` failures."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected transient failure #%d'
                                            % (i + 1))
        state = {'calls': 0}

        def wrapper(*args, **kwargs):
            i = state['calls']
            state['calls'] += 1
            if i < fail_times:
                raise exc_factory(i)
            return fn(*args, **kwargs)

        wrapper.calls = lambda: state['calls']
        return wrapper

    def flaky_reader(self, reader, fail_at, fail_times=1, exc_factory=None):
        """Decorate a paddle-style reader creator: each of the first
        `fail_times` iterations raises just before yielding sample index
        `fail_at`. With paddle_tpu.reader.fault_tolerant around it, the
        stream should heal without duplicating or dropping samples (until
        retries are exhausted, when it degrades to skip-with-warning)."""
        if exc_factory is None:
            exc_factory = lambda i: IOError('injected reader failure #%d'
                                            % (i + 1))
        state = {'iters': 0}

        def creator():
            it = state['iters']
            state['iters'] += 1
            def gen():
                for i, sample in enumerate(reader()):
                    if it < fail_times and i == fail_at:
                        raise exc_factory(it)
                    yield sample
            return gen()

        return creator

    # -- numeric faults ----------------------------------------------------

    def poison_nan(self, batch, rate=1.0):
        """Return a copy of a feed batch (ndarray, or nested list/tuple/
        dict of ndarrays) with a seeded fraction of float entries replaced
        by NaN — the canonical way to force an unhealthy training step
        through the REAL compiled path (the NaN propagates into loss and
        gradients; the anomaly guard must skip the step)."""
        if isinstance(batch, dict):
            return {k: self.poison_nan(v, rate) for k, v in batch.items()}
        if isinstance(batch, (list, tuple)):
            return type(batch)(self.poison_nan(v, rate) for v in batch)
        arr = np.array(batch, copy=True)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        mask = self.rng.rand(*arr.shape) < rate if arr.shape else \
            np.asarray(self.rng.rand() < rate)
        flat = arr.reshape(-1)
        flat[np.asarray(mask).reshape(-1)] = np.nan
        return flat.reshape(arr.shape)

    # -- file faults -------------------------------------------------------

    def truncate_file(self, path, keep_fraction=None, keep_bytes=None):
        """Truncate a file in place (a torn write / crashed writer). By
        default keeps a seeded fraction in [0.25, 0.75) of the bytes."""
        size = os.path.getsize(path)
        if keep_bytes is None:
            frac = (0.25 + 0.5 * self.rng.rand()) if keep_fraction is None \
                else keep_fraction
            keep_bytes = int(size * frac)
        keep_bytes = max(0, min(size - 1, keep_bytes))
        with open(path, 'r+b') as f:
            f.truncate(keep_bytes)
        return keep_bytes

    def corrupt_file(self, path, n_bytes=4):
        """Flip `n_bytes` seeded bytes in place WITHOUT changing the file
        size — the case only a content checksum (manifest CRC32) catches;
        a size check alone passes."""
        size = os.path.getsize(path)
        offsets = self.rng.randint(0, size, size=n_bytes)
        with open(path, 'r+b') as f:
            for off in offsets:
                f.seek(int(off))
                b = f.read(1)
                f.seek(int(off))
                f.write(bytes([b[0] ^ 0xFF]))
        return sorted(int(o) for o in offsets)

    def pick_file(self, directory, suffix='.npy'):
        """Seeded choice of one file (sorted listing, so the same seed
        picks the same shard on every run)."""
        names = sorted(n for n in os.listdir(directory)
                       if n.endswith(suffix))
        if not names:
            raise ValueError('no %r files under %r' % (suffix, directory))
        return os.path.join(directory, names[self.rng.randint(len(names))])

    # -- checkpoint faults -------------------------------------------------

    def torn_checkpoint(self, ckpt_dir, what=None):
        """Tear a sharded checkpoint dir the way a crash mid-save (or
        bit rot after it) would, for the elastic drills:

          'drop_manifest'     — delete manifest.json (+ its .sum): the
                                serial can never verify;
          'truncate_manifest' — cut the manifest short (a torn write the
                                .sum sidecar exposes as a typed failure);
          'corrupt_manifest'  — same-size bit rot in the manifest (only
                                the sidecar CRC catches it);
          'drop_shard'        — delete one seeded shard file;
          'truncate_shard'    — truncate one seeded shard file.

        Default: a seeded choice among all five. Returns (what, path)."""
        modes = ('drop_manifest', 'truncate_manifest', 'corrupt_manifest',
                 'drop_shard', 'truncate_shard')
        if what is None:
            what = modes[self.rng.randint(len(modes))]
        if what not in modes:
            raise ValueError('unknown torn_checkpoint mode %r (one of %s)'
                             % (what, modes))
        if what.endswith('_manifest'):
            path = os.path.join(ckpt_dir, 'manifest.json')
            if what == 'drop_manifest':
                os.remove(path)
                for side in (path + '.sum',):
                    if os.path.exists(side):
                        os.remove(side)
            elif what == 'truncate_manifest':
                self.truncate_file(path)
            else:
                self.corrupt_file(path)
            return what, path
        path = self.pick_file(ckpt_dir, suffix='.npy')
        if what == 'drop_shard':
            os.remove(path)
        else:
            self.truncate_file(path)
        return what, path

    # -- network faults ----------------------------------------------------

    def chaos_proxy(self, target):
        """Stand a `ChaosProxy` between a client and `target` ('host',
        port): traffic forwards transparently until the test calls
        sever()/delay()/garble(). Byte choices for garbling come from
        this injector's seeded RNG, so a corrupted-frame drill
        reproduces bit-for-bit. Point the client at `proxy.addr`."""
        return ChaosProxy(target, rng=self.rng)

    # -- process faults ----------------------------------------------------

    def preempt(self, sig=signal.SIGTERM):
        """Simulated preemption of THIS process (see send_preemption)."""
        send_preemption(sig)

    def kill_process(self, proc, sig=signal.SIGKILL):
        """SIGKILL a child process mid-step — the host-failure fault: no
        handlers run, no flush happens, beats stop. `proc` is a
        subprocess.Popen (or anything with .pid) or a raw pid. Returns
        the pid killed."""
        pid = int(getattr(proc, 'pid', proc))
        if pid == os.getpid():
            raise ValueError(
                'kill_process targets a CHILD (SIGKILL to self would '
                'take the test runner down); use preempt() for '
                'self-delivered signals')
        os.kill(pid, sig)
        return pid


class ChaosProxy(object):
    """A TCP forwarding proxy that misbehaves ON COMMAND — the network-
    fault primitive for the RPC pod-wire drills (serving/transport.py).
    Listens on an ephemeral local port; each accepted client connection
    is paired with a fresh connection to the real target and pumped in
    both directions until a fault is injected:

      sever()       close every live pairing mid-stream (the client
                    sees a reset/EOF; its Channel must reconnect — a
                    NEW pairing through the proxy works again);
      delay(s)      sleep `s` seconds before forwarding each chunk
                    (latency, not loss — nothing may time out wrongly);
      garble(n=8)   corrupt `n` seeded bytes of the NEXT forwarded
                    chunk (a torn/garbled frame: the reader must fail
                    typed, never hang); direction= picks which half of
                    the wire rots — 'up' (client->server), 'down'
                    (server->client), or 'both'.

    Faults are one-shot where that is the honest physics (garble) and
    latching where it is (delay persists until delay(0)). The proxy is
    deliberately L4-dumb: it never parses frames, so it cannot
    accidentally re-align a corrupted stream."""

    def __init__(self, target, rng=None):
        self.target = (str(target[0]), int(target[1]))
        self._rng = rng if rng is not None else np.random.RandomState(0)
        self._delay_s = 0.0
        self._garble = {'up': 0, 'down': 0}
        self._lock = threading.Lock()
        self._closed = False
        self._pairs = []          # [(client_sock, upstream_sock), ...]
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(('127.0.0.1', 0))
        self._sock.listen(16)
        self.addr = self._sock.getsockname()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name='chaos-proxy', daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._closed:
            try:
                client, _ = self._sock.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(self.target,
                                                    timeout=2.0)
                upstream.settimeout(None)
            except OSError:
                client.close()
                continue
            with self._lock:
                if self._closed:
                    client.close()
                    upstream.close()
                    return
                self._pairs.append((client, upstream))
            for src, dst, way in ((client, upstream, 'up'),
                                  (upstream, client, 'down')):
                t = threading.Thread(target=self._pump,
                                     args=(src, dst, way),
                                     name='chaos-pump', daemon=True)
                t.start()

    def _pump(self, src, dst, way):
        while True:
            try:
                chunk = src.recv(65536)
            except OSError:
                break
            if not chunk:
                break
            d = self._delay_s
            if d > 0:
                time.sleep(d)
            with self._lock:
                n = self._garble[way]
                if n and chunk:
                    buf = bytearray(chunk)
                    offs = self._rng.randint(0, len(buf),
                                             size=min(n, len(buf)))
                    for off in offs:
                        buf[int(off)] ^= 0xFF
                    chunk = bytes(buf)
                    self._garble[way] = 0
            try:
                dst.sendall(chunk)
            except OSError:
                break
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def sever(self):
        """Cut every live pairing NOW (mid-stream, not at a frame
        boundary). New connections still pair up — this is a network
        blip, not a dead host."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
        for client, upstream in pairs:
            for s in (client, upstream):
                try:
                    s.close()
                except OSError:
                    pass

    def delay(self, seconds):
        """Latch `seconds` of added one-way latency per forwarded
        chunk; delay(0) restores normal forwarding."""
        self._delay_s = float(seconds)

    def garble(self, n_bytes=8, direction='both'):
        """Corrupt `n_bytes` seeded bytes of the next forwarded chunk —
        the in-flight-frame bit-rot case only the frame codec's typed
        failure catches. `direction` aims the rot: 'up' hits the next
        client->server chunk (the server's reader fails typed and drops
        the connection), 'down' the next server->client chunk (the
        client Channel surfaces a typed TransportError), 'both' arms
        each half once."""
        if direction not in ('up', 'down', 'both'):
            raise ValueError("direction must be 'up', 'down' or 'both'")
        with self._lock:
            if direction in ('up', 'both'):
                self._garble['up'] = int(n_bytes)
            if direction in ('down', 'both'):
                self._garble['down'] = int(n_bytes)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        self.sever()
